"""Hybrid data x pipeline parallel training of the transformer LM.

Beyond the reference's parity scope (it is DP-only, SURVEY.md §5.7); this
demonstrates tpu_dist's pipeline axis
(`parallel/pipeline_parallel.py`): add a ``'pipe'`` axis to the mesh,
ask the model builder for ``pipeline_stages``, and the SAME
``compile``/``fit`` program GPipe-pipelines the transformer blocks —
each device holds ONE stage's weights (model memory scales 1/S), a
batch is split into microbatches, and every schedule tick hands
activations to the next stage with a single ring ``ppermute`` inside
the compiled step. The backward pipeline is derived by ``jax.grad``
through the scan; no NCCL/MPI send-recv choreography exists anywhere.

What to look at after fit():
* ``params['pipelinedblocks']['stages']`` leaves are [S, ...]-stacked
  and 1/S-sharded over 'pipe' (``.sharding.spec``,
  ``.addressable_shards``);
* losses are numerically identical to the same model on a pipe-less
  mesh, where the stages run as a sequential scan
  (tests/test_pipeline_parallel.py pins this) — placement, not math;
* checkpoints restore onto pipe-less topologies and back.

Run single-host (8 virtual devices), from the repo root:
    PYTHONPATH=. JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_parallel_lm.py
Multi-host: same per-worker TF_CONFIG launch as
examples/tpu_dist_example.py — the pipe axis may span hosts (stage
handoffs then ride DCN; tests prove the 2-process case).
"""

import numpy as np

import tpu_dist as td
from tpu_dist.models.transformer import build_transformer_lm

VOCAB, SEQ = 512, 64
STAGES, MICROBATCHES = 4, 4

strategy = td.MirroredStrategy(axis_shapes={"data": 2, "pipe": STAGES})
print(f"mesh: {dict(strategy.mesh.shape)} "
      f"({strategy.num_replicas_in_sync} data replicas x {STAGES} stages)")

# Deterministic synthetic next-token stream.
stream = (np.arange(20_000) * 2654435761) % VOCAB
xs = np.stack([stream[i:i + SEQ] for i in range(0, 16_000, 40)])
ys = np.stack([stream[i + 1:i + SEQ + 1] for i in range(0, 16_000, 40)])
ds = (td.data.Dataset.from_tensor_slices(
    (xs.astype(np.int64), ys.astype(np.int64))).batch(32).repeat())

with strategy.scope():
    model = build_transformer_lm(
        VOCAB, SEQ, d_model=128, depth=8, num_heads=8,
        pipeline_stages=STAGES, pipeline_microbatches=MICROBATCHES)
    model.compile(
        loss=td.ops.SparseCategoricalCrossentropy(from_logits=True),
        optimizer=td.ops.Adam(1e-3), metrics=["accuracy"])
    model.fit(ds, epochs=3, steps_per_epoch=20)

import jax  # noqa: E402

stages = model.variables["params"]["pipelinedblocks"]["stages"]
leaf = jax.tree_util.tree_leaves(stages)[0]
print(f"stage stack leaf {leaf.shape}: spec={leaf.sharding.spec}, "
      f"local stage shard={leaf.addressable_shards[0].data.shape}")

# -- 1F1B: the memory-bounded schedule (pipeline_1f1b.py) --------------------
# fit() above runs GPipe (jax.grad through the forward scan: all M
# microbatch activations alive at the backward's start). The 1F1B step
# interleaves each microbatch's backward as soon as it clears the last
# stage — O(STAGES) activation memory, no bubble FLOPs — as a custom
# training loop on the same mesh, the same params, the same checkpoint.
from tpu_dist.parallel import make_1f1b_train_step  # noqa: E402

loss = td.ops.SparseCategoricalCrossentropy(from_logits=True)
step = make_1f1b_train_step(model, loss, strategy=strategy)
opt = td.ops.SGD(0.01)
params = model.variables["params"]
opt_state = opt.init(params)
it = iter(ds)
for i in range(20):
    xb, yb = next(it)
    loss_v, grads = step(params, np.asarray(xb), np.asarray(yb))
    params, opt_state = opt.update(grads, opt_state, params)
    if i % 5 == 0:
        print(f"1F1B step {i}: loss {float(loss_v):.4f}")
print("1F1B custom loop done — same stage sharding, O(S) activation memory")
