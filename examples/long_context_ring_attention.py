"""Long-context training with ring attention (sequence parallelism).

Beyond the reference's parity scope (it is DP-only, SURVEY.md §5.7); this
demonstrates tpu_dist's long-context axis: a context too large to attend on
one device is sharded along the sequence over a `seq` mesh axis, and
`ring_attention` computes EXACT attention by rotating K/V shards around the
ring (`ppermute` neighbor exchange on the ICI torus) while a flash-style
online softmax merges the blocks. No device ever holds the [L, L] score
matrix or the full K/V. Composes with data parallelism on the same mesh.

Run (8 virtual devices): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/long_context_ring_attention.py
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.parallel import make_mesh, ring_attention, sequence_sharding

B, H, L, D = 2, 4, 4096, 64  # 4k context, sharded 4-way below

mesh = make_mesh({"data": 2, "seq": len(jax.devices()) // 2})
print(f"mesh: {dict(mesh.shape)}  per-device context: "
      f"{L // mesh.shape['seq']} of {L} tokens")

rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32))
           for _ in range(3))

# Keep activations sequence-sharded end to end: each device holds L/P tokens.
sharding = sequence_sharding(mesh, batch_axis="data")
q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))

attend = jax.jit(lambda q, k, v: ring_attention(
    q, k, v, mesh=mesh, axis_name="seq", causal=True, batch_axis="data"))
out = attend(q, k, v)
out.block_until_ready()
assert out.sharding.is_equivalent_to(sharding, out.ndim)
print(f"ring attention over {L} tokens: output {out.shape}, "
      f"still sequence-sharded ({len(out.sharding.device_set)} devices)")

# Exactness spot check against dense attention on a small slice budget.
Ls = 256
qs, ks, vs = (np.asarray(x[:, :, :Ls]) for x in (q, k, v))
s = jnp.einsum("bhqd,bhkd->bhqk", qs, ks) / math.sqrt(D)
mask = np.tril(np.ones((Ls, Ls), bool))
dense = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(jnp.where(mask, s, -jnp.inf), -1), vs)
err = float(jnp.max(jnp.abs(np.asarray(out[:, :, :Ls]) - dense)))
print(f"max |ring - dense| over the first {Ls} tokens: {err:.2e}")
assert err < 3e-5
