"""Line-for-line port of the reference's tf_dist_example.py onto tpu_dist.

The reference script (reference: tf_dist_example.py:1-59) demonstrates
2-worker synchronous data-parallel MNIST training with
MultiWorkerMirroredStrategy. This is the same program on the TPU-native stack:
same TF_CONFIG shape, same strategy/scope/compile/fit surface, same dataset
pipeline and shard-policy semantics, same model and hyperparameters.

Run one process per worker with per-worker TF_CONFIG (launch recipe at
reference README.md:156-162), or run it with no TF_CONFIG for single-host
training (the one-worker degradation rule, reference README.md:34). All
README.md:N citations in this file point at the reference repo's README,
matching the convention used throughout tpu_dist docstrings:

    # worker 0 (also the chief)
    TF_CONFIG='{"cluster":{"worker":["10.0.0.1:12345","10.0.0.2:12345"]},
                "task":{"type":"worker","index":0}}' python tpu_dist_example.py
    # worker 1
    TF_CONFIG='{"cluster":{"worker":["10.0.0.1:12345","10.0.0.2:12345"]},
                "task":{"type":"worker","index":1}}' python tpu_dist_example.py
"""

import json
import os

import jax.numpy as jnp

import tpu_dist as td

# -- Cluster config (reference tf_dist_example.py:6-10) ----------------------
# The reference hard-codes a 2-worker cluster in-process; here we keep
# whatever TF_CONFIG the launcher exported, and show the in-process
# alternative commented out:
#
# os.environ["TF_CONFIG"] = json.dumps({
#     "cluster": {"worker": ["172.16.16.5:12345", "172.16.16.6:12345"]},
#     "task": {"type": "worker", "index": 1},
# })

# -- Strategy (reference tf_dist_example.py:12-13) ---------------------------
strategy = td.MultiWorkerMirroredStrategy(
    td.CollectiveCommunication.AUTO)
# strategy = td.MirroredStrategy()   # single-host multi-device alternative

td.data.disable_progress_bar()            # reference tf_dist_example.py:15
BUFFER_SIZE = 10000                       # reference tf_dist_example.py:16-18
NUM_WORKERS = max(td.cluster.process_count(), 1)
GLOBAL_BATCH_SIZE = 64 * NUM_WORKERS


# -- Dataset (reference tf_dist_example.py:15-37) ----------------------------
def make_datasets_unbatched():
    def scale(image, label):
        image = jnp.asarray(image, jnp.float32) / 255.0
        return image, label

    datasets, info = td.data.load(with_info=True,
                                  name="mnist",
                                  as_supervised=True)

    return datasets["train"].map(scale).cache().shuffle(BUFFER_SIZE)


train_datasets = make_datasets_unbatched().batch(GLOBAL_BATCH_SIZE)
options = td.data.Options()
options.experimental_distribute.auto_shard_policy = td.AutoShardPolicy.OFF
train_datasets_no_auto_shard = train_datasets.with_options(options)


# -- Model (reference tf_dist_example.py:39-53) ------------------------------
def build_and_compile_cnn_model():
    return td.models.build_and_compile_cnn_model(learning_rate=0.001)


# -- Scoped build + fit (reference tf_dist_example.py:56-59) -----------------
with strategy.scope():
    multi_worker_model = build_and_compile_cnn_model()

multi_worker_model.fit(x=train_datasets_no_auto_shard, epochs=10,
                       steps_per_epoch=20)
