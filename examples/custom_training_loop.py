"""Custom training loop — the TPU-native pattern plus the TF compat surface.

The reference trains through Keras fit (tf_dist_example.py:59); TF users who
outgrow fit write custom loops against `strategy.run` (the API Keras itself
calls, keras:src/backend/tensorflow/trainer.py:134). On TPU the idiomatic
custom loop is even simpler — ONE jitted function over globally-sharded
arrays; XLA's partitioner inserts the gradient all-reduce — and
`strategy.run`/`strategy.reduce` remain available for per-replica
inspection (the run-then-reduce idiom). This example shows both:

* the train step: plain `jax.jit` over the sharded global batch, params
  replicated — the compiled-step path `fit` itself uses;
* per-replica diagnostics: `strategy.run` computing each replica's local
  loss on its own shard, reduced with `strategy.reduce`.

Run single-host:          python examples/custom_training_loop.py
Run per-worker TF_CONFIG: same launch recipe as examples/tpu_dist_example.py.
"""

import jax
import numpy as np

import tpu_dist as td
from tpu_dist.ops.losses import sparse_categorical_crossentropy

strategy = td.MultiWorkerMirroredStrategy()
GLOBAL_BATCH = 8 * strategy.num_replicas_in_sync

model = td.models.build_cnn_model()
variables = model.init(seed=0)
state = variables["state"]
params = strategy.replicate(variables["params"])
opt = td.ops.SGD(learning_rate=0.01)
opt_state = opt.init(params)


def dataset_fn(ctx):
    ds = td.data.load("mnist", split="train", synthetic_size=4096)
    ds = ds.map(lambda x, y: (np.asarray(x, np.float32) / 255.0, y))
    return ds.shuffle(1024, seed=ctx.input_pipeline_id).batch(
        ctx.get_per_replica_batch_size(GLOBAL_BATCH)).repeat()


@jax.jit
def train_step(params, opt_state, x, y):
    """Forward + loss + backward + update as ONE SPMD program: the mean over
    the sharded global batch makes XLA emit the cross-replica AllReduce for
    the gradients of the replicated params (SURVEY.md §5.8)."""
    def loss_fn(p):
        logits, _ = model.apply(p, state, x, training=True)
        return sparse_categorical_crossentropy(
            logits, y, from_logits=True).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_params, new_opt = opt.update(grads, opt_state, params)
    return loss, new_params, new_opt


def replica_loss(params, x, y):
    """Runs per replica under strategy.run: x/y arrive as THIS replica's
    shard, so the returned vector (one entry per replica) localizes a data
    problem to a worker — the PerReplica-inspection affordance."""
    logits, _ = model.apply(params, state, x, training=False)
    return sparse_categorical_crossentropy(logits, y, from_logits=True).mean()


dist = strategy.distribute_datasets_from_function(dataset_fn)
it = iter(dist)
for step in range(100):
    x, y = next(it)
    loss, params, opt_state = train_step(params, opt_state, x, y)
    if step % 20 == 0:
        per_replica = strategy.run(replica_loss, args=(params, x, y))
        mean_of_replicas = strategy.reduce("mean", per_replica)
        # Multi-worker note: per_replica is a GLOBAL array — only this
        # process's replica entries are addressable, so inspect local
        # shards (remote values would need a process_allgather).
        local = sorted(
            (s.index[0].start or 0, round(float(np.asarray(s.data)[0]), 3))
            for s in per_replica.addressable_shards)
        print(f"step {step:3d}  loss {float(loss):.4f}  local replicas "
              f"{dict(local)} (global mean {float(mean_of_replicas):.4f})")
print("done")
