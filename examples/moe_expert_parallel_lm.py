"""Expert-parallel Mixture-of-Experts LM on a data x expert mesh.

The fourth parallelism family (after DP, the seq ring, Megatron TP and
the pipe schedules): a Switch-transformer LM whose FFN experts shard
one-bundle-per-device over the ``expert`` mesh axis, with tokens
travelling to their experts and back through two ``all_to_all``
collectives inside the compiled step (parallel/expert.py). The router's
load-balance auxiliary loss joins the training objective automatically
(the trainer's add_loss analog).

Run on the 8-device virtual CPU mesh:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/moe_expert_parallel_lm.py
"""

import numpy as np

import tpu_dist as td
from tpu_dist.models.transformer import build_transformer_lm

VOCAB, SEQ = 512, 64
EXPERTS = 8

strategy = td.MirroredStrategy(axis_shapes={"data": 2, "expert": 4})
print(f"mesh: {dict(strategy.mesh.shape)} "
      f"({EXPERTS} experts, {EXPERTS // 4} per expert-axis device)")

stream = (np.arange(20_000) * 2654435761) % VOCAB
xs = np.stack([stream[i:i + SEQ] for i in range(0, 16_000, 40)])
ys = np.stack([stream[i + 1:i + SEQ + 1] for i in range(0, 16_000, 40)])
ds = (td.data.Dataset.from_tensor_slices(
    (xs.astype(np.int64), ys.astype(np.int64))).batch(32).repeat())

with strategy.scope():
    model = build_transformer_lm(
        VOCAB, SEQ, d_model=128, depth=4, num_heads=8, ff_dim=256,
        moe_experts=EXPERTS, moe_top_k=2, moe_groups=8)
    model.compile(
        loss=td.ops.SparseCategoricalCrossentropy(from_logits=True),
        optimizer=td.ops.Adam(1e-3), metrics=["accuracy"])
    model.fit(ds, epochs=3, steps_per_epoch=20)

import jax  # noqa: E402

flat = jax.tree_util.tree_flatten_with_path(model.variables["params"])[0]
w1 = [leaf for path, leaf in flat
      if getattr(path[-1], "key", None) == "w1"][0]
print(f"expert stack w1 {w1.shape}: spec={w1.sharding.spec}, "
      f"local bundle={w1.addressable_shards[0].data.shape}")
sflat = jax.tree_util.tree_flatten_with_path(model.variables["state"])[0]
aux = [float(leaf) for path, leaf in sflat
       if getattr(path[-1], "key", None) == "aux_loss"]
print(f"load-balance aux losses (in the objective): "
      f"{[round(a, 5) for a in aux]}")
