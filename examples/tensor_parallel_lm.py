"""Hybrid data x tensor parallel training of the transformer LM.

Beyond the reference's parity scope (it is DP-only, SURVEY.md §5.7); this
demonstrates tpu_dist's tensor-parallel axis (`parallel/tensor.py`): add a
``'model'`` axis to the mesh and the SAME ``compile``/``fit`` program
shards its attention and MLP parameters Megatron-style across it —
column-parallel QKV and MLP-up, row-parallel output projections — with
XLA's SPMD partitioner deriving the per-block all-reduces from the sharded
matmuls. No model or training-loop changes: the strategy's ``axis_shapes``
is the entire opt-in.

What to look at after fit():
* parameter leaves really are 1/M-sharded per device (`.sharding.spec`
  and `.addressable_shards`), as are Adam's moments;
* losses are numerically identical to the replicated data-parallel run
  (tests/test_tensor_parallel.py pins this) — sharding is placement, not
  math.

Run single-host (8 virtual devices), from the repo root:
    PYTHONPATH=. JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tensor_parallel_lm.py
Multi-host: same per-worker TF_CONFIG launch as examples/tpu_dist_example.py
(the mesh then spans hosts; 'model' stays intra-host for ICI-speed
all-reduces when axis_shapes is ordered data-outermost, as here).
"""

import numpy as np

import tpu_dist as td
from tpu_dist.models.transformer import build_transformer_lm

VOCAB, SEQ = 512, 128

# data(2) x model(4): batches shard 2 ways, every layer's heads/hidden
# shard 4 ways. axis_shapes must include 'data' (batches ride it).
strategy = td.MirroredStrategy(axis_shapes={"data": 2, "model": 4})

with strategy.scope():
    model = build_transformer_lm(VOCAB, SEQ, d_model=128, depth=2,
                                 num_heads=8)
    model.compile(
        loss=td.ops.SparseCategoricalCrossentropy(from_logits=True),
        optimizer=td.ops.Adam(1e-3),
        metrics=["accuracy"],
    )

# Synthetic next-token data (any td.data pipeline works here).
rng = np.random.default_rng(0)
stream = rng.integers(0, VOCAB, size=4096 + SEQ + 1).astype(np.int64)
xs = np.stack([stream[i:i + SEQ] for i in range(0, 4096, 32)])
ys = np.stack([stream[i + 1:i + SEQ + 1] for i in range(0, 4096, 32)])
ds = td.data.Dataset.from_tensor_slices((xs, ys)).batch(16).repeat()

model.fit(ds, epochs=2, steps_per_epoch=8, verbose=1)

wq = model.variables["params"]["block"]["residual"]["main"][
    "multiheadattention"]["wq"]
print(f"wq: global {wq.shape}, spec {wq.sharding.spec}, "
      f"per-device shard {wq.addressable_shards[0].data.shape}")
