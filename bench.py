"""Benchmark harness: the reference's headline workloads, TPU-native.

Workloads (BASELINE.md configs 1-5): the reference's MNIST 2-conv CNN
(tf_dist_example.py:39-53) plus ResNet-18/Fashion-MNIST and ResNet-50/CIFAR-10,
trained with the jitted SPMD step over a data-parallel mesh.

Default (driver) run measures, on the available hardware:
  * compiled-step throughput (fwd+loss+bwd+allreduce+update, input off the
    timed path) for mnist_cnn, resnet18, resnet50 — with analytic MFU from
    XLA's own cost model (compiled.cost_analysis) against the chip's peak;
  * end-to-end ``fit()`` throughput for mnist_cnn (host pipeline +
    native loader + prefetch + dispatch ON the timed path);
  * a like-for-like 2-device CPU baseline of the reference's own measured
    config — ``vs_baseline`` compares against the ACTUAL TensorFlow
    MultiWorkerMirroredStrategy reference program measured on this same host
    (benchmarks/tf_reference_bench.py, cached in
    benchmarks/tf_baseline_host.json), not TPU-vs-CPU; falls back to the
    survey's ~62 ms/step (SURVEY.md §3.5) where TF is unavailable.

and prints ONE JSON line on stdout:

    {"metric": "mnist_cnn_images_per_sec_per_core", "value": N,
     "unit": "images/sec/core", "vs_baseline": R, ...extras...}

Other modes:
    python bench.py [mnist_cnn|resnet18|resnet50|transformer_lm]
                    [--steps N] [--batch N] [--spe K] [--bf16] [--e2e]
                                             # one config, report to stderr
    python bench.py --scaling                # 1/2/4/8-device virtual CPU mesh
                                             # fixed-global-work partition-
                                             # overhead table
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

# Fallback baseline (images/sec/core) when TF can't be measured in-situ,
# SURVEY.md §3.5/§6: the reference example at ~62 ms/step, where each of the
# 2 loopback workers consumes its OWN batch of 128 per step (autoshard OFF,
# SURVEY.md §3.4) — so per worker/core the stream rate is 128/0.062, the
# same accounting tf_reference_bench.py uses for the measured number.
REFERENCE_CPU_IMG_PER_SEC_PER_CORE = 128 / 0.062

#: Peak FLOP/s per chip for MFU. TPU v5e (v5 lite): 197e12 bf16. Override
#: with $TPU_DIST_PEAK_FLOPS when running on other hardware.
PEAK_FLOPS_TPU = float(os.environ.get("TPU_DIST_PEAK_FLOPS", 197e12))

CONFIGS = {
    # name: (dataset, model builder name, input shape, default global batch)
    "mnist_cnn": ("mnist", "cnn", (28, 28, 1), 128),
    "resnet18": ("fashion_mnist", "resnet18", (28, 28, 1), 256),
    "resnet50": ("cifar10", "resnet50", (32, 32, 3), 256),
    # Long-context family: GPT-style causal LM, seq len 512, synthetic
    # tokens ("shape" = (seq_len,) of int ids, not pixels).
    "transformer_lm": ("synthetic_tokens", "transformer_lm", (512,), 64),
}

#: transformer_lm model hyperparameters (GPT-small-ish layer dims so the
#: attention/MLP matmuls are MXU-shaped).
TRANSFORMER_LM = dict(vocab_size=8192, d_model=512, depth=4, num_heads=8)


def build_model(kind: str, input_shape, num_classes: int = 10,
                steps_per_execution: int = 1):
    from tpu_dist.ops.losses import SparseCategoricalCrossentropy
    from tpu_dist.ops.metrics import SparseCategoricalAccuracy
    from tpu_dist.ops.optimizers import SGD

    if kind == "cnn":
        from tpu_dist.models.cnn import build_cnn_model

        model = build_cnn_model(num_classes=num_classes,
                                input_shape=input_shape)
    elif kind == "transformer_lm":
        from tpu_dist.models.transformer import build_transformer_lm

        model = build_transformer_lm(
            TRANSFORMER_LM["vocab_size"], input_shape[0],
            d_model=TRANSFORMER_LM["d_model"],
            depth=TRANSFORMER_LM["depth"],
            num_heads=TRANSFORMER_LM["num_heads"])
    else:
        from tpu_dist.models import resnet

        model = {"resnet18": resnet.ResNet18,
                 "resnet50": resnet.ResNet50}[kind](
            num_classes=num_classes, input_shape=input_shape)
    # Measured r3 (v5e, transformer_lm): the fused Pallas CE wins in
    # isolation (4.9 vs 6.3 ms fwd+bwd at [32k, 8k]) but LOSES inside the
    # full jitted train step (46.7 vs 42.5 ms/step) — the custom call is a
    # fusion barrier between the vocab-head matmul and the loss, blocking
    # XLA's own epilogue fusion. Keep the XLA-fused jnp loss here.
    model.compile(
        loss=SparseCategoricalCrossentropy(from_logits=True),
        optimizer=SGD(learning_rate=0.001),
        metrics=[SparseCategoricalAccuracy()],
        steps_per_execution=steps_per_execution,
    )
    return model


def load_batch(dataset_name: str, shape, global_batch: int):
    """One global batch from the named dataset (local files if present, else
    the deterministic synthetic fallback — tpu_dist.data.sources)."""
    from tpu_dist.data.sources import load_arrays

    if dataset_name == "synthetic_tokens":
        # Next-token LM batch: deterministic id stream, targets = inputs
        # shifted by one.
        ln = shape[0]
        vocab = TRANSFORMER_LM["vocab_size"]
        stream = (np.arange(global_batch * ln + 1) * 2654435761) % vocab
        x = stream[:-1].reshape(global_batch, ln).astype(np.int64)
        y = stream[1:].reshape(global_batch, ln).astype(np.int64)
        return x, y

    x_all, y_all = load_arrays(dataset_name, "train")
    reps = -(-global_batch // len(x_all))
    if reps > 1:
        x_all, y_all = np.tile(x_all, (reps, 1, 1, 1)), np.tile(y_all, reps)
    x = (x_all[:global_batch].reshape(global_batch, *shape)
         .astype(np.float32) / 255.0)
    y = y_all[:global_batch].astype(np.int64)
    return x, y


def _flops_per_step(model, strategy, shape, global_batch,
                    token_model: bool = False) -> float | None:
    """XLA's own FLOP estimate for ONE train step (fwd+bwd+update).

    Always measured on the single-step program: XLA's cost model counts a
    ``lax.scan`` body once regardless of trip count, so analyzing the
    steps_per_execution program would underreport by K.
    """
    import jax

    try:
        fn = model.make_train_function(steps_per_execution=1)
        state = model.train_state()
        if token_model:  # int ids in, per-position labels out
            x = np.zeros((global_batch, *shape), np.int64)
            y = np.zeros((global_batch, *shape), np.int64)
        else:
            x = np.zeros((global_batch, *shape), np.float32)
            y = np.zeros((global_batch,), np.int64)
        xb = strategy.distribute_batch(x)
        yb = strategy.distribute_batch(y)
        cost = fn.lower(*state, xb, yb,
                        jax.random.PRNGKey(0)).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def run_step_bench(config: str, steps: int, warmup: int,
                   global_batch: int | None, spe: int = 1,
                   repeats: int = 3, precision_policy: str | None = None,
                   seq_len: int | None = None) -> dict:
    """Compiled-step throughput: input delivery OFF the timed path — matching
    how the reference's steady-state step time was read (cached tf.data
    pipeline, SURVEY.md §3.4). Public API only: make_train_function /
    train_state (SURVEY.md D15). ``precision_policy="mixed_bfloat16"``
    enables the TPU-native mixed-precision recipe (bf16 activations on the
    MXU, fp32 params/statistics — models/policy.py)."""
    from tpu_dist.models.policy import policy as get_policy, set_policy

    dataset_name, kind, shape, default_batch = CONFIGS[config]
    if seq_len is not None:
        if kind != "transformer_lm":
            raise ValueError("--seq only applies to transformer_lm")
        shape = (seq_len,)
    global_batch = global_batch or default_batch
    prev_policy = get_policy()
    if precision_policy:
        set_policy(precision_policy)
    try:
        return _run_step_bench_body(
            config, dataset_name, kind, shape, global_batch, steps, warmup,
            spe, repeats)
    finally:
        set_policy(prev_policy)


def _run_step_bench_body(config, dataset_name, kind, shape, global_batch,
                         steps, warmup, spe, repeats):
    import jax

    from tpu_dist.models.policy import policy as get_policy
    from tpu_dist.parallel.strategy import MirroredStrategy
    from tpu_dist.training.trainer import jnp_stack_keys

    strategy = MirroredStrategy()
    n_dev = strategy.num_replicas_in_sync
    if global_batch % n_dev:
        global_batch += n_dev - global_batch % n_dev

    with strategy.scope():
        model = build_model(kind, shape, steps_per_execution=spe)

    train_fn = model.make_train_function()
    state = model.train_state()
    key = jax.random.PRNGKey(0)

    if spe > 1:
        steps = -(-steps // spe) * spe
        warmup = -(-warmup // spe) * spe
        x, y = load_batch(dataset_name, shape, global_batch * spe)
        xb = strategy.distribute_batch_stack(
            x.reshape(spe, global_batch, *x.shape[1:]))
        yb = strategy.distribute_batch_stack(
            y.reshape(spe, global_batch, *y.shape[1:]))
        keys = [jnp_stack_keys(key, i * spe, spe)
                for i in range((warmup + steps) // spe)]
        n_exec_warm, n_exec = warmup // spe, steps // spe
    else:
        x, y = load_batch(dataset_name, shape, global_batch)
        xb = strategy.distribute_batch(x)
        yb = strategy.distribute_batch(y)
        # Per-step keys precomputed off the timed path — fold_in is an eager
        # device op whose dispatch would otherwise pollute the dispatch-bound
        # step-time measurement.
        keys = [jax.random.fold_in(key, i) for i in range(warmup + steps)]
        n_exec_warm, n_exec = warmup, steps

    def one_exec(state, i):
        loss, p, s, o, m, acc, _health = train_fn(*state, xb, yb,
                                                  keys[i % len(keys)])
        return loss, (p, s, o, m, acc)

    # XLA:CPU in-process partition collectives run their rendezvous on the
    # host's shared intra-op pool; with free-running async dispatch a later
    # execution's thunks can be queued ahead of an earlier execution's
    # unfinished rendezvous and starve it (observed as the runtime's 40 s
    # termination abort on this 1-core host). Bounding in-flight work to
    # one execution keeps rendezvous pairs adjacent — and mirrors the TF
    # reference loop, which fetches the loss every step anyway. Applied to
    # EVERY CPU run (including n_dev=1) so scaling tables compare rows
    # measured the same way. TPU runs keep free-running dispatch (single
    # device, no partition rendezvous).
    platform = jax.devices()[0].platform
    sync_each_exec = platform == "cpu"

    loss = None
    for i in range(n_exec_warm):
        loss, state = one_exec(state, i)
        if sync_each_exec:
            jax.block_until_ready((loss, state))
    jax.block_until_ready((loss, state))

    # Repeated timing windows, best + median reported: the chip is shared
    # (tunnelled), so a single window is hostage to neighbor load.
    # Window-end sync is a LOSS FETCH, not block_until_ready: through the
    # axon tunnel block_until_ready has been observed returning before
    # device work completes (r4: a window once implied 343M tok/s, ~200x
    # the peak-bound maximum). device_get must materialize the bytes, so
    # it cannot lie; its round-trip cost is measured and subtracted.
    jax.device_get(loss)
    rtt_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loss)
        rtt_samples.append(time.perf_counter() - t0)
    # min of several samples: one tunnel hiccup in the correction would
    # systematically inflate every window's reported throughput.
    fetch_rtt = min(rtt_samples)
    windows = []
    i0 = n_exec_warm
    for _ in range(repeats):
        t0 = time.perf_counter()
        for i in range(i0, i0 + n_exec):
            loss, state = one_exec(state, i)
            if sync_each_exec:
                jax.block_until_ready((loss, state))
        jax.device_get(loss)
        windows.append(max(time.perf_counter() - t0 - fetch_rtt, 1e-6))
        i0 += n_exec
    elapsed = min(windows)
    median = sorted(windows)[len(windows) // 2]

    step_ms = elapsed / steps * 1e3
    img_per_sec = global_batch * steps / elapsed
    result = {
        "config": config,
        "mode": "step",
        "devices": n_dev,
        "platform": platform,
        "global_batch": global_batch,
        "steps": steps,
        "steps_per_execution": spe,
        "timing_windows": repeats,
        "step_ms": round(step_ms, 4),
        "step_ms_median": round(median / steps * 1e3, 4),
        "images_per_sec": round(img_per_sec, 1),
        "images_per_sec_per_core": round(img_per_sec / n_dev, 1),
        "final_loss": float(jax.device_get(loss)),
        "precision_policy": get_policy(),
    }
    if dataset_name == "synthetic_tokens":
        # "images" are sequences here; tokens/sec is the LM-native unit.
        result["tokens_per_sec_per_core"] = round(
            img_per_sec * shape[0] / n_dev, 1)
    flops_step = _flops_per_step(model, strategy, shape, global_batch,
                                 token_model=dataset_name == "synthetic_tokens")
    if flops_step is not None:
        if dataset_name == "synthetic_tokens":
            # The fused flash kernel is an XLA custom call, scored ZERO by
            # cost_analysis; when it ACTUALLY dispatches (mirror the
            # _default_attention decision — gating on use_flash alone
            # would double-count whenever the model falls back to dense,
            # whose matmuls cost_analysis does see), add the analytic
            # attention model-FLOPs (fwd + 2x bwd, causal half, recompute
            # NOT counted) or reported MFU decays with L purely as an
            # accounting artifact.
            from tpu_dist.models import transformer as tr_mod
            from tpu_dist.models.policy import compute_dtype
            from tpu_dist.ops import flash_attention as fa

            h = TRANSFORMER_LM["num_heads"]
            dk = TRANSFORMER_LM["d_model"] // h
            qshape = jax.ShapeDtypeStruct(
                (global_batch, h, shape[0], dk), compute_dtype())
            flash_dispatched = False
            if fa.use_flash(qshape):
                with strategy.scope():
                    flash_dispatched = (
                        tr_mod._mesh_mapped_flash(
                            qshape, causal=True, scale=1.0) is not None
                        or tr_mod._unwrapped_flash_safe())
            if flash_dispatched:
                correction = TRANSFORMER_LM["depth"] * fa.analytic_train_flops(
                    global_batch, h, shape[0], dk, causal=True)
                flops_step += correction
                result["flops_note"] = (
                    "attention runs in the Pallas flash kernel (opaque to "
                    "cost_analysis); its analytic model FLOPs "
                    f"(+{correction:.3g}/step) are added")
                result["mfu_convention"] = (
                    "model flops; causal attention counted at HALF (the "
                    "work the kernel performs)")
            else:
                result["mfu_convention"] = (
                    "cost_analysis executed flops; dense attention "
                    "computes (and is credited) the FULL L^2 — not "
                    "directly comparable to flash rows' half-credit")
        flops_per_sec = flops_step / (elapsed / steps)
        result["tflops_per_sec_per_core"] = round(
            flops_per_sec / n_dev / 1e12, 3)
        if platform == "tpu":
            result["mfu_pct"] = round(
                100.0 * flops_per_sec / n_dev / PEAK_FLOPS_TPU, 2)
            result["mfu_peak_flops_assumed"] = PEAK_FLOPS_TPU
    return result


def run_e2e_fit(config: str, epochs: int, steps_per_epoch: int,
                global_batch: int | None, spe: int = 16,
                pipeline: str = "device") -> dict:
    """End-to-end ``fit()`` throughput — input delivery + dispatch ON the
    timed path; what a user of the ported reference script gets.

    ``pipeline="device"``: DeviceDataset (one upload, on-device batch gather
    — the framework's intended path for HBM-sized datasets).
    ``pipeline="host"``: native C++ loader + prefetch + per-step transfer
    (the streaming path larger-than-HBM datasets use).
    """
    import jax

    from tpu_dist.data.device import device_pipeline
    from tpu_dist.data.native import native_pipeline
    from tpu_dist.parallel.strategy import MirroredStrategy

    dataset_name, kind, shape, default_batch = CONFIGS[config]
    global_batch = global_batch or default_batch

    strategy = MirroredStrategy()
    n_dev = strategy.num_replicas_in_sync
    if global_batch % n_dev:
        global_batch += n_dev - global_batch % n_dev

    with strategy.scope():
        model = build_model(kind, shape, steps_per_execution=spe)

    need = global_batch * (steps_per_epoch + 1)
    if pipeline == "device":
        ds = device_pipeline(dataset_name, global_batch_size=global_batch,
                             synthetic_size=max(8192, need))
    elif pipeline == "refchain":
        # The LITERAL reference pipeline shape (tf_dist_example.py:20-33)
        # through the public combinators — exercises the vectorize pass's
        # device-residency promotion (data/vectorize.py), i.e. what a user
        # porting the reference script actually gets from fit().
        import jax.numpy as jnp

        from tpu_dist.data.pipeline import Dataset
        from tpu_dist.data.sources import load_arrays

        images, labels = load_arrays(dataset_name, "train",
                                     synthetic_size=max(8192, need))

        def scale(image, label):
            return jnp.asarray(image, jnp.float32) / 255.0, label

        ds = (Dataset.from_tensor_slices((images, labels)).map(scale)
              .cache().shuffle(10000).batch(global_batch,
                                            drop_remainder=True))
    else:
        ds = native_pipeline(dataset_name, global_batch_size=global_batch,
                             synthetic_size=max(8192, need))
    # Warmup fit pays the compile; the timed fit measures the steady loop.
    model.fit(ds, epochs=1, steps_per_epoch=steps_per_epoch, verbose=0)
    t0 = time.perf_counter()
    model.fit(ds, epochs=epochs, steps_per_epoch=steps_per_epoch, verbose=0)
    elapsed = time.perf_counter() - t0

    total_steps = epochs * steps_per_epoch
    img_per_sec = global_batch * total_steps / elapsed
    result = {
        "config": config,
        "mode": f"e2e_fit_{pipeline}",
        "input_pipeline": pipeline,
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "global_batch": global_batch,
        "epochs": epochs,
        "steps_per_epoch": steps_per_epoch,
        "steps_per_execution": spe,
        "step_ms": round(elapsed / total_steps * 1e3, 4),
        "images_per_sec": round(img_per_sec, 1),
        "images_per_sec_per_core": round(img_per_sec / n_dev, 1),
    }
    if pipeline == "host":
        transform = getattr(ds, "_device_transform", None)
        result["transfer"] = "uint8" if transform is not None else "float32"
        result["h2d_floor_note"] = (
            "true streaming path: every image crosses the host->device "
            "link each step. r5 re-probe (benchmarks/h2d_probe.py -> "
            "h2d_probe_r5.json) resolves r4's self-contradiction (a "
            "'~18 MB/s => 23k img/s cap' note under a 41.3k row): the r4 "
            "probe measured SERIALIZED transfers (each payload "
            "acknowledged before the next, paying the tunnel latency per "
            "transfer), while this bench overlaps transfers (prefetch + "
            "async dispatch). Measured pipelined bandwidth spans "
            "~12-42 MB/s depending on ambient tunnel load and payload "
            "compressibility (sync/serialized reads 3-11 MB/s on the "
            "same link minutes apart); at uint8 MNIST's 784 B/img that "
            "is ~15-53k img/s/core, so a 41k row (~32 MB/s achieved) "
            "sits inside the pipelined envelope, and any single-sample "
            "'link rate' is a floor, not a ceiling. Real TPU hosts feed "
            "over PCIe (GB/s) where this path is compute-bound; "
            "HBM-resident sources take the promoted device path instead "
            "(see e2e_fit_refchain).")
    return result


# -- subprocess modes ---------------------------------------------------------


def _child_env(n_devices: int) -> dict:
    # Same flag surgery as the driver entrypoint's virtual-mesh re-exec —
    # one implementation, two child-spawn paths.
    from __graft_entry__ import _force_device_count_flags

    env = dict(os.environ)
    env["XLA_FLAGS"] = _force_device_count_flags(
        env.get("XLA_FLAGS", ""), n_devices)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # disarm the TPU sitecustomize
    return env


def _run_child(args: list[str], n_devices: int, timeout: float = 900,
               extra_env: dict | None = None):
    env = _child_env(n_devices)
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *args],
        env=env, capture_output=True, text=True,
        timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench child {args} failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"bench child {args} printed no JSON:\n"
                       f"{proc.stdout[-2000:]}")


TF_BASELINE_CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "benchmarks", "tf_baseline_host.json")


def measure_tf_reference(timeout: float = 1500) -> dict | None:
    """The reference stack's OWN throughput on THIS host: runs the real
    TF MultiWorkerMirroredStrategy 2-worker loopback program
    (benchmarks/tf_reference_bench.py) on the same synthetic dataset the
    tpu_dist benches use. Cached in benchmarks/tf_baseline_host.json because
    the measurement costs minutes; the cache carries a host fingerprint and
    is ignored (re-measured) on any other machine, so the 'measured on this
    host' basis stays true. Delete the cache to force a re-measure. Returns
    None where tensorflow/tf_keras is unavailable (fallback: the survey
    constant)."""
    import importlib.metadata
    import platform
    import socket

    try:
        tf_version = importlib.metadata.version("tensorflow")
    except importlib.metadata.PackageNotFoundError:
        tf_version = None

    def _machine_unique():
        # Same-image VMs share hostname/kernel/cpu_count; machine-id (or
        # per-boot boot_id) actually distinguishes machines, at the cost of
        # one fresh ~minute measurement per machine/boot.
        for p in ("/etc/machine-id", "/proc/sys/kernel/random/boot_id"):
            try:
                with open(p) as f:
                    return f.read().strip()
            except OSError:
                continue
        return None

    fingerprint = {"hostname": socket.gethostname(),
                   "machine": platform.machine(),
                   "cpu_count": os.cpu_count(),
                   "kernel": platform.release(),
                   "tf_version": tf_version,
                   "machine_id": _machine_unique()}
    try:
        with open(TF_BASELINE_CACHE) as f:
            cached = json.load(f)
        if cached.get("host_fingerprint") == fingerprint:
            return cached
        print("tf baseline cache is from another host; re-measuring",
              file=sys.stderr)
    except (OSError, ValueError):
        pass
    result = measure_tf_reference_once(timeout)
    if result is not None:
        result["host_fingerprint"] = fingerprint
        try:
            with open(TF_BASELINE_CACHE, "w") as f:
                json.dump(result, f, indent=2)
        except OSError:
            pass
    return result


def measure_tf_reference_once(timeout: float = 1500) -> dict | None:
    """ONE fresh (uncached) run of the TF reference loopback bench — the
    same-session side of the r5 interleaved A/B protocol. Never reads or
    writes the cross-round cache."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "tf_reference_bench.py")
    try:
        proc = subprocess.run(
            [sys.executable, script, "--warmup-steps", "10",
             "--timed-steps", "30"],
            capture_output=True, text=True, timeout=timeout)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"tf reference measurement failed: {e}", file=sys.stderr)
        return None
    if proc.returncode != 0:
        print(f"tf reference measurement rc={proc.returncode}: "
              f"{proc.stderr[-500:]}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    return None


def run_cpu_baseline() -> dict:
    """The reference's own measured config, like for like: 2 CPU devices,
    global batch 256 (= the reference's effective 2x128 consumption, see
    below), end-to-end fit loop — compared against the ACTUAL
    TF MultiWorkerMirroredStrategy reference program measured on this same
    host (measure_tf_reference), falling back to the survey's ~62 ms/step
    (=> ~2065 img/s/core per worker stream, SURVEY.md §3.5) when TF is
    unavailable."""
    # Global batch 256 = the reference's effective consumption: with
    # autoshard OFF each of its 2 workers draws its OWN batch of 128
    # (SURVEY.md §3.4), so 256 distinct images/step over 2 cores. Our SPMD
    # equivalent is one 256 batch sharded over 2 devices; per-core rates are
    # then directly comparable. Host pipeline, matching the TF reference's
    # host-side tf.data stream — the device-resident pipeline's rate is in
    # the breakdown, clearly labeled, not in the headline ratio.
    #
    # r5 protocol (VERDICT r4 #1): SYMMETRIC same-session interleaving.
    # r4 compared a fresh framework sample against a cached best-of-windows
    # TF number measured on an idle host, so the recorded ratio tracked
    # ambient load, not code (0.825 -> 0.679 with nothing slower). Now TF
    # and tpu_dist run A/B/A/B in the SAME session under the same load,
    # both sides take best-of (the same estimator the old cache used), and
    # vs_reference is computed against the same-session TF rate. The
    # cached number stays recorded as the cross-round reference point.
    import datetime

    session_started = datetime.datetime.now(datetime.timezone.utc)
    td_args = ["--e2e-child", "mnist_cnn", "--batch", "256",
               "--epochs", "2", "--steps", "50", "--spe", "1",
               "--pipeline", "host"]
    tf_runs, td_runs, td_batch_runs = [], [], []
    for _ in range(3):
        tf = measure_tf_reference_once()
        if tf is not None:
            tf_runs.append(tf)
        td_runs.append(_run_child(td_args, 2))
        # SCHED_BATCH variant: the 2-partition child resyncs its
        # threads every step, amplifying any timeslice churn 4-5x
        # (measured: the same child swings 865-1204 img/s/core across
        # sessions while its single-stream and the TF side hold within
        # a few %). Longer timeslices bound the amplification — the
        # same mitigation the 2proc section records.
        td_batch_runs.append(_run_child(
            td_args, 2, extra_env={"TPU_DIST_SCHED": "batch"}))
    # Estimator symmetry: the scheduling mode is a CONFIGURATION choice
    # (a framework may set its own process scheduling), not extra
    # samples — the winning config is chosen first, then its best-of-3
    # stands against TF's best-of-3. Pooling all 6 td samples against 3
    # TF samples would inflate the ratio by sample count alone.
    best_of = lambda runs: max(
        runs, key=lambda x: x["images_per_sec_per_core"])
    chosen, sched = td_runs, "default"
    if (td_batch_runs
            and best_of(td_batch_runs)["images_per_sec_per_core"]
            > best_of(td_runs)["images_per_sec_per_core"]):
        chosen, sched = td_batch_runs, "sched_batch"
    r = best_of(chosen)
    r["runs_step_ms"] = [x["step_ms"] for x in chosen]
    r["mode"] = "cpu_baseline_like_for_like"
    r["interleave"] = {
        "protocol": ("A/B/A/B same-session, 3 rounds: tf reference and "
                     "tpu_dist alternate under the same ambient load; "
                     "the tpu_dist scheduling config (default vs "
                     "SCHED_BATCH) is chosen first, then ITS best-of-3 "
                     "stands against tf's best-of-3 — same sample count "
                     "on both sides of the ratio"),
        "session_started_utc": session_started.isoformat(
            timespec="seconds"),
        "scheduling_config_chosen": sched,
        "tf_img_s_core": [round(t["images_per_sec_per_core"], 1)
                          for t in tf_runs],
        "tpu_dist_img_s_core": [round(t["images_per_sec_per_core"], 1)
                                for t in td_runs],
        "tpu_dist_sched_batch_img_s_core": [
            round(t["images_per_sec_per_core"], 1)
            for t in td_batch_runs],
    }
    # Where the remaining gap lives (r3 audit, measured on the 1-core
    # build host after the conv-im2col/pool fast paths): step-only equals
    # e2e (input off the step path), and a single unpartitioned stream
    # shows the 2-virtual-devices-on-1-core partition-emulation cost.
    try:
        r["breakdown"] = {
            "e2e_2dev_device_pipeline": _run_child(
                ["--e2e-child", "mnist_cnn", "--batch", "256",
                 "--epochs", "1", "--steps", "50", "--spe", "1",
                 "--pipeline", "device"], 2),
            "step_only_2dev": _run_child(
                ["--step-child", "mnist_cnn", "--batch", "256",
                 "--steps", "60", "--warmup", "12", "--spe", "1",
                 "--repeats", "2"], 2),
            "single_stream_1dev_batch128": _run_child(
                ["--step-child", "mnist_cnn", "--batch", "128",
                 "--steps", "60", "--warmup", "12", "--spe", "1",
                 "--repeats", "2"], 1),
            "floor_note": (
                "XLA:CPU conv floor (microbenched, batch 128): the wide "
                "3x3x32->64 conv's best formulation is the native lax conv "
                "(fwd 5.2 ms, +grads 21 ms); im2col and shifted-matmul "
                "recasts lose 2-3x, and the --xla_cpu_use_onednn/xnnpack "
                "flags measure as no-ops for conv here. TF/oneDNN runs the "
                "same worker stream in ~45 ms vs our 50 ms (0.90x); the "
                "rest of the gap is two partition threads timesharing one "
                "physical core + per-step rendezvous sync, which real "
                "multi-core workers don't pay."),
        }
    except Exception as e:
        r["breakdown"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    _attach_reference_ratio(r, include_tf_record=True,
                            same_session_tf=tf_runs)
    # Paired gap decomposition (VERDICT r4 #1's fallback 'Done'): the
    # single unpartitioned stream runs one 128-batch step on the whole
    # core (rate R1); an overhead-free 2-partition step would serialize
    # two of those on the same core => per-core rate R1/2. Measured
    # 2-dev per-core vs R1/2 isolates the PARTITION-EMULATION cost (two
    # XLA partitions timesharing one physical core — paid only on this
    # degenerate host); R1/2 vs the same-session TF rate isolates the
    # KERNEL gap (XLA:CPU conv vs oneDNN, the r3 floor audit). Their
    # product reproduces vs_reference.
    try:
        ss = r["breakdown"]["single_stream_1dev_batch128"]
        ideal = ss["images_per_sec_per_core"] / 2
        ref = r.get("reference_images_per_sec_per_core")
        r["gap_decomposition"] = {
            "single_stream_img_s_core": ss["images_per_sec_per_core"],
            "ideal_2dev_per_core_R1_over_2": round(ideal, 1),
            "partition_emulation_factor": round(
                r["images_per_sec_per_core"] / ideal, 3),
            "kernel_factor_vs_tf": (round(ideal / ref, 3)
                                    if ref else None),
            "note": ("vs_reference ~= kernel_factor x emulation_factor; "
                     "the emulation term is the "
                     "2-virtual-devices-on-1-core artifact no real "
                     "deployment pays"),
        }
    except (KeyError, TypeError, ZeroDivisionError):
        pass
    return r


def _attach_reference_ratio(r: dict, *, include_tf_record: bool = False,
                            basis_suffix: str = "",
                            same_session_tf: list | None = None) -> None:
    """Stamp reference_basis / reference rate / vs_reference onto a CPU
    bench section — ONE definition of what 'vs_reference' means, shared by
    the in-process and 2-process baselines. ``same_session_tf`` (r5) is a
    list of fresh interleaved TF measurements: when present, vs_reference
    uses their best (the symmetric estimator) and the cached cross-round
    number is recorded separately for continuity."""
    tf_ref = measure_tf_reference()
    if same_session_tf:
        best = max(same_session_tf,
                   key=lambda t: t["images_per_sec_per_core"])
        ref_rate = best["images_per_sec_per_core"]
        r["reference_basis"] = (
            "tf MultiWorkerMirroredStrategy 2-worker loopback measured "
            "SAME-SESSION, interleaved A/B with the tpu_dist runs"
            + basis_suffix)
        if include_tf_record:
            r["tf_reference"] = best
        if tf_ref is not None:
            r["cross_round_reference_rate"] = round(
                tf_ref["images_per_sec_per_core"], 1)
        r["reference_images_per_sec_per_core"] = round(ref_rate, 1)
        r["vs_reference"] = round(
            r["images_per_sec_per_core"] / ref_rate, 3)
        return
    if tf_ref is not None:
        ref_rate = tf_ref["images_per_sec_per_core"]
        r["reference_basis"] = ("tf MultiWorkerMirroredStrategy 2-worker "
                                "loopback measured on this host"
                                + basis_suffix)
        if include_tf_record:
            r["tf_reference"] = tf_ref
    else:
        ref_rate = REFERENCE_CPU_IMG_PER_SEC_PER_CORE
        r["reference_basis"] = ("survey-hardware constant ~62 ms/step "
                                "(SURVEY.md §3.5); tf unavailable here")
    r["reference_images_per_sec_per_core"] = round(ref_rate, 1)
    r["vs_reference"] = round(r["images_per_sec_per_core"] / ref_rate, 3)


def run_cpu_baseline_2proc(timeout: float = 1200) -> dict:
    """BASELINE.md config 3's LITERAL shape: two real OS processes, each
    with a per-worker TF_CONFIG and ONE CPU device, synchronized through
    the jax.distributed coordination service with per-step cross-process
    all-reduces — the same topology the TF reference baseline was measured
    in (benchmarks/tf_reference_bench.py). The like-for-like
    ``cpu_baseline`` section instead emulates 2 devices inside one process
    (in-process SPMD), which pays partition-threads-on-one-core costs a
    real 2-process launch does not; this section settles which sync
    mechanism the 0.x gap belongs to. One device per process also sidesteps
    the XLA:CPU shared-pool rendezvous-starvation hazard
    (trainer._bounded_dispatch), so the dispatch pipeline stays on."""
    import socket

    from tpu_dist.cluster.config import make_local_cluster

    def one_launch(extra_env: dict) -> list[dict]:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        configs = make_local_cluster(2, base_port=port)
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "twoproc_worker.py")
        procs = []
        for cfg in configs:
            env = dict(os.environ)
            env.update({
                "TF_CONFIG": json.dumps(cfg),
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
                + os.pathsep + env.get("PYTHONPATH", ""),
            })
            env.update(extra_env)
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        results = []
        try:
            for i, p in enumerate(procs):
                try:
                    out, err = p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    raise RuntimeError(f"2proc worker {i} timed out")
                if p.returncode != 0:
                    raise RuntimeError(
                        f"2proc worker {i} rc={p.returncode}: {err[-500:]}")
                payload = None
                for line in out.splitlines():
                    if line.startswith("RESULT:"):
                        payload = json.loads(line[len("RESULT:"):])
                if payload is None:
                    raise RuntimeError(f"2proc worker {i} emitted no "
                                       f"RESULT ({out[-300:]!r})")
                results.append(payload)
        finally:
            # A dead worker must take its sibling with it: the survivor
            # would otherwise busy-wait in coordination-service connect on
            # the shared single core, polluting every later bench section.
            for q in procs:
                if q.poll() is None:
                    q.kill()
        return results

    # r5 (VERDICT r4 #6): attempt the spin mitigation, then frame the row
    # honestly. SCHED_BATCH is the one host-side knob that could plausibly
    # bound the gloo busy-poll's damage (longer timeslices => fewer
    # mid-compute preemptions by the spinning peer); both settings are
    # measured and recorded. jax's CPU collectives expose no blocking-wait
    # knob to bound the spin itself.
    attempts = {}
    results = one_launch({})
    attempts["default"] = {
        "step_ms": max(w["step_ms"] for w in results),
        "images_per_sec_per_core": min(
            w["images_per_sec_per_core"] for w in results)}
    try:
        batch_results = one_launch({"TWOPROC_SCHED": "batch"})
        attempts["sched_batch"] = {
            "step_ms": max(w["step_ms"] for w in batch_results),
            "images_per_sec_per_core": min(
                w["images_per_sec_per_core"] for w in batch_results)}
        if (attempts["sched_batch"]["images_per_sec_per_core"]
                > attempts["default"]["images_per_sec_per_core"]):
            results = batch_results
    except RuntimeError as e:
        attempts["sched_batch"] = {"error": str(e)[:200]}
    r = {
        "mode": "cpu_baseline_2proc_tf_config_loopback",
        # The headline for BASELINE config 3 on this host is the
        # in-process SPMD `cpu_baseline` section: this row measures a
        # DEGENERATE topology (2 spinning workers on 1 physical core)
        # that no real deployment runs, kept for the honest record.
        "degenerate_topology": True,
        "workers": 2,
        "per_worker": results,
        "mitigation_attempts": attempts,
        # Collectives make the workers' step times near-identical; report
        # the slower worker (the job runs at the laggard's pace).
        "step_ms": max(w["step_ms"] for w in results),
        "images_per_sec_per_core": min(
            w["images_per_sec_per_core"] for w in results),
        "topology_note": (
            "DEGENERATE TOPOLOGY: 2 real processes timeshare this host's "
            "ONE physical core — a configuration no real deployment runs "
            "(the reference's own docs assume a core per worker). r4 "
            "probes: the compiled step carries only 2 (tuple-packed) "
            "all-reduces — XLA combines the 8 gradient tensors like TF's "
            "bytes_per_pack — and a lone cross-process all-reduce costs "
            "~4-5 ms; the dominant cost is jax's gloo CPU collectives "
            "BUSY-POLLING while the peer computes, stealing ~half the "
            "shared core (measured: compute runs ~2x slower with a "
            "spinning peer; 2x(2x48 ms) matches the ~198 ms step). TF's "
            "gRPC ring blocks in epoll instead of spinning, so its two "
            "workers serialize cleanly at ~90 ms. r5 mitigation: jax "
            "exposes no blocking-wait knob for its CPU collectives, but "
            "SCHED_BATCH on both workers (longer timeslices => fewer "
            "mid-compute preemptions by the spinning sibling) recovers a "
            "large fraction — see mitigation_attempts; the better "
            "setting is the reported row. With >=1 core per worker "
            "(every real deployment) the spin overlaps nothing; the "
            "in-process SPMD `cpu_baseline` section is the config-3 "
            "like-for-like on this host."),
    }
    _attach_reference_ratio(
        r, basis_suffix=" — IDENTICAL topology to this section")
    return r


def run_scaling(mesh_sizes=(1, 2, 4, 8), global_batch: int = 128,
                spe: int = 16, config: str = "mnist_cnn",
                steps: int = 32, warmup: int = 16,
                seq_len: int | None = None) -> dict:
    """SPMD partition-overhead table on a virtual CPU mesh, at fixed GLOBAL
    work: the same global batch (the reference's 128, tf_dist_example.py:
    17-18) is sharded over 1/2/4/8 virtual devices that all share one
    physical core. Total FLOPs are identical at every mesh size, so ideal
    behavior is a flat step time; efficiency = t(1 device)/t(n devices).
    What this isolates is everything the SPMD partitioner ADDS — partition
    bookkeeping + emulated collectives — which is exactly the overhead this
    framework's design is supposed to keep out of the step (SURVEY.md §5.8).

    (True weak scaling — per-core batch fixed, ≥90% to 32 cores,
    BASELINE.md's north star — needs real parallel silicon; on one physical
    core growing total work n-fold just measures the core doing n× the
    FLOPs. The driver's multichip dryrun plus this overhead table are the
    1-chip-environment stand-ins.)"""
    rows = []
    for n in mesh_sizes:
        args = ["--step-child", config,
                "--batch", str(global_batch),
                "--steps", str(steps), "--warmup", str(warmup),
                "--spe", str(spe), "--repeats", "2"]
        if seq_len is not None:
            args += ["--seq", str(seq_len)]
        r = _run_child(args, n)
        rows.append({"devices": n,
                     "global_batch": r["global_batch"],
                     "per_device_batch": r["global_batch"] // n,
                     "step_ms": r["step_ms"],
                     "images_per_sec": r["images_per_sec"]})
    base = rows[0]["step_ms"]
    for row in rows:
        row["partition_efficiency_pct"] = round(
            100.0 * base / row["step_ms"], 1)
    return {"mode": "spmd_fixed_global_work_virtual_cpu_mesh",
            "config": config,
            "global_batch": global_batch,
            "steps_per_execution": spe, "rows": rows}


def run_scaling_all() -> dict:
    """Both scaling workloads side by side (VERDICT r2 'weak #4'):

    - ``transformer_lm``: matmul-dominated, so single-core cost is ~linear
      in per-device batch and the fixed-global-work ideal (flat step time)
      genuinely bounds SPMD partition overhead.
    - ``mnist_cnn``: kept for continuity, with its known caveat — XLA:CPU
      conv cost is superlinear in per-device batch, so its 'efficiency'
      column mixes backend artifacts into the metric.
    """
    # spe=1 for both workloads: XLA:CPU lowers the scanned multi-step body
    # pathologically (r3: spe=8 measured 3.4 s/step vs 8x115 ms unrolled),
    # and with per-exec sync the spe knob only adds that pathology to the
    # thing being measured. Batch/step counts sized for a 1-core host: the
    # LM's matmul-dominated step measures ~9 s at batch 8 there, so each
    # mesh size costs ~3 min of the 900 s child timeout.
    return {
        "transformer_lm": run_scaling(config="transformer_lm",
                                      global_batch=8, spe=1, steps=8,
                                      warmup=3),
        # The 1 -> 32-device virtual table (BASELINE.md config 5's 32-core
        # story, as far as a 1-chip host allows): the matmul-dominated LM
        # at seq 128 / batch 32, so the per-device batch stays >= 1 at 32
        # partitions and one physical core can afford six mesh sizes.
        "transformer_lm_32": run_scaling(
            mesh_sizes=(1, 2, 4, 8, 16, 32), config="transformer_lm",
            global_batch=32, spe=1, steps=4, warmup=2, seq_len=128),
        "mnist_cnn_conv_caveat": run_scaling(spe=1, steps=24, warmup=8),
    }


# -- entry points -------------------------------------------------------------


def _data_basis() -> dict:
    """Per-dataset provenance of the benched data, recorded with every
    run: real files when $TPU_DIST_DATA_DIR (or a keras/tfds dir) holds
    that dataset, else the deterministic synthetic fallback. The build
    environment is egress-free (scripts/fetch_data.py fails at DNS; no
    dataset copies exist in the image — README 'Data'), so rounds 1-3 are
    synthetic throughout."""
    from tpu_dist.data.sources import _find_shard_files, _try_local
    basis = {}
    for name in ("mnist", "fashion_mnist", "cifar10"):
        real = bool(_find_shard_files(name, "train")) or (
            _try_local(name, "train") is not None)
        basis[name] = "real local files" if real else "synthetic fallback"
    basis["note"] = ("egress-free host, no local datasets staged; "
                     "see README Data section")
    return basis


def driver_run() -> int:
    """Default mode: full benchmark record; ONE JSON line on stdout."""
    extras: dict = {}

    # CPU baselines FIRST, before this parent process ever initializes
    # jax on the tunneled TPU: the axon client keeps heartbeat/poll
    # threads alive that steal slices of the single core, and the
    # lock-step 2-virtual-device child AMPLIFIES any steal (its two
    # partition threads resync every step) while TF's blocking gRPC
    # workers barely notice — measured r5: td 1203 -> 865 img/s/core
    # with a TPU-initialized parent vs TF 1449 -> 1410, skewing
    # vs_reference from 0.83 to 0.62 for ordering reasons alone.
    for name, fn in (("cpu_baseline", run_cpu_baseline),
                     ("cpu_baseline_2proc", run_cpu_baseline_2proc)):
        try:
            extras[name] = fn()
            print(json.dumps(extras[name]), file=sys.stderr)
        except Exception as e:
            extras[name] = {"error": f"{type(e).__name__}: {e}"[:500]}
            print(f"section {name} failed: {e}", file=sys.stderr)

    # 5 timing windows: the chip is shared (tunnelled) and run-to-run
    # variance is large; best-of-5 makes the headline robust to neighbors.
    # spe=64 (r4 A/B: 0.29 ms/step vs 0.60 at spe=16 — the step is
    # dispatch-bound, deeper scanning halves the amortized dispatch).
    # The tunnel can also be DOWN (observed r5: 'Unable to initialize
    # backend axon: UNAVAILABLE' mid-day) — a dead chip must still
    # produce the one parseable stdout line, with the failure recorded.
    try:
        headline = run_step_bench("mnist_cnn", steps=512, warmup=64,
                                  global_batch=128, spe=64, repeats=5)
    except Exception as e:
        headline = {"images_per_sec_per_core": None,
                    "steps_per_execution": 64,
                    "error": f"{type(e).__name__}: {e}"[:500]}
    print(json.dumps(headline), file=sys.stderr)

    sections = {
        "mnist_cnn_spe1": lambda: run_step_bench(
            "mnist_cnn", steps=200, warmup=20, global_batch=128, spe=1),
        "mnist_cnn_e2e_fit": lambda: run_e2e_fit(
            "mnist_cnn", epochs=3, steps_per_epoch=100, global_batch=128),
        "mnist_cnn_e2e_fit_hostpipe": lambda: run_e2e_fit(
            "mnist_cnn", epochs=1, steps_per_epoch=100, global_batch=128,
            pipeline="host"),
        # The ported reference script's own pipeline shape through the
        # public combinators (load -> map(scale) -> cache -> shuffle ->
        # batch): the vectorize pass promotes it to device residency.
        "mnist_cnn_e2e_fit_refchain": lambda: run_e2e_fit(
            "mnist_cnn", epochs=3, steps_per_epoch=100, global_batch=128,
            pipeline="refchain"),
        "resnet18": lambda: run_step_bench(
            "resnet18", steps=96, warmup=16, global_batch=256, spe=8),
        "resnet50": lambda: run_step_bench(
            "resnet50", steps=48, warmup=8, global_batch=256, spe=4),
        # The TPU-native recipe (bf16 on the MXU): ~1.3x on ResNet-18
        # (47% MFU), ~1.9x on ResNet-50 (31% MFU), identical loss curves.
        "resnet18_bf16": lambda: run_step_bench(
            "resnet18", steps=96, warmup=16, global_batch=256, spe=8,
            precision_policy="mixed_bfloat16"),
        "resnet50_bf16": lambda: run_step_bench(
            "resnet50", steps=48, warmup=8, global_batch=256, spe=4,
            precision_policy="mixed_bfloat16"),
        # Long-context family: GPT-style causal LM (vocab 8k, d_model 512,
        # 4 blocks, seq 512) — the attention/MLP matmul workload. spe=32:
        # the r4 on-chip A/B measured 42.7 % MFU bf16 vs 40.7 at spe=16
        # (dispatch amortization still pays at ~45 ms steps through the
        # tunneled runtime; b=128 at spe=16 measured below b=64 at spe=32).
        "transformer_lm": lambda: run_step_bench(
            "transformer_lm", steps=64, warmup=32, global_batch=64, spe=32),
        "transformer_lm_bf16": lambda: run_step_bench(
            "transformer_lm", steps=64, warmup=32, global_batch=64, spe=32,
            precision_policy="mixed_bfloat16"),
    }
    for name, fn in sections.items():
        try:
            extras[name] = fn()
            print(json.dumps(extras[name]), file=sys.stderr)
        except Exception as e:  # a failed extra must not kill the headline
            extras[name] = {"error": f"{type(e).__name__}: {e}"[:500]}
            print(f"section {name} failed: {e}", file=sys.stderr)

    # vs_baseline answers BASELINE.md's north-star question directly: does
    # the TPU-native harness match/beat the reference's 2-worker
    # throughput-per-device? Numerator: our end-to-end fit() per-core rate
    # (input pipeline + dispatch on the timed path — what a user gets).
    # Denominator: the ACTUAL TF reference program measured on this same
    # host (same synthetic data, same model/batch/optimizer). The hardware
    # differs by design — switching the silicon is the point of the
    # framework; the basis string says so, and the same-silicon CPU-backend
    # ratio is in extras.cpu_baseline.vs_reference for completeness.
    cpu = extras.get("cpu_baseline", {})
    tf_ref = (cpu.get("tf_reference") or {}).get("images_per_sec_per_core")
    e2e = extras.get("mnist_cnn_e2e_fit", {}).get("images_per_sec_per_core")
    if tf_ref and e2e:
        vs_baseline = round(e2e / tf_ref, 3)
        basis = ("e2e fit img/s/core on this chip vs the TF reference "
                 "program's 2-worker loopback img/s/core measured on this "
                 "same host (benchmarks/tf_reference_bench.py)")
    else:
        vs_baseline = cpu.get("vs_reference")
        basis = cpu.get(
            "reference_basis",
            "2-device CPU e2e fit vs SURVEY.md §3.5 constant")
    # The driver captures only the TAIL of stdout, so the one stdout JSON
    # line must stay short (r2 inlined every extra and the capture started
    # mid-JSON -> BENCH_r02 parsed=null). Headline scalars only here; the
    # full record goes to the extras blob (path emitted in the line).
    extras_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "bench_r5_full.json")
    try:
        os.makedirs(os.path.dirname(extras_path), exist_ok=True)
        with open(extras_path, "w") as f:
            json.dump({"headline": headline, "extras": extras,
                       "data_basis": _data_basis()}, f, indent=1)
    except OSError as e:
        print(f"could not write extras blob: {e}", file=sys.stderr)
        extras_path = None

    def _pick(name, key):
        v = extras.get(name, {})
        return v.get(key) if isinstance(v, dict) else None

    line = {
        "metric": "mnist_cnn_images_per_sec_per_core",
        "value": headline.get("images_per_sec_per_core"),
        **({"chip_error": headline["error"]}
           if "error" in headline else {}),
        "unit": "images/sec/core",
        "steps_per_execution": headline["steps_per_execution"],
        "mfu_pct": headline.get("mfu_pct"),
        "headline_note": ("mnist step is dispatch-bound (sub-ms; deeper "
                          "steps_per_execution scans keep halving it); its "
                          "mfu_pct measures dispatch amortization, not the "
                          "MXU — see highlights for MXU-bound configs"),
        "vs_baseline": vs_baseline,
        "vs_baseline_basis": basis,
        "highlights": {
            "e2e_fit_img_s_core": _pick("mnist_cnn_e2e_fit",
                                        "images_per_sec_per_core"),
            "e2e_refchain_img_s_core": _pick("mnist_cnn_e2e_fit_refchain",
                                             "images_per_sec_per_core"),
            "hostpipe_img_s_core": _pick("mnist_cnn_e2e_fit_hostpipe",
                                         "images_per_sec_per_core"),
            "resnet50_bf16_mfu_pct": _pick("resnet50_bf16", "mfu_pct"),
            "resnet50_fp32_mfu_pct": _pick("resnet50", "mfu_pct"),
            "lm_bf16_mfu_pct": _pick("transformer_lm_bf16", "mfu_pct"),
            "lm_bf16_tokens_s_core": _pick("transformer_lm_bf16",
                                           "tokens_per_sec_per_core"),
            "cpu_vs_reference": cpu.get("vs_reference"),
            "cpu_vs_reference_basis": (
                "same-session interleaved A/B"
                if cpu.get("interleave") else cpu.get("reference_basis")),
            "cpu_2proc_vs_reference_degenerate_topology": _pick(
                "cpu_baseline_2proc", "vs_reference"),
        },
        "extras_path": extras_path,
    }
    print(json.dumps(line))
    return 0


def main(argv=None) -> int:
    # Child scheduling knob (parent sets TPU_DIST_SCHED=batch): longer
    # timeslices cut the preemption churn that the in-process
    # 2-partition SPMD child AMPLIFIES (its threads resync every step,
    # so a 5% steal reads as a 20-30% step inflation). Same mitigation
    # the 2-process bench records in mitigation_attempts.
    if os.environ.get("TPU_DIST_SCHED") == "batch":
        try:
            os.sched_setscheduler(0, os.SCHED_BATCH, os.sched_param(0))
        except (OSError, AttributeError) as e:
            print(f"SCHED_BATCH unavailable: {e}", file=sys.stderr)
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("config", nargs="?", default=None,
                        choices=sorted(CONFIGS))
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--warmup", type=int, default=20)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--spe", type=int, default=16,
                        help="steps per execution (lax.scan inside one "
                             "dispatch); 1 = classic per-step dispatch")
    parser.add_argument("--e2e", action="store_true",
                        help="measure end-to-end fit() instead of the "
                             "compiled step")
    parser.add_argument("--pipeline", choices=("device", "host", "refchain"),
                        default="device",
                        help="e2e input path: device-resident gather, host "
                             "streaming loader, or the literal reference "
                             "combinator chain (vectorize promotion)")
    parser.add_argument("--scaling", action="store_true",
                        help="1/2/4/8-device virtual-CPU fixed-global-work "
                             "partition-overhead table")
    parser.add_argument("--repeats", type=int, default=3,
                        help="timing windows per measurement")
    parser.add_argument("--bf16", action="store_true",
                        help="mixed_bfloat16 policy (bf16 activations on "
                             "the MXU, fp32 params)")
    parser.add_argument("--seq", type=int, default=None,
                        help="transformer_lm sequence-length override "
                             "(long-context sweeps)")
    parser.add_argument("--step-child", metavar="CONFIG",
                        help=argparse.SUPPRESS)
    parser.add_argument("--e2e-child", metavar="CONFIG",
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.step_child:
        print(json.dumps(run_step_bench(args.step_child, args.steps,
                                        args.warmup, args.batch, args.spe,
                                        repeats=args.repeats,
                                        seq_len=args.seq)))
        return 0
    if args.e2e_child:
        print(json.dumps(run_e2e_fit(args.e2e_child, args.epochs, args.steps,
                                     args.batch, args.spe,
                                     pipeline=args.pipeline)))
        return 0
    if args.scaling:
        table = run_scaling_all()
        print(json.dumps(table, indent=2), file=sys.stderr)
        print(json.dumps(table))
        return 0
    if args.config is None:
        return driver_run()

    policy_arg = "mixed_bfloat16" if args.bf16 else None
    if args.e2e:
        if args.bf16:
            from tpu_dist.models.policy import set_policy
            set_policy("mixed_bfloat16")
        result = run_e2e_fit(args.config, args.epochs, args.steps,
                             args.batch, args.spe, pipeline=args.pipeline)
    else:
        result = run_step_bench(args.config, args.steps, args.warmup,
                                args.batch, args.spe, repeats=args.repeats,
                                precision_policy=policy_arg,
                                seq_len=args.seq)
    print(json.dumps(result), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
