"""Benchmark harness: the reference's headline workload, TPU-native.

Workload (BASELINE.md): the reference's MNIST 2-conv CNN, global batch 128,
SGD lr=0.001 (tf_dist_example.py:17-18, 51) — trained with the jitted SPMD
step over a data-parallel mesh of every available device. Prints ONE JSON line:

    {"metric": "mnist_cnn_images_per_sec_per_core", "value": N,
     "unit": "images/sec/core", "vs_baseline": R}

``vs_baseline`` is relative to the survey's indicative measurement of the
reference (no numbers are published by the reference itself — BASELINE.md):
~62 ms/step at global batch 128 across 2 CPU workers, i.e. ~1032
images/sec/core (SURVEY.md §3.5, §6).

Extra configs (BASELINE.md table) are selectable:
    python bench.py [mnist_cnn|resnet18|resnet50] [--steps N] [--batch N]
Only the default config prints the driver JSON line on stdout; others report
to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

# Indicative reference throughput (images/sec/core), SURVEY.md §3.5/§6:
# global batch 128 / 62 ms/step / 2 workers (1 device each).
BASELINE_IMG_PER_SEC_PER_CORE = 128 / 0.062 / 2

CONFIGS = {
    # name: (dataset, model builder name, image shape, default global batch)
    "mnist_cnn": ("mnist", "cnn", (28, 28, 1), 128),
    "resnet18": ("fashion_mnist", "resnet18", (28, 28, 1), 256),
    "resnet50": ("cifar10", "resnet50", (32, 32, 3), 256),
}


def build_model(kind: str, input_shape, num_classes: int = 10):
    from tpu_dist.ops.losses import SparseCategoricalCrossentropy
    from tpu_dist.ops.metrics import SparseCategoricalAccuracy
    from tpu_dist.ops.optimizers import SGD

    if kind == "cnn":
        from tpu_dist.models.cnn import build_cnn_model

        model = build_cnn_model(num_classes=num_classes,
                                input_shape=input_shape)
    else:
        from tpu_dist.models import resnet

        model = {"resnet18": resnet.ResNet18,
                 "resnet50": resnet.ResNet50}[kind](
            num_classes=num_classes, input_shape=input_shape)
    model.compile(
        loss=SparseCategoricalCrossentropy(from_logits=True),
        optimizer=SGD(learning_rate=0.001),
        metrics=[SparseCategoricalAccuracy()],
    )
    return model


def load_batch(dataset_name: str, shape, global_batch: int):
    """One global batch from the named dataset (local files if present, else
    the deterministic synthetic fallback — tpu_dist.data.sources)."""
    from tpu_dist.data.sources import load_arrays

    x_all, y_all = load_arrays(dataset_name, "train")
    reps = -(-global_batch // len(x_all))
    if reps > 1:
        x_all, y_all = np.tile(x_all, (reps, 1, 1, 1)), np.tile(y_all, reps)
    x = (x_all[:global_batch].reshape(global_batch, *shape)
         .astype(np.float32) / 255.0)
    y = y_all[:global_batch].astype(np.int64)
    return x, y


def run(config: str, steps: int, warmup: int, global_batch: int | None,
        spe: int = 1) -> dict:
    import jax

    from tpu_dist.parallel.strategy import MirroredStrategy

    dataset_name, kind, shape, default_batch = CONFIGS[config]
    global_batch = global_batch or default_batch

    strategy = MirroredStrategy()
    n_dev = strategy.num_replicas_in_sync
    if global_batch % n_dev:
        global_batch += n_dev - global_batch % n_dev

    with strategy.scope():
        model = build_model(kind, shape)

    from tpu_dist.training.trainer import Trainer, jnp_stack_keys

    trainer = Trainer(model)
    trainer.ensure_variables(seed=0)

    # Device-resident batches, pre-sharded: the benchmark measures the compiled
    # step (fwd+loss+bwd+allreduce+update), with input delivery off the timed
    # path — matching how the reference's steady-state step time was read
    # (cached tf.data pipeline, SURVEY.md §3.4).
    key = jax.random.PRNGKey(0)
    v = trainer.variables
    state = (v["params"], v["state"], v["opt"], v["metrics"],
             trainer._init_loss_acc())

    if spe > 1:
        # steps_per_execution: one dispatch runs `spe` scanned steps over
        # distinct stacked batches (trainer._build_multi_step).
        # Round the step counts up to whole executions.
        steps = -(-steps // spe) * spe
        warmup = -(-warmup // spe) * spe
        train_fn = trainer._build_multi_step()
        x, y = load_batch(dataset_name, shape, global_batch * spe)
        xb = strategy.distribute_batch_stack(
            x.reshape(spe, global_batch, *shape))
        yb = strategy.distribute_batch_stack(y.reshape(spe, global_batch))
        keys = [jnp_stack_keys(key, i * spe, spe)
                for i in range((warmup + steps) // spe)]
        n_exec_warm, n_exec = warmup // spe, steps // spe
    else:
        train_fn = trainer._build_train_step()
        x, y = load_batch(dataset_name, shape, global_batch)
        xb = strategy.distribute_batch(x)
        yb = strategy.distribute_batch(y)
        # Per-step keys precomputed off the timed path — fold_in is an eager
        # device op whose dispatch would otherwise pollute the dispatch-bound
        # step-time measurement.
        keys = [jax.random.fold_in(key, i) for i in range(warmup + steps)]
        n_exec_warm, n_exec = warmup, steps

    def one_exec(state, i):
        loss, p, s, o, m, acc = train_fn(*state, xb, yb, keys[i])
        return loss, (p, s, o, m, acc)

    loss = None
    for i in range(n_exec_warm):
        loss, state = one_exec(state, i)
    jax.block_until_ready((loss, state))

    t0 = time.perf_counter()
    for i in range(n_exec_warm, n_exec_warm + n_exec):
        loss, state = one_exec(state, i)
    jax.block_until_ready((loss, state))
    elapsed = time.perf_counter() - t0

    step_ms = elapsed / steps * 1e3
    img_per_sec = global_batch * steps / elapsed
    img_per_sec_per_core = img_per_sec / n_dev
    return {
        "config": config,
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "global_batch": global_batch,
        "steps": steps,
        "steps_per_execution": spe,
        "step_ms": round(step_ms, 4),
        "images_per_sec": round(img_per_sec, 1),
        "images_per_sec_per_core": round(img_per_sec_per_core, 1),
        "final_loss": float(jax.device_get(loss)),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("config", nargs="?", default="mnist_cnn",
                        choices=sorted(CONFIGS))
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--warmup", type=int, default=20)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--spe", type=int, default=16,
                        help="steps per execution (lax.scan inside one "
                             "dispatch); 1 = classic per-step dispatch")
    args = parser.parse_args(argv)

    result = run(args.config, args.steps, args.warmup, args.batch, args.spe)
    print(json.dumps(result), file=sys.stderr)

    if args.config == "mnist_cnn":
        # Headline measured at the framework's intended best-practice config
        # (steps_per_execution amortizes dispatch, compile(steps_per_execution=K)
        # in user code); the spe value is recorded so the number is
        # interpretable against per-step runs (--spe 1).
        line = {
            "metric": "mnist_cnn_images_per_sec_per_core",
            "value": result["images_per_sec_per_core"],
            "unit": "images/sec/core",
            "steps_per_execution": result["steps_per_execution"],
            "vs_baseline": round(
                result["images_per_sec_per_core"]
                / BASELINE_IMG_PER_SEC_PER_CORE, 3),
        }
        print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
