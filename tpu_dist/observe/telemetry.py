"""Telemetry wiring: step timers, the collective observe hook, and the
``Telemetry`` fit callback.

This is the glue between the passive :mod:`~tpu_dist.observe.metrics`
registry and the places time is actually spent:

* :class:`StepTimer` — the trainer's hot loop (training/trainer.py) splits
  each compiled execution into **data-wait** (host input pipeline),
  **dispatch** (host->device launch of the jitted program) and **device**
  (blocking ``block_until_ready``) and records per-step means here. The
  trainer finds the timer through :func:`active_step_timer` — a module
  global, not a callback argument — so the hot loop pays one global read
  when telemetry is off.
* :func:`registry_collective_hook` — plugs into the observe-hook seam in
  ``parallel/collectives.py`` (the sibling of the resilience fault hook)
  and turns every wrapper call into per-op counters (calls, payload
  bytes) and host-wall-time distributions.
* :class:`Telemetry` — the built-in callback that arms all of the above
  for one ``fit`` span, exchanges per-rank step times through
  ``collectives.host_all_gather`` at each epoch end, runs straggler
  detection on the chief, emits ``step_timing`` / ``straggler_detected``
  records into the resilience :mod:`~tpu_dist.resilience.events` log,
  and exports JSONL/Prometheus snapshots.

Like the fault plan, telemetry can ride in through the environment:
``TPU_DIST_OBSERVE_DIR=/some/dir`` makes every ``fit`` in the process
attach a :class:`Telemetry` writing ``metrics.jsonl`` + ``metrics.prom``
there — the Supervisor uses exactly this to instrument chaos workers
without code edits (:func:`maybe_telemetry_from_env`).
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Optional

from tpu_dist.observe import exporters, straggler
from tpu_dist.observe import metrics as metrics_lib
from tpu_dist.training.callbacks import Callback

logger = logging.getLogger("tpu_dist.observe")

#: Environment variable arming per-fit telemetry (directory for exports);
#: set by the resilience Supervisor for chaos workers.
OBSERVE_DIR_ENV = "TPU_DIST_OBSERVE_DIR"

#: The StepTimer the trainer's hot loop reports to; None when no Telemetry
#: span is active (the common case — one global read per execution).
_ACTIVE_TIMER: Optional["StepTimer"] = None


def active_step_timer() -> Optional["StepTimer"]:
    return _ACTIVE_TIMER


def set_active_step_timer(timer: Optional["StepTimer"]):
    """Install (or with None, clear) the hot-loop step timer; returns the
    previous one so callers can restore it."""
    global _ACTIVE_TIMER
    prev = _ACTIVE_TIMER
    _ACTIVE_TIMER = timer
    return prev


class StepTimer:
    """Per-execution timing split, recorded as per-step means.

    One compiled execution covers ``steps`` train steps (1, or K under
    ``steps_per_execution``); the split is divided by ``steps`` before
    recording so the distributions are per-step regardless of K. Epoch
    aggregates accumulate alongside for the straggler exchange.
    """

    def __init__(self, registry: Optional[metrics_lib.MetricsRegistry] = None):
        self.registry = registry or metrics_lib.get_registry()
        r = self.registry
        self._count = r.counter("step.count")
        self._total = r.distribution("step.total_s")
        self._data = r.distribution("step.data_wait_s")
        self._dispatch = r.distribution("step.dispatch_s")
        self._device = r.distribution("step.device_block_s")
        # Overlap health of the step schedule: host-side collective wait
        # (instrumented wrappers report it via comm_wait_s; in-program
        # collectives are invisible to the host and land in device_block)
        # and the fraction of execution wall time the device was actually
        # busy — double-buffered input drives this toward 1.0 by taking
        # data_wait out of the denominator's stall share.
        self._comm = r.distribution("step.comm_wait_s")
        self._overlap = r.distribution("step.overlap")
        self.reset_epoch()

    def reset_epoch(self) -> None:
        self.epoch_steps = 0
        self.epoch_total_s = 0.0
        self.epoch_data_wait_s = 0.0
        self.epoch_dispatch_s = 0.0
        self.epoch_device_s = 0.0
        self.epoch_comm_wait_s = 0.0

    def record_execution(self, *, steps: int, data_wait_s: float,
                         dispatch_s: float, device_block_s: float,
                         comm_wait_s: float = 0.0) -> None:
        if steps <= 0:
            return
        total = data_wait_s + dispatch_s + device_block_s
        per = 1.0 / steps
        self._count.inc(steps)
        self._total.observe(total * per)
        self._data.observe(data_wait_s * per)
        self._dispatch.observe(dispatch_s * per)
        self._device.observe(device_block_s * per)
        self._comm.observe(comm_wait_s * per)
        if total > 0:
            self._overlap.observe(device_block_s / total)
        self.epoch_steps += steps
        self.epoch_total_s += total
        self.epoch_data_wait_s += data_wait_s
        self.epoch_dispatch_s += dispatch_s
        self.epoch_device_s += device_block_s
        self.epoch_comm_wait_s += comm_wait_s

    def epoch_mean_step_s(self) -> float:
        if self.epoch_steps == 0:
            return 0.0
        return self.epoch_total_s / self.epoch_steps


def registry_collective_hook(
        registry: Optional[metrics_lib.MetricsRegistry] = None):
    """A collective observe hook (``parallel/collectives.py`` seam) that
    records per-op calls, payload bytes, and host wall time into a
    registry. Trace-time firings (a wrapper traced into a jitted program
    runs once at trace time, not per step) are counted separately so a
    reader never mistakes compile-time activity for steady-state traffic.
    """
    r = registry or metrics_lib.get_registry()

    def hook(op: str, *, phase: str, leaves: int, nbytes: int,
             seconds: Optional[float] = None) -> None:
        r.counter(f"collective.{op}.calls").inc()
        if phase == "trace":
            r.counter(f"collective.{op}.trace_calls").inc()
        if nbytes:
            r.counter(f"collective.{op}.bytes").inc(nbytes)
        if seconds is not None:
            r.distribution(f"collective.{op}.host_seconds").observe(seconds)
            if phase != "trace":
                # Host-visible collective wait, aggregated across ops —
                # the measured sibling of the cost model's comm tail.
                r.distribution("step.comm_wait_s").observe(seconds)

    return hook


class Telemetry(Callback):
    """Arm metrics + collective telemetry + straggler detection for one fit.

    Scoped strictly to the fit span: ``on_train_begin`` resets and enables
    the registry (each span's series starts from a clean slate — sequential
    fits on the shared default registry must not bleed counts into each
    other), installs the collective observe hook and the hot-loop step
    timer; ``on_train_end`` restores every previous state, so sequential
    fits compose. Exports are optional — without paths the callback only
    populates the registry (and the event log, if armed).
    """

    def __init__(self, *,
                 jsonl_path: Optional[str | os.PathLike] = None,
                 prometheus_path: Optional[str | os.PathLike] = None,
                 registry: Optional[metrics_lib.MetricsRegistry] = None,
                 straggler_threshold: float = straggler.DEFAULT_THRESHOLD):
        self.registry = registry or metrics_lib.get_registry()
        self.jsonl_path = jsonl_path
        self.prometheus_path = prometheus_path
        self.straggler_threshold = straggler_threshold
        self.timer: Optional[StepTimer] = None
        self._exporter: Optional[exporters.JsonlExporter] = None
        self._prev_hook = None
        self._prev_timer = None
        self._was_enabled = False
        self._armed = False

    # -- lifecycle -----------------------------------------------------------

    def on_train_begin(self) -> None:
        from tpu_dist.parallel import collectives

        self._was_enabled = self.registry.enabled
        self.registry.reset()
        self.registry.enable()
        self._prev_hook = collectives.install_observe_hook(
            registry_collective_hook(self.registry))
        self.timer = StepTimer(self.registry)
        self._prev_timer = set_active_step_timer(self.timer)
        if self.jsonl_path is not None:
            self._exporter = exporters.JsonlExporter(self.jsonl_path)
        self._armed = True

    def on_train_end(self) -> None:
        if not self._armed:
            return
        from tpu_dist.parallel import collectives

        self._export(kind="final", epoch=None)
        collectives.install_observe_hook(self._prev_hook)
        set_active_step_timer(self._prev_timer)
        if not self._was_enabled:
            self.registry.disable()
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        self._armed = False

    def on_epoch_begin(self, epoch: int) -> None:
        if self.timer is not None:
            self.timer.reset_epoch()

    # -- per-epoch aggregation -----------------------------------------------

    def on_epoch_end(self, epoch: int, logs: dict) -> None:
        if not self._armed:
            return
        import numpy as np

        from tpu_dist.parallel import collectives
        from tpu_dist.resilience import events

        r = self.registry
        timer = self.timer
        epoch_time = float(logs.get("epoch_time", 0.0) or 0.0)
        if "loss" in logs:
            r.gauge("epoch.last_loss").set(float(logs["loss"]))
        r.gauge("epoch.last_time_s").set(epoch_time)
        steps = timer.epoch_steps if timer is not None else 0
        if steps and epoch_time > 0:
            r.gauge("epoch.steps_per_s").set(steps / epoch_time)
        mean_step = timer.epoch_mean_step_s() if timer is not None else 0.0

        # Cross-rank exchange of this epoch's mean step time. Runs through
        # the instrumented host collective, so even a single-process run
        # records collective traffic (and its host wall time) — the demo's
        # non-vacuity check depends on this.
        per_rank = collectives.host_all_gather(np.float32(mean_step))
        per_rank = [float(t) for t in np.asarray(per_rank).reshape(-1)]
        for rank_i, t in enumerate(per_rank):
            r.gauge(f"rank{rank_i}.step_time_s").set(t)

        import jax

        rank = jax.process_index()
        events.maybe_log(
            "step_timing", rank=rank, epoch=epoch, steps=steps,
            mean_step_s=round(mean_step, 6),
            data_wait_s=round(timer.epoch_data_wait_s, 6) if timer else 0.0,
            dispatch_s=round(timer.epoch_dispatch_s, 6) if timer else 0.0,
            device_s=round(timer.epoch_device_s, 6) if timer else 0.0,
            comm_wait_s=round(timer.epoch_comm_wait_s, 6) if timer else 0.0)

        from tpu_dist.cluster import bootstrap

        if bootstrap.is_chief():
            for verdict in straggler.detect_stragglers(
                    per_rank, threshold=self.straggler_threshold):
                r.counter("straggler.flags").inc()
                logger.warning(
                    "straggler: rank %d at %.4fs/step, %.1fx the gang "
                    "median", verdict.rank, verdict.step_s, verdict.ratio)
                events.maybe_log("straggler_detected", epoch=epoch,
                                 **verdict.to_dict())
        self._export(kind="epoch", epoch=epoch)

    def _export(self, *, kind: str, epoch: Optional[int]) -> None:
        snapshot = self.registry.snapshot()
        stamp = {"kind": kind}
        if epoch is not None:
            stamp["epoch"] = epoch
        try:
            if self._exporter is not None:
                self._exporter.write(snapshot, **stamp)
            if self.prometheus_path is not None:
                exporters.write_prometheus_textfile(
                    snapshot, self.prometheus_path)
        except OSError as exc:  # diagnostics must never kill the run
            logger.warning("telemetry export failed: %s", exc)


def maybe_telemetry_from_env() -> Optional[Telemetry]:
    """A :class:`Telemetry` writing under ``$TPU_DIST_OBSERVE_DIR``, or None
    when the variable is unset — the trainer calls this in ``fit`` so a
    Supervisor (or a shell) can instrument any run without code edits."""
    d = os.environ.get(OBSERVE_DIR_ENV)
    if not d:
        return None
    base = Path(d)
    return Telemetry(jsonl_path=base / "metrics.jsonl",
                     prometheus_path=base / "metrics.prom")
