import sys

from tpu_dist.observe.cli import main

sys.exit(main())
