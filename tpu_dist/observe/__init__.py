"""tpu_dist.observe — metrics, collective telemetry, straggler detection.

The observability subsystem the reference stack never had (its surface was
the chief's TensorBoard duty, SURVEY.md §5.1). Four layers, one per module:

* :mod:`~tpu_dist.observe.metrics` — a low-overhead in-process registry
  (counters, gauges, reservoir-sampled distributions with p50/p95/p99);
  free when disabled, host-side only.
* :mod:`~tpu_dist.observe.telemetry` — the :class:`Telemetry` fit callback
  wiring the registry to the trainer's step-phase timers and the collective
  observe-hook seam in ``parallel/collectives.py``; armable via
  ``$TPU_DIST_OBSERVE_DIR`` (the Supervisor does this for chaos workers).
* :mod:`~tpu_dist.observe.straggler` — per-rank step-time comparison on
  the chief (median-multiple threshold) plus a heartbeat monitor; verdicts
  land in the resilience event log as ``straggler_detected``.
* :mod:`~tpu_dist.observe.exporters` — schema-versioned JSONL time-series
  and Prometheus textfiles.

``python -m tpu_dist.observe`` (:mod:`~tpu_dist.observe.cli`) runs the demo
workload instrumented, summarizes/asserts on a series, diffs against a
baseline, and benchmarks telemetry overhead (``BENCH_OBSERVE.json``).

Only the dependency-light metric/exporter/straggler halves import eagerly;
Telemetry and the CLI pull in the training stack lazily via ``__getattr__``
so ``from tpu_dist.observe import metrics`` stays cheap everywhere.
"""

from tpu_dist.observe.exporters import (SCHEMA, JsonlExporter, SchemaError,
                                        read_series,
                                        write_prometheus_textfile)
from tpu_dist.observe.metrics import (MetricsRegistry, disable, enable,
                                      enabled, get_registry, inc,
                                      observe_value, set_gauge)
from tpu_dist.observe.straggler import (HeartbeatMonitor, StragglerVerdict,
                                        detect_stragglers)

__all__ = [
    "SCHEMA", "JsonlExporter", "SchemaError", "read_series",
    "write_prometheus_textfile",
    "MetricsRegistry", "disable", "enable", "enabled", "get_registry",
    "inc", "observe_value", "set_gauge",
    "HeartbeatMonitor", "StragglerVerdict", "detect_stragglers",
    "OBSERVE_DIR_ENV", "StepTimer", "Telemetry", "active_step_timer",
    "maybe_telemetry_from_env",
]

_LAZY = {
    "OBSERVE_DIR_ENV": "tpu_dist.observe.telemetry",
    "StepTimer": "tpu_dist.observe.telemetry",
    "Telemetry": "tpu_dist.observe.telemetry",
    "active_step_timer": "tpu_dist.observe.telemetry",
    "maybe_telemetry_from_env": "tpu_dist.observe.telemetry",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
