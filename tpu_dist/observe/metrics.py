"""MetricsRegistry: counters, gauges, streaming distributions — host-side only.

The reference stack's metric story is whatever the chief writes to
TensorBoard (SURVEY.md §5.1); there is no in-process registry a trainer,
collective wrapper, or chaos harness can record into. This module is that
registry, built for the hot-loop constraints of a dispatch-bound trainer:

* **disabled is free** — every instrument checks one boolean before doing
  any work, so an un-enabled registry costs an attribute read per call and
  production code can leave instrumentation in place unconditionally;
* **eager host code only** — recording is a Python-level side effect; under
  a jit trace it would run once at trace time, not per step (exactly the
  SC103 class shardcheck flags), so call sites live in callbacks, the fit
  loop, and host collectives — never inside a compiled step;
* **bounded memory** — distributions keep exact count/sum/min/max forever
  but sample values into a fixed reservoir (Vitter's algorithm R, seeded so
  runs are reproducible), so p50/p95/p99 stay available over arbitrarily
  long runs without unbounded growth.

Quantiles use linear interpolation over the sorted reservoir (numpy's
default scheme), which makes small-sample quantiles exact — the property
the unit tests pin.
"""

from __future__ import annotations

import random
import threading
from typing import Optional

#: Quantiles every distribution snapshot reports.
SNAPSHOT_QUANTILES = (0.5, 0.95, 0.99)

#: Reservoir size: exact quantiles up to this many observations, uniform
#: subsampling beyond it. 1024 doubles are 8 KiB per distribution.
DEFAULT_RESERVOIR_SIZE = 1024


def quantile(sorted_values: list, q: float) -> float:
    """Linear-interpolation quantile of an ascending-sorted list
    (numpy's default 'linear' method): h = (n-1)q, interpolate between
    floor(h) and ceil(h)."""
    if not sorted_values:
        raise ValueError("quantile of an empty sequence")
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    n = len(sorted_values)
    if n == 1:
        return float(sorted_values[0])
    h = (n - 1) * q
    lo = int(h)
    hi = min(lo + 1, n - 1)
    frac = h - lo
    return float(sorted_values[lo]) * (1.0 - frac) + float(
        sorted_values[hi]) * frac


class Counter:
    """Monotonic count (steps run, collectives fired, faults seen)."""

    __slots__ = ("_registry", "value")

    def __init__(self, registry: "MetricsRegistry"):
        self._registry = registry
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if self._registry.enabled:
            self.value += n


class Gauge:
    """Last-written value (current epoch time, a rank's step duration)."""

    __slots__ = ("_registry", "value")

    def __init__(self, registry: "MetricsRegistry"):
        self._registry = registry
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        if self._registry.enabled:
            self.value = float(v)


class Distribution:
    """Streaming value distribution: exact count/sum/min/max plus
    reservoir-sampled quantiles."""

    __slots__ = ("_registry", "_lock", "_rng", "_reservoir", "_capacity",
                 "count", "sum", "min", "max")

    def __init__(self, registry: "MetricsRegistry",
                 reservoir_size: int = DEFAULT_RESERVOIR_SIZE):
        self._registry = registry
        self._lock = threading.Lock()
        # Seeded per-instrument: reservoir contents are reproducible across
        # runs and never touch jax's RNG or the global `random` state.
        self._rng = random.Random(0xD157)
        self._reservoir: list = []
        self._capacity = reservoir_size
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        if not self._registry.enabled:
            return
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            # Algorithm R: keep each of the first k values, then replace a
            # random slot with probability k/count.
            if len(self._reservoir) < self._capacity:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._capacity:
                    self._reservoir[j] = v

    def quantile(self, q: float) -> float:
        with self._lock:
            values = sorted(self._reservoir)
        return quantile(values, q)

    def snapshot(self) -> dict:
        with self._lock:
            values = sorted(self._reservoir)
        out = {"count": self.count, "sum": self.sum,
               "min": self.min, "max": self.max}
        for q in SNAPSHOT_QUANTILES:
            out[f"p{int(q * 100)}"] = quantile(values, q) if values else None
        return out


class MetricsRegistry:
    """Named instrument namespace with one on/off switch.

    Instruments are created on first use and live for the registry's
    lifetime; a disabled registry still hands out instruments (call sites
    never branch) — they just drop writes.
    """

    def __init__(self, *, enabled: bool = True,
                 reservoir_size: int = DEFAULT_RESERVOIR_SIZE):
        self.enabled = bool(enabled)
        self._reservoir_size = reservoir_size
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._distributions: dict[str, Distribution] = {}

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def _get(self, table: dict, name: str, factory):
        inst = table.get(name)
        if inst is None:
            with self._lock:
                inst = table.setdefault(name, factory())
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, lambda: Counter(self))

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, lambda: Gauge(self))

    def distribution(self, name: str) -> Distribution:
        return self._get(
            self._distributions, name,
            lambda: Distribution(self, self._reservoir_size))

    def reset(self) -> None:
        """Drop every instrument (a fresh run's clean slate)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._distributions.clear()

    def snapshot(self) -> dict:
        """Point-in-time JSON-ready view of every instrument."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            dists = dict(self._distributions)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "distributions": {k: d.snapshot()
                              for k, d in sorted(dists.items())},
        }


#: The process-wide default registry. Starts DISABLED: instrumentation is
#: free until a Telemetry callback (or an explicit enable()) turns it on.
_default = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return _default


def enabled() -> bool:
    """Cheap jit-safe read: is the default registry recording?"""
    return _default.enabled


def enable() -> None:
    _default.enable()


def disable() -> None:
    _default.disable()


# -- eager recording helpers --------------------------------------------------
# One-liners for callback/hook call sites. These are HOST side effects:
# calling them inside a jitted function records once at trace time, not per
# step — shardcheck's SC103 flags exactly that misuse.

def inc(name: str, n: int = 1) -> None:
    _default.counter(name).inc(n)


def observe_value(name: str, v: float) -> None:
    _default.distribution(name).observe(v)


def set_gauge(name: str, v: float) -> None:
    _default.gauge(name).set(v)
