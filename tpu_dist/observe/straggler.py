"""Straggler and heartbeat detection over per-rank step timings.

A synchronous-SPMD gang runs at the speed of its slowest member: one rank
with a throttled chip, a contended host, or a failing NIC drags every
all-reduce. The reference stack had no way to see this — a slow worker
just looked like a slow job. Here the chief aggregates each rank's mean
step duration (gathered through ``collectives.host_all_gather``, see
telemetry.py) and flags ranks whose step time exceeds a multiple of the
gang median. Median — not mean — so a single extreme straggler cannot
mask itself by dragging the baseline up.

Detection is advisory: verdicts are recorded as ``straggler_detected``
events in the resilience ``EventLog`` for the Supervisor's chaos reports;
nothing here kills or restarts a rank.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Optional, Sequence

#: A rank is a straggler when step_s > threshold * median(step_s).
DEFAULT_THRESHOLD = 2.0

#: Absolute floor: below this median step time (seconds), ratios are
#: dominated by scheduler noise and nothing is flagged.
DEFAULT_MIN_STEP_S = 1e-4


@dataclasses.dataclass(frozen=True)
class StragglerVerdict:
    """One flagged rank: its step time, the gang median, and the ratio."""

    rank: int
    step_s: float
    median_s: float
    ratio: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def detect_stragglers(
        per_rank_step_s: Sequence[float],
        *,
        threshold: float = DEFAULT_THRESHOLD,
        min_step_s: float = DEFAULT_MIN_STEP_S,
) -> list[StragglerVerdict]:
    """Flag ranks whose mean step time exceeds ``threshold`` x the gang
    median. A gang of 0 or 1 ranks has no peers to compare against and a
    sub-``min_step_s`` median is all noise — both return no verdicts.
    """
    if threshold <= 1.0:
        raise ValueError(f"threshold must be > 1.0, got {threshold}")
    times = [float(t) for t in per_rank_step_s]
    if len(times) <= 1:
        return []
    median = statistics.median(times)
    if median < min_step_s:
        return []
    out = []
    for rank, t in enumerate(times):
        if t > threshold * median:
            out.append(StragglerVerdict(
                rank=rank, step_s=t, median_s=median, ratio=t / median))
    return out


class HeartbeatMonitor:
    """Last-progress tracker: which ranks have gone silent?

    Complements ratio-based detection — a rank that *stops* reporting has
    no step time to compare. Feed it ``beat(rank)`` whenever a rank's
    timing arrives; ``stale_ranks(timeout_s)`` names the ranks whose last
    beat is older than the timeout (never-beaten known ranks included).
    """

    def __init__(self, num_ranks: int, *, clock=time.monotonic):
        self._clock = clock
        self._last_beat: dict[int, Optional[float]] = {
            r: None for r in range(num_ranks)}
        self._started = self._clock()

    def beat(self, rank: int) -> None:
        self._last_beat[rank] = self._clock()

    def stale_ranks(self, timeout_s: float) -> list[int]:
        now = self._clock()
        stale = []
        for rank in sorted(self._last_beat):
            last = self._last_beat[rank]
            ref = last if last is not None else self._started
            if now - ref > timeout_s:
                stale.append(rank)
        return stale
