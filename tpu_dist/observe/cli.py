"""``python -m tpu_dist.observe`` — demo, summarize, diff, bench.

The CLI mirrors the resilience chaos runner's conventions: machine-first
JSON output, and a hard anti-vacuity stance — a metrics series with no
step timing or no collective traffic FAILS, because an empty series passed
silently is how observability rots.

Subcommands::

    demo        run the built-in workload instrumented; write + validate a
                metrics series (exit 1 if the series is empty or missing
                step/collective metrics)
    summarize   read a series back; print steps/s, step-time percentiles,
                per-collective counts; --require step,collective turns
                missing families into a nonzero exit
    diff        compare two series' summaries; gate steps/s regression
                with --max-regress-pct
    bench       measure telemetry overhead (off vs. on) on the demo
                workload and write BENCH_OBSERVE.json

The demo workload is the resilience demo's synthetic-MNIST CNN
(resilience/entrypoints.py) so chaos and observe exercises stay
comparable.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile
from typing import Optional

#: Schema tag of the bench artifact (BENCH_OBSERVE.json).
BENCH_SCHEMA = "tpu_dist.bench_observe/v1"

#: Metric families --require understands: family -> predicate over the
#: final snapshot's counters.
_FAMILIES = ("step", "collective")


def _final_snapshot(records: list[dict]) -> Optional[dict]:
    """The series' authoritative snapshot: the last ``kind="final"`` record
    if one exists (snapshots are cumulative), else the last record."""
    if not records:
        return None
    for rec in reversed(records):
        if rec.get("kind") == "final":
            return rec.get("metrics")
    return records[-1].get("metrics")


def _family_present(snapshot: dict, family: str) -> bool:
    counters = snapshot.get("counters", {})
    if family == "step":
        return counters.get("step.count", 0) > 0
    if family == "collective":
        return any(name.startswith("collective.") and name.endswith(".calls")
                   and value > 0 for name, value in counters.items())
    raise ValueError(
        f"unknown metric family {family!r} (known: {list(_FAMILIES)})")


def summarize_series(records: list[dict]) -> dict:
    """Reduce a JSONL series to the numbers a regression check compares."""
    snapshot = _final_snapshot(records) or {}
    counters = snapshot.get("counters", {})
    gauges = snapshot.get("gauges", {})
    dists = snapshot.get("distributions", {})
    step = dists.get("step.total_s", {})
    collectives = {
        name[len("collective."):-len(".calls")]: value
        for name, value in sorted(counters.items())
        if name.startswith("collective.") and name.endswith(".calls")}
    return {
        "records": len(records),
        "steps": counters.get("step.count", 0),
        "steps_per_s": gauges.get("epoch.steps_per_s"),
        "step_total_s": {q: step.get(q) for q in ("p50", "p95", "p99")},
        "step_phase_p50_s": {
            phase: (dists.get(f"step.{phase}_s", {}) or {}).get("p50")
            for phase in ("data_wait", "dispatch", "device_block")},
        "collective_calls": collectives,
        "straggler_flags": counters.get("straggler.flags", 0),
    }


def _check_required(snapshot: Optional[dict], families: list[str]) -> list[str]:
    """Names of required families that are missing/empty (snapshot None =
    all missing)."""
    if snapshot is None:
        return list(families)
    return [f for f in families if not _family_present(snapshot, f)]


def _parse_require(spec: Optional[str]) -> list[str]:
    if not spec:
        return []
    families = [f.strip() for f in spec.split(",") if f.strip()]
    for f in families:
        if f not in _FAMILIES:
            raise SystemExit(
                f"error: unknown --require family {f!r} "
                f"(known: {','.join(_FAMILIES)})")
    return families


# -- demo workload ------------------------------------------------------------

def _run_demo(observe_dir: Optional[pathlib.Path], *, epochs: int,
              steps_per_epoch: int, batch: int, telemetry: bool,
              model=None):
    """One in-process instrumented demo run; returns (history, model).

    ``model=None`` builds a fresh CNN; passing the previous run's model
    back in reuses its compiled step (the bench uses this so the off/on
    comparison measures telemetry, not recompilation).
    """
    from tpu_dist.models.cnn import build_and_compile_cnn_model
    from tpu_dist.observe.telemetry import Telemetry
    from tpu_dist.resilience.entrypoints import demo_dataset

    ds = demo_dataset(n=batch * steps_per_epoch, batch=batch)
    if model is None:
        model = build_and_compile_cnn_model(learning_rate=0.01)
    callbacks = []
    if telemetry:
        callbacks.append(Telemetry(
            jsonl_path=observe_dir / "metrics.jsonl" if observe_dir else None,
            prometheus_path=(observe_dir / "metrics.prom"
                             if observe_dir else None)))
    history = model.fit(ds, epochs=epochs, steps_per_epoch=steps_per_epoch,
                        verbose=0, callbacks=callbacks)
    return history, model


def _steps_per_s(history, steps_per_epoch: int) -> Optional[float]:
    """Fastest post-compile epoch's throughput: epoch 0 carries trace+compile
    and min-time is robust against host noise in the remaining epochs."""
    times = [float(t) for t in history.history.get("epoch_time", [])[1:]]
    if not times:
        return None
    return steps_per_epoch / min(times)


def _add_demo_knobs(p: argparse.ArgumentParser, *, epochs: int,
                    steps: int, batch: int) -> None:
    p.add_argument("--epochs", type=int, default=epochs)
    p.add_argument("--steps-per-epoch", type=int, default=steps)
    p.add_argument("--batch", type=int, default=batch)


# -- subcommands --------------------------------------------------------------

def cmd_demo(args) -> int:
    out_dir = pathlib.Path(args.out or tempfile.mkdtemp(
        prefix="tpu-dist-observe-"))
    out_dir.mkdir(parents=True, exist_ok=True)
    print(f"observe demo: writing to {out_dir}", file=sys.stderr)
    _run_demo(out_dir, epochs=args.epochs,
              steps_per_epoch=args.steps_per_epoch, batch=args.batch,
              telemetry=True)

    from tpu_dist.observe.exporters import read_series

    records = read_series(out_dir / "metrics.jsonl")
    summary = summarize_series(records)
    missing = _check_required(_final_snapshot(records),
                              list(_FAMILIES))  # demo always requires both
    payload = {"metrics_path": str(out_dir / "metrics.jsonl"),
               "prometheus_path": str(out_dir / "metrics.prom"),
               "summary": summary, "missing": missing,
               "ok": not records == [] and not missing}
    print(json.dumps(payload, indent=2))
    if not records:
        print("error: demo produced an EMPTY metrics series — vacuous run",
              file=sys.stderr)
        return 1
    if missing:
        print(f"error: demo series is missing metric families: {missing}",
              file=sys.stderr)
        return 1
    return 0


def cmd_summarize(args) -> int:
    from tpu_dist.observe.exporters import read_series

    try:
        records = read_series(args.series)
    except FileNotFoundError:
        print(f"error: no series at {args.series}", file=sys.stderr)
        return 1
    summary = summarize_series(records)
    required = _parse_require(args.require)
    missing = _check_required(_final_snapshot(records), required)
    if args.json:
        print(json.dumps({"summary": summary, "missing": missing,
                          "ok": not missing and bool(records)}, indent=2))
    else:
        print(f"records:          {summary['records']}")
        print(f"steps:            {summary['steps']}")
        sps = summary["steps_per_s"]
        print(f"steps/s (epoch):  "
              f"{sps:.3f}" if sps is not None else "steps/s (epoch):  n/a")
        st = summary["step_total_s"]
        if st.get("p50") is not None:
            print("step time p50/p95/p99: "
                  + " / ".join(f"{st[q] * 1e3:.2f}ms"
                               for q in ("p50", "p95", "p99")))
        for op, calls in summary["collective_calls"].items():
            print(f"collective {op}: {calls} calls")
        if summary["straggler_flags"]:
            print(f"straggler flags:  {summary['straggler_flags']}")
    if not records:
        print("error: series is empty", file=sys.stderr)
        return 1
    if missing:
        print(f"error: required metric families missing: {missing}",
              file=sys.stderr)
        return 1
    return 0


def cmd_diff(args) -> int:
    from tpu_dist.observe.exporters import read_series

    base = summarize_series(read_series(args.baseline))
    curr = summarize_series(read_series(args.current))
    result = {"baseline": base, "current": curr}
    regressions = []
    if base["steps_per_s"] and curr["steps_per_s"]:
        delta_pct = 100.0 * (1.0 - curr["steps_per_s"] / base["steps_per_s"])
        result["steps_per_s_regress_pct"] = round(delta_pct, 3)
        if delta_pct > args.max_regress_pct:
            regressions.append(
                f"steps/s regressed {delta_pct:.1f}% "
                f"(limit {args.max_regress_pct}%)")
    for q in ("p50", "p95"):
        b, c = base["step_total_s"].get(q), curr["step_total_s"].get(q)
        if b and c:
            result[f"step_{q}_delta_pct"] = round(100.0 * (c / b - 1.0), 3)
    result["regressions"] = regressions
    result["ok"] = not regressions
    print(json.dumps(result, indent=2))
    return 0 if not regressions else 1


def cmd_bench(args) -> int:
    workdir = pathlib.Path(args.workdir or tempfile.mkdtemp(
        prefix="tpu-dist-observe-bench-"))
    workdir.mkdir(parents=True, exist_ok=True)
    knobs = dict(epochs=args.epochs, steps_per_epoch=args.steps_per_epoch,
                 batch=args.batch)
    # Off / on / off on ONE model (shared compiled step): the second off
    # run re-measures the uninstrumented loop after any allocator/cache
    # warm-up the on run benefited from, and the better of the two off
    # runs is the baseline — bias, if any, goes AGAINST telemetry.
    print("bench: telemetry off (run 1)...", file=sys.stderr)
    hist_off1, model = _run_demo(None, telemetry=False, **knobs)
    print("bench: telemetry on...", file=sys.stderr)
    on_dir = workdir / "on"
    hist_on, model = _run_demo(on_dir, telemetry=True, model=model, **knobs)
    print("bench: telemetry off (run 2)...", file=sys.stderr)
    hist_off2, model = _run_demo(None, telemetry=False, model=model, **knobs)

    offs = [s for s in (_steps_per_s(hist_off1, args.steps_per_epoch),
                        _steps_per_s(hist_off2, args.steps_per_epoch))
            if s is not None]
    on = _steps_per_s(hist_on, args.steps_per_epoch)
    if not offs or on is None:
        print("error: bench runs produced no timeable epochs (need "
              "epochs >= 2)", file=sys.stderr)
        return 1
    off = max(offs)
    overhead_pct = 100.0 * (1.0 - on / off)
    report = {
        "schema": BENCH_SCHEMA,
        "workload": {"model": "demo_cnn", **knobs},
        "telemetry_off_steps_per_s": round(off, 3),
        "telemetry_on_steps_per_s": round(on, 3),
        "overhead_pct": round(overhead_pct, 3),
        "max_overhead_pct": args.max_overhead_pct,
        "metrics_path": str(on_dir / "metrics.jsonl"),
        "ok": overhead_pct < args.max_overhead_pct,
    }
    out = json.dumps(report, indent=2)
    print(out)
    if args.out:
        pathlib.Path(args.out).write_text(out + "\n")
    if not report["ok"]:
        print(f"error: telemetry overhead {overhead_pct:.2f}% exceeds "
              f"{args.max_overhead_pct}%", file=sys.stderr)
        return 1
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.observe",
        description="Observability runner: instrumented demo run, series "
                    "summarize/diff, telemetry-overhead benchmark.")
    sub = p.add_subparsers(dest="command", required=True)

    demo = sub.add_parser("demo", help="instrumented demo run + validation")
    _add_demo_knobs(demo, epochs=3, steps=4, batch=32)
    demo.add_argument("--out", default=None,
                      help="directory for metrics.jsonl/metrics.prom "
                           "(default: fresh temp dir)")
    demo.set_defaults(fn=cmd_demo)

    summ = sub.add_parser("summarize", help="summarize a metrics series")
    summ.add_argument("series", help="path to a metrics.jsonl series")
    summ.add_argument("--require", default=None, metavar="FAMILIES",
                      help="comma list of families that must be non-empty "
                           f"({','.join(_FAMILIES)}); missing = exit 1")
    summ.add_argument("--json", action="store_true")
    summ.set_defaults(fn=cmd_summarize)

    diff = sub.add_parser("diff", help="compare two series (regression gate)")
    diff.add_argument("baseline")
    diff.add_argument("current")
    diff.add_argument("--max-regress-pct", type=float, default=10.0,
                      help="max allowed steps/s regression (default 10)")
    diff.set_defaults(fn=cmd_diff)

    bench = sub.add_parser(
        "bench", help="measure telemetry overhead, write BENCH_OBSERVE.json")
    _add_demo_knobs(bench, epochs=4, steps=4, batch=256)
    bench.add_argument("--workdir", default=None)
    bench.add_argument("--out", default=None,
                       help="also write the JSON report here "
                            "(e.g. BENCH_OBSERVE.json)")
    bench.add_argument("--max-overhead-pct", type=float, default=5.0)
    bench.set_defaults(fn=cmd_bench)
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
