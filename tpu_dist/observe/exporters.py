"""Exporters: schema-versioned JSONL time-series and Prometheus textfiles.

Two sinks, two audiences:

* **JSONL** for machines and the ``python -m tpu_dist.observe`` CLI —
  one self-describing record per snapshot, append-only so a crashed run
  keeps everything written before the crash (the same line-atomicity
  contract as ``resilience.events.EventLog``). ``read_series`` tolerates
  a torn final line by default, because that is exactly what a
  kill-at-step-N chaos run produces.
* **Prometheus textfile** for humans with a node_exporter — the standard
  ``textfile collector`` handoff: write to a tmp file, ``os.replace``
  into place so the scraper never reads a half-written file.

Schema versioning: every JSONL record carries ``"schema":
"tpu_dist.observe/v1"``. Readers reject records from a different major
schema rather than silently misparsing them.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import IO, Optional, Union

from tpu_dist.observe import metrics as metrics_lib

#: Version tag stamped into every JSONL record.
SCHEMA = "tpu_dist.observe/v1"


class SchemaError(ValueError):
    """A series record is missing or carries an incompatible schema tag."""


class JsonlExporter:
    """Append metric snapshots to a JSONL file, one record per write."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh: Optional[IO[str]] = open(self.path, "a", encoding="utf-8")

    def write(self, snapshot: dict, **stamp) -> dict:
        """Write one record: ``{"schema", "ts", **stamp, "metrics"}``.
        Extra stamp fields (epoch=, rank=, kind=) label the record."""
        if self._fh is None:
            raise RuntimeError(f"exporter for {self.path} is closed")
        record = {"schema": SCHEMA, "ts": time.time(), **stamp,
                  "metrics": snapshot}
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()
        return record

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "JsonlExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_series(path: Union[str, Path], *, strict: bool = False) -> list[dict]:
    """Read every record of a JSONL series back, schema-checked.

    By default a torn/unparsable line (the tail a killed writer leaves)
    is skipped; ``strict=True`` raises on it instead. A record whose
    schema tag is missing or from a different series format always
    raises ``SchemaError`` — that is corruption, not a torn write.
    """
    records = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                if strict:
                    raise
                continue
            tag = record.get("schema") if isinstance(record, dict) else None
            if tag != SCHEMA:
                raise SchemaError(
                    f"{path}:{lineno}: expected schema {SCHEMA!r}, "
                    f"got {tag!r}")
            records.append(record)
    return records


def _prom_name(name: str) -> str:
    """Sanitize a dotted metric name into the Prometheus grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() or ch == "_":
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out)
    if not s or not (s[0].isalpha() or s[0] == "_"):
        s = "_" + s
    return "tpu_dist_" + s


def write_prometheus_textfile(snapshot: dict,
                              path: Union[str, Path]) -> None:
    """Render a registry snapshot as a Prometheus textfile and atomically
    replace ``path`` (tmp + ``os.replace``), so a concurrent textfile
    collector never scrapes a partial file."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    lines = []
    for name, value in snapshot.get("counters", {}).items():
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {value}")
    for name, value in snapshot.get("gauges", {}).items():
        if value is None:
            continue
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {value}")
    for name, stats in snapshot.get("distributions", {}).items():
        pname = _prom_name(name)
        # Prometheus has no native distribution type for textfiles;
        # export as a summary (quantile labels) plus _count/_sum.
        lines.append(f"# TYPE {pname} summary")
        # Summary quantile labels, one per registry snapshot quantile —
        # derived from metrics.SNAPSHOT_QUANTILES so a new quantile there
        # shows up here without a second edit (the snapshot's flattened
        # pNN keys are the JSONL schema and stay unchanged).
        for q in metrics_lib.SNAPSHOT_QUANTILES:
            v = stats.get(f"p{int(q * 100)}")
            if v is not None:
                lines.append(f'{pname}{{quantile="{q}"}} {v}')
        lines.append(f"{pname}_count {stats.get('count', 0)}")
        lines.append(f"{pname}_sum {stats.get('sum', 0.0)}")
    body = "\n".join(lines) + "\n"
    tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
    tmp.write_text(body, encoding="utf-8")
    os.replace(tmp, path)
