"""Utilities: progress bar, profiling, structured logging."""

from tpu_dist.utils import profiler
from tpu_dist.utils.progbar import ProgressBar

__all__ = ["ProgressBar", "profiler"]
