"""Profiling hooks: TensorBoard-compatible traces, chief-only by default.

The reference's observability surface is the chief's TensorBoard duty
(README.md:51; SURVEY.md §5.1) — profiling was the era's Keras progbar timing
plus an uninvoked TF profiler. TPU-native: ``jax.profiler`` writes XLA/TPU
traces (HLO timelines, ICI collective activity) viewable in TensorBoard or
Perfetto; :func:`trace` wraps a fit/eval span, :func:`step_annotation` marks
step boundaries so the trace viewer aligns host dispatch with device work.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

logger = logging.getLogger("tpu_dist.profiler")

#: True while a trace span is open in this process — lets hot loops skip
#: annotation overhead entirely when nothing is recording.
_ACTIVE = False


def _observe_registry():
    """The tpu_dist.observe default registry, or None when the observe
    package is unavailable/unloadable — profiling must work without it."""
    try:
        from tpu_dist.observe import metrics

        return metrics.get_registry()
    except Exception:  # noqa: BLE001 - diagnostics only
        return None


def is_active() -> bool:
    return _ACTIVE


@contextlib.contextmanager
def trace(logdir: str | os.PathLike, *, chief_only: bool = True) -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed span.

    ``chief_only`` matches the reference's "chief generates TensorBoard"
    division of labor (README.md:51): non-chief processes run the body
    untraced.
    """
    import jax

    from tpu_dist.cluster import bootstrap

    if chief_only and not bootstrap.is_chief():
        yield
        return
    global _ACTIVE
    logdir = str(logdir)
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    _ACTIVE = True
    reg = _observe_registry()
    if reg is not None and reg.enabled:
        reg.counter("profiler.traces").inc()
    logger.info("profiler trace started -> %s", logdir)
    try:
        yield
    finally:
        _ACTIVE = False
        jax.profiler.stop_trace()
        logger.info("profiler trace written -> %s", logdir)


def step_annotation(step: int):
    """Context manager annotating one train step in the trace timeline.

    Free when no trace is active (returns a null context)."""
    if not _ACTIVE:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named trace span (host-side), e.g. around input pipeline sections.

    Doubles as a metric emitter: when the tpu_dist.observe registry is
    enabled, the span's wall time is recorded as the ``span.<name>.s``
    distribution — so an annotated section shows up in metrics exports
    even when no profiler trace is being captured."""
    import jax

    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        reg = _observe_registry()
        if reg is not None and reg.enabled:
            reg.distribution(f"span.{name}.s").observe(
                time.perf_counter() - t0)
