"""Keras-style epoch progress bar (chief-only; SURVEY.md §5.5).

Mirrors the reference's verbose-fit affordance: per-epoch ``N/N`` progress with
step time and loss (the output surface of tf_dist_example.py:59's fit run).
Throttled so display never bounds step dispatch.
"""

from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, total: int, *, enabled: bool = True, width: int = 24,
                 min_interval_s: float = 0.1):
        self.total = total
        self.enabled = enabled
        self.width = width
        self.min_interval = min_interval_s
        self._start = time.perf_counter()
        self._last_render = 0.0

    def update(self, step: int, **values) -> None:
        if not self.enabled:
            return
        now = time.perf_counter()
        if step < self.total and now - self._last_render < self.min_interval:
            return
        self._last_render = now
        frac = step / max(self.total, 1)
        filled = int(frac * self.width)
        bar = "=" * filled + (">" if filled < self.width else "")
        bar = bar.ljust(self.width, ".")
        ms = 1000.0 * (now - self._start) / max(step, 1)
        vals = " - ".join(f"{k}: {v:.4f}" for k, v in values.items())
        sys.stdout.write(f"\r{step}/{self.total} [{bar}] - {ms:.0f}ms/step - {vals}")
        sys.stdout.flush()

    def finish(self, logs: dict) -> None:
        if not self.enabled:
            return
        elapsed = time.perf_counter() - self._start
        ms = 1000.0 * elapsed / max(self.total, 1)
        vals = " - ".join(
            f"{k}: {v:.4f}" for k, v in logs.items() if isinstance(v, float))
        sys.stdout.write(
            f"\r{self.total}/{self.total} - {elapsed:.1f}s - {ms:.0f}ms/step - {vals}\n")
        sys.stdout.flush()
