"""Cluster configuration: TF_CONFIG-shaped JSON -> cluster spec + task identity.

The reference's entire cluster-config surface is the ``TF_CONFIG`` environment
variable (reference: tf_dist_example.py:6-10, README.md:36-59, 156-162): a JSON
object with

* ``cluster``: map of role -> list of ``host:port`` strings. Roles the reference
  documents: ``chief``, ``worker``, ``ps``, ``evaluator`` (README.md:44-57).
* ``task``: ``{"type": <role>, "index": <0-based int>}`` identifying this process
  (README.md:59: the ``cluster`` map must be identical on every node; ``task``
  differs per node and must name an entry of the map).

This module parses that same JSON shape (drop-in familiarity) into an immutable
:class:`ClusterConfig` which the TPU-native bootstrap (``tpu_dist.cluster.bootstrap``)
maps onto ``jax.distributed.initialize`` — the JAX coordination service replaces the
reference's per-process gRPC servers (TF ``TFConfigClusterResolver`` +
``ServerDef``/``GrpcServer`` bring-up, SURVEY.md D1/D3/D10).

Chief semantics follow README.md:51: an explicit ``chief`` task if declared,
otherwise worker 0 acts as chief (checkpointing, TensorBoard, etc.).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Mapping, Sequence

TF_CONFIG_ENV = "TF_CONFIG"

#: Roles the reference's TF_CONFIG documents (README.md:44-57), in the canonical
#: global-ordering used to assign process ids: chief first (it is the coordinator
#: and checkpoint writer), then workers, then parameter servers, then evaluators.
KNOWN_ROLES = ("chief", "worker", "ps", "evaluator")

_ADDR_RE = re.compile(r"^(?P<host>[^:]+|\[[0-9a-fA-F:]+\]):(?P<port>\d{1,5})$")


class ClusterConfigError(ValueError):
    """Raised when a TF_CONFIG-shaped payload is malformed or inconsistent."""


@dataclasses.dataclass(frozen=True)
class TaskInfo:
    """This process's role and 0-based index within that role."""

    type: str
    index: int

    def __post_init__(self) -> None:
        if self.index < 0:
            raise ClusterConfigError(f"task index must be >= 0, got {self.index}")


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """Role -> ordered list of ``host:port`` addresses, identical on every node."""

    jobs: Mapping[str, tuple[str, ...]]

    def __post_init__(self) -> None:
        frozen = {}
        for role, addrs in dict(self.jobs).items():
            if isinstance(addrs, str):
                raise ClusterConfigError(
                    f"cluster role {role!r} must map to a list of addresses, "
                    f"got a bare string {addrs!r}"
                )
            addr_tuple = tuple(addrs)
            for a in addr_tuple:
                if not isinstance(a, str) or not _ADDR_RE.match(a):
                    raise ClusterConfigError(
                        f"cluster role {role!r} has malformed address {a!r}; "
                        "expected 'host:port'"
                    )
            frozen[role] = addr_tuple
        object.__setattr__(self, "jobs", frozen)

    @property
    def roles(self) -> tuple[str, ...]:
        """Roles in canonical order (known roles first, then extras sorted)."""
        known = [r for r in KNOWN_ROLES if r in self.jobs]
        extra = sorted(r for r in self.jobs if r not in KNOWN_ROLES)
        return tuple(known + extra)

    def num_tasks(self, role: str) -> int:
        return len(self.jobs.get(role, ()))

    @property
    def num_processes(self) -> int:
        return sum(len(a) for a in self.jobs.values())

    def task_address(self, role: str, index: int) -> str:
        try:
            return self.jobs[role][index]
        except (KeyError, IndexError):
            raise ClusterConfigError(
                f"task ({role!r}, {index}) is not an entry of the cluster spec "
                f"{dict(self.jobs)!r}"
            ) from None

    def global_index(self, role: str, index: int) -> int:
        """Flat 0-based process id: roles in canonical order, index within role.

        With no explicit chief, worker 0 gets global index 0 — matching the
        reference's "worker 0 defaults to chief" rule (README.md:51) and JAX's
        "process 0 is special" convention.
        """
        self.task_address(role, index)  # validates membership
        offset = 0
        for r in self.roles:
            if r == role:
                return offset + index
            offset += self.num_tasks(r)
        raise AssertionError("unreachable")

    def all_addresses(self) -> tuple[str, ...]:
        return tuple(
            addr for role in self.roles for addr in self.jobs.get(role, ())
        )


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Parsed cluster spec + this process's task identity."""

    cluster: ClusterSpec
    task: TaskInfo

    def __post_init__(self) -> None:
        # Task must name an entry of the cluster map (README.md:59).
        self.cluster.task_address(self.task.type, self.task.index)

    # -- identity ------------------------------------------------------------

    @property
    def is_chief(self) -> bool:
        """Chief = explicit 'chief' task, else worker 0 (README.md:51)."""
        if "chief" in self.cluster.jobs:
            return self.task.type == "chief" and self.task.index == 0
        return self.task.type == "worker" and self.task.index == 0

    @property
    def process_id(self) -> int:
        return self.cluster.global_index(self.task.type, self.task.index)

    @property
    def num_processes(self) -> int:
        return self.cluster.num_processes

    @property
    def task_address(self) -> str:
        return self.cluster.task_address(self.task.type, self.task.index)

    @property
    def coordinator_address(self) -> str:
        """Address of global process 0 — the JAX coordination-service endpoint.

        The reference had every process run a gRPC server and mesh-connect
        (README.md:65); JAX instead has every process dial process 0. The
        chief's declared ``host:port`` is used verbatim — no TF gRPC servers
        exist in this framework, so the TF_CONFIG ports are ours to bind.
        """
        first_role = self.cluster.roles[0]
        return self.cluster.task_address(first_role, 0)

    # -- parsing -------------------------------------------------------------

    @classmethod
    def from_json(cls, payload: str | Mapping) -> "ClusterConfig":
        if isinstance(payload, str):
            try:
                payload = json.loads(payload)
            except json.JSONDecodeError as e:
                raise ClusterConfigError(f"TF_CONFIG is not valid JSON: {e}") from e
        if not isinstance(payload, Mapping):
            raise ClusterConfigError(
                f"TF_CONFIG must be a JSON object, got {type(payload).__name__}"
            )
        cluster = payload.get("cluster")
        task = payload.get("task")
        if cluster is None:
            raise ClusterConfigError("TF_CONFIG missing required 'cluster' key")
        if task is None:
            raise ClusterConfigError("TF_CONFIG missing required 'task' key")
        if not isinstance(task, Mapping) or "type" not in task or "index" not in task:
            raise ClusterConfigError(
                "TF_CONFIG 'task' must be an object with 'type' and 'index'"
            )
        return cls(
            cluster=ClusterSpec(jobs=dict(cluster)),
            task=TaskInfo(type=str(task["type"]), index=int(task["index"])),
        )

    @classmethod
    def from_env(cls, environ: Mapping[str, str] | None = None) -> "ClusterConfig | None":
        """Parse TF_CONFIG from the environment; None when unset/empty.

        Mirrors TF's resolver behavior of treating an absent/empty TF_CONFIG as
        "no cluster" — the single-worker degradation path (README.md:34).
        """
        environ = os.environ if environ is None else environ
        raw = environ.get(TF_CONFIG_ENV, "").strip()
        if not raw:
            return None
        return cls.from_json(raw)


def make_local_cluster(num_workers: int, base_port: int = 23456,
                       host: str = "127.0.0.1") -> list[dict]:
    """Synthesize per-worker TF_CONFIG dicts for an N-process loopback cluster.

    The analog of TF's ``multi_worker_test_base`` localhost cluster fabrication
    (SURVEY.md §4) — used by the multi-process test harness and by local launch
    scripts.
    """
    if num_workers < 1:
        raise ClusterConfigError("num_workers must be >= 1")
    workers = [f"{host}:{base_port + i}" for i in range(num_workers)]
    return [
        {"cluster": {"worker": workers}, "task": {"type": "worker", "index": i}}
        for i in range(num_workers)
    ]
