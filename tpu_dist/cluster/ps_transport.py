"""Host-side parameter-server transport: atomic file protocol, no sockets.

The PS execution model (parallel/ps_strategy.py) needs exactly three wire
primitives between one server process and N worker processes on a shared
filesystem:

* the server **publishes** a versioned parameter snapshot workers can read
  at any moment without tearing;
* each worker **pushes** gradient packets the server discovers and applies
  in arrival order;
* both sides exchange small **control** facts (per-rank applied counts for
  the staleness gate, heartbeats, a STOP marker, DONE markers).

All three reuse the one durability idiom the rest of the host runtime is
built on (cluster/bootstrap.py, training/checkpoint.py): write to a
pid-suffixed temp name in the same directory, then ``os.replace`` — readers
see either the old complete file or the new complete file, never a torn
one. JSON carries control facts (``bootstrap._atomic_write_json`` /
``_read_json``, torn-read tolerant by construction); ``npz`` carries
arrays, with the packet's metadata embedded IN the npz (one file per push —
a sidecar json could land before or after its arrays and reintroduce the
torn-read window the idiom exists to close).

Nothing here touches jax: this module is importable by the server loop, a
worker's hot loop, tests, and the chaos runner alike, and stays inside the
host-runtime concurrency rules (no threads; the single writer per file
class is the server for params/control, rank r for its own grads/marks).
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Optional

import numpy as np

from tpu_dist.cluster.bootstrap import _atomic_write_json, _read_json

#: Environment knobs (the Supervisor/chaos runner launch one argv for every
#: role and differentiate through these — same convention as entrypoints).
PS_DIR_ENV = "TPU_DIST_PS_DIR"
PS_ROLE_ENV = "TPU_DIST_PS_ROLE"            # "server" | "worker"
PS_RANK_ENV = "TPU_DIST_PS_RANK"            # worker rank (server has none)
PS_WORLD_ENV = "TPU_DIST_PS_WORLD"          # number of worker ranks
PS_STALENESS_ENV = "TPU_DIST_PS_STALENESS"  # bounded-staleness window
PS_SYNC_ENV = "TPU_DIST_PS_SYNC"            # "1" = gang-synchronous control
PS_PULL_TIMEOUT_ENV = "TPU_DIST_PS_PULL_TIMEOUT"  # worker pull deadline (s)

#: Default bounded-staleness window: a worker may have at most this many of
#: its own pushes still unapplied when it pulls. Small by design — the
#: convergence contract is *bounded* staleness, not eventual consistency.
DEFAULT_STALENESS = 4

_META_KEY = "__ps_meta__"
_MANIFEST = "PUBLISHED.json"
_STOP = "STOP.json"


def _atomic_write_npz(path: pathlib.Path, arrays: dict) -> None:
    tmp = path.parent / f".{path.name}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _load_npz(path: pathlib.Path) -> Optional[dict]:
    """All arrays of ``path``, or None when the file is gone/unreadable —
    publishes are atomic, so unreadable means racing a GC unlink, and the
    caller re-resolves from the manifest."""
    import zipfile

    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except (OSError, ValueError, zipfile.BadZipFile):
        return None


class PSDir:
    """One PS session's on-disk layout under ``root``::

        params/params-<version>.npz     server-published snapshots
        params/PUBLISHED.json           manifest: version, file, applied
                                        counts per rank, leaf checksums
        grads/g-r<rank>-<seq>.npz       worker-pushed gradient packets
        control/hb-rank<r>.json         worker heartbeats (one per step)
        control/done-rank<r>.json       worker completion marks
        control/STOP.json               server's budget-reached stop order
        apply_log.jsonl                 server's apply-order log
    """

    def __init__(self, root):
        self.root = pathlib.Path(root)
        self.params = self.root / "params"
        self.grads = self.root / "grads"
        self.control = self.root / "control"
        self.apply_log = self.root / "apply_log.jsonl"

    def ensure(self) -> "PSDir":
        for d in (self.params, self.grads, self.control):
            d.mkdir(parents=True, exist_ok=True)
        return self

    # -- server: publish / discover -----------------------------------------

    def publish_params(self, arrays: dict, *, version: int,
                       applied: dict, checksums: dict,
                       extra: Optional[dict] = None) -> None:
        """Publish snapshot ``version``: arrays first, then the manifest
        that names them — a reader following the manifest always finds a
        complete npz. Keeps the last two snapshots so a reader holding the
        previous manifest never loses a race with GC."""
        fname = f"params-{int(version)}.npz"
        _atomic_write_npz(self.params / fname, arrays)
        manifest = {
            "version": int(version),
            "file": fname,
            "applied": {str(r): int(n) for r, n in applied.items()},
            "checksums": {k: int(v) for k, v in checksums.items()},
            "time": time.time(),
        }
        if extra:
            manifest.update(extra)
        _atomic_write_json(self.params / _MANIFEST, manifest)
        for old in self.params.glob("params-*.npz"):
            try:
                v = int(old.stem.split("-", 1)[1])
            except ValueError:
                continue
            if v < version - 1:
                try:
                    old.unlink()
                except OSError:
                    pass

    def read_manifest(self) -> Optional[dict]:
        return _read_json(self.params / _MANIFEST)

    def load_published(self) -> Optional[tuple]:
        """(manifest, arrays) of the newest readable snapshot, or None
        before the first publish. Re-resolves once if the npz was GC'd
        between manifest read and array read."""
        for _ in range(2):
            manifest = self.read_manifest()
            if manifest is None:
                return None
            arrays = _load_npz(self.params / manifest["file"])
            if arrays is not None:
                return manifest, arrays
        return None

    # -- worker: push / heartbeat / done -------------------------------------

    def push_grad(self, arrays: dict, *, rank: int, seq: int,
                  meta: dict) -> pathlib.Path:
        """One gradient packet; ``meta`` (rank, worker seq, base version,
        loss) rides inside the npz so packet and provenance are one atomic
        unit."""
        payload = dict(arrays)
        payload[_META_KEY] = np.frombuffer(
            json.dumps({"rank": int(rank), "seq": int(seq), **meta}).encode(
                "utf-8"), dtype=np.uint8).copy()
        path = self.grads / f"g-r{int(rank)}-{int(seq):08d}.npz"
        _atomic_write_npz(path, payload)
        return path

    def heartbeat(self, rank: int, *, step: int) -> None:
        _atomic_write_json(self.control / f"hb-rank{int(rank)}.json",
                           {"step": int(step), "time": time.time()})

    def mark_done(self, rank: int, *, steps: int) -> None:
        _atomic_write_json(self.control / f"done-rank{int(rank)}.json",
                           {"steps": int(steps), "time": time.time()})

    def done_ranks(self) -> set:
        out = set()
        for p in self.control.glob("done-rank*.json"):
            try:
                out.add(int(p.stem[len("done-rank"):]))
            except ValueError:
                continue
        return out

    def heartbeat_age_s(self, rank: int) -> Optional[float]:
        rec = _read_json(self.control / f"hb-rank{int(rank)}.json")
        if rec is None:
            return None
        return max(0.0, time.time() - float(rec.get("time", 0.0)))

    # -- server: gradient discovery ------------------------------------------

    def scan_grads(self, *, seen: set) -> list:
        """Unconsumed packet paths in arrival order. ``os.replace`` stamps
        the destination mtime at publish, so (mtime, name) is the honest
        arrival order; the name breaks exact ties deterministically."""
        entries = []
        try:
            with os.scandir(self.grads) as it:
                for e in it:
                    if (e.name.startswith("g-r") and e.name.endswith(".npz")
                            and e.name not in seen):
                        try:
                            entries.append((e.stat().st_mtime_ns, e.name))
                        except OSError:
                            continue
        except OSError:
            return []
        entries.sort()
        return [self.grads / name for _, name in entries]

    @staticmethod
    def load_grad(path: pathlib.Path) -> Optional[tuple]:
        """(meta, arrays) of one packet, or None when unreadable."""
        arrays = _load_npz(path)
        if arrays is None or _META_KEY not in arrays:
            return None
        meta = json.loads(bytes(arrays.pop(_META_KEY)).decode("utf-8"))
        return meta, arrays

    # -- control --------------------------------------------------------------

    def write_stop(self, *, reason: str, applies: int) -> None:
        _atomic_write_json(self.control / _STOP,
                           {"reason": reason, "applies": int(applies),
                            "time": time.time()})

    def stop_requested(self) -> Optional[dict]:
        return _read_json(self.control / _STOP)

    # -- apply-order log -------------------------------------------------------

    def append_apply_log(self, record: dict) -> None:
        """Single-writer (the server) append; one fsync'd line per apply so
        the log survives the same crashes the checkpoints do."""
        with open(self.apply_log, "a", encoding="utf-8") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def read_apply_log(self) -> list:
        try:
            text = self.apply_log.read_text(encoding="utf-8")
        except OSError:
            return []
        out = []
        for line in text.splitlines():
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn trailing line after a crash
        return out

    def rewrite_apply_log(self, records: list) -> None:
        """Truncate the log to ``records`` (server restart: entries past
        the restored checkpoint describe applies the restore rewound)."""
        tmp = self.root / f".apply_log.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.apply_log)


# -- env resolution ------------------------------------------------------------

def staleness_from_env() -> int:
    try:
        return max(0, int(os.environ.get(PS_STALENESS_ENV,
                                         DEFAULT_STALENESS)))
    except ValueError:
        return DEFAULT_STALENESS


def role_from_env() -> Optional[str]:
    role = os.environ.get(PS_ROLE_ENV, "").strip().lower()
    return role if role in ("server", "worker") else None


def rank_from_env() -> int:
    for var in (PS_RANK_ENV, "TPU_DIST_REJOIN_RANK"):
        val = os.environ.get(var)
        if val is not None:
            try:
                return int(val)
            except ValueError:
                continue
    return 0


def world_from_env() -> int:
    try:
        return max(1, int(os.environ.get(PS_WORLD_ENV, "1")))
    except ValueError:
        return 1


def sync_from_env() -> bool:
    return os.environ.get(PS_SYNC_ENV, "") == "1"


def pull_timeout_from_env() -> float:
    try:
        return max(1.0, float(os.environ.get(PS_PULL_TIMEOUT_ENV, "300")))
    except ValueError:
        return 300.0
