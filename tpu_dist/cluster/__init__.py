"""Cluster/config layer: TF_CONFIG-shaped config + JAX coordination bring-up."""

from tpu_dist.cluster.config import (
    TF_CONFIG_ENV,
    ClusterConfig,
    ClusterConfigError,
    ClusterSpec,
    TaskInfo,
    make_local_cluster,
)
from tpu_dist.cluster.bootstrap import (
    barrier,
    cluster_config,
    initialize,
    is_chief,
    is_initialized,
    process_count,
    process_index,
)
from tpu_dist.cluster.liveness import (
    LivenessMonitor,
    PeerUnavailableError,
    check_peer_health,
)

__all__ = [
    "LivenessMonitor",
    "PeerUnavailableError",
    "check_peer_health",
    "TF_CONFIG_ENV",
    "ClusterConfig",
    "ClusterConfigError",
    "ClusterSpec",
    "TaskInfo",
    "make_local_cluster",
    "barrier",
    "cluster_config",
    "initialize",
    "is_chief",
    "is_initialized",
    "process_count",
    "process_index",
]
