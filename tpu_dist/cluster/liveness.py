"""Peer liveness monitoring on top of the JAX coordination service.

The reference's failure-detection story (SURVEY.md D12, §5.3) is a Python
health-check thread: every worker pings every peer every 30 s
(``check_collective_ops_peer_health``, 3 retries x 10 s timeout); an
unreachable peer aborts collectives with ``UnavailableError`` and the job must
be restarted — fail-fast, no elasticity
(tf:...collective_all_reduce_strategy.py:337-349, 990-1042).

TPU-native translation: the C++ coordination service started by
``jax.distributed.initialize`` already heartbeats every process (the D11
equivalent ships with jaxlib). This module surfaces it at the framework level:

* :func:`check_peer_health` — one-shot liveness probe of every peer
  (``get_live_nodes`` on the coordination-service client).
* :class:`LivenessMonitor` — the D12 analog: background thread probing every
  ``interval`` seconds; a dead peer marks the monitor failed, and
  :meth:`raise_if_failed` (called by the fit loop between epochs) surfaces a
  :class:`PeerUnavailableError` — restart-required semantics, matching, not
  exceeding, the reference (no elastic recovery there either).

The startup barrier that keeps health checks from firing during bring-up
(tf:...collective_all_reduce_strategy.py:1043-1066) is
``bootstrap.barrier()``, run by MultiWorkerMirroredStrategy before any
monitor starts.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional, Sequence

logger = logging.getLogger("tpu_dist.liveness")

#: Reference knobs (tf:...collective_all_reduce_strategy.py:337-349):
#: check every 30 s, 10 s per-probe timeout.
DEFAULT_INTERVAL_S = float(os.environ.get("TPU_DIST_HEALTH_INTERVAL", "30"))
DEFAULT_TIMEOUT_S = float(os.environ.get("TPU_DIST_HEALTH_TIMEOUT", "10"))


class PeerUnavailableError(RuntimeError):
    """A peer process is unreachable; the job must be restarted.

    The analog of TF's ``UnavailableError`` from the health-check thread
    (SURVEY.md §5.3: fail-fast-and-restart, paired with checkpoint/resume).
    """


class _Prober:
    """One long-lived daemon thread that runs liveness probes with a deadline.

    ``get_live_nodes`` has no RPC deadline of its own, so a partitioned
    (reachable-but-unresponsive) coordinator can hang a probe indefinitely.
    Running probes on a persistent worker bounds the damage: a hung call
    wedges one thread, later attempts queue and time out in turn. When a
    probe TIMES OUT mid-call the worker is considered wedged and the next
    probe starts a FRESH worker (with a fresh RPC) so liveness can recover
    once the coordinator heals — capped at ``MAX_WEDGED_WORKERS`` abandoned
    threads per process, after which probes fail fast without spawning more
    (permanent-coordinator-death backstop; the r1-advice unbounded-thread
    leak stays fixed).
    """

    MAX_WEDGED_WORKERS = 4

    def __init__(self):
        import queue

        self._submit_lock = threading.Lock()
        self._requests: "queue.Queue" = queue.Queue()
        self._cv = threading.Condition()
        self._results: dict = {}
        self._abandoned: set = set()
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._in_flight: Optional[int] = None  # seq the worker is running
        self._wedged_count = 0
        self._gen = 0  # worker generation; replaced workers stop touching state

    def _run(self, generation_queue, gen: int) -> None:
        # Persistent daemon worker: blocking on the queue IS its idle
        # state. Liveness is owed by the callers — probe() bounds every
        # request with timeout_s and abandons wedged ones.
        while True:  # shardcheck: disable=SC502 -- idle state of a daemon worker; probe() callers carry the timeout
            seq, fn = generation_queue.get()
            with self._cv:
                if seq in self._abandoned:
                    # Caller timed out while this request was still queued
                    # (e.g. behind a hung probe): skip the stale RPC entirely
                    # so a backlog never delays the first fresh probe.
                    self._abandoned.discard(seq)
                    continue
                if self._gen == gen:
                    self._in_flight = seq
            try:
                out = fn()
            except Exception as e:  # returned to the caller as the result
                out = e
            with self._cv:
                # A replaced (wedged) worker that eventually finishes must
                # not clobber the live generation's bookkeeping.
                if self._gen == gen:
                    self._in_flight = None
                if seq in self._abandoned:
                    self._abandoned.discard(seq)  # caller gave up mid-call
                else:
                    self._results[seq] = out
                    self._cv.notify_all()

    def probe(self, fn, timeout_s: float):
        """Run ``fn()`` on the worker; returns its result/exception, or a
        TimeoutError if no answer arrives within ``timeout_s``."""
        import queue
        import time

        with self._submit_lock:
            with self._cv:
                wedged = self._in_flight is not None and \
                    self._in_flight in self._abandoned
            if wedged:
                if self._wedged_count >= self.MAX_WEDGED_WORKERS:
                    return TimeoutError(
                        f"coordination service unresponsive: "
                        f"{self._wedged_count} probe workers wedged; "
                        "not spawning more")
                # Abandon the wedged worker (its queue goes with it) and
                # start a fresh one so this probe issues a FRESH RPC.
                self._wedged_count += 1
                self._thread = None
                self._requests = queue.Queue()
                with self._cv:
                    self._gen += 1
                    self._in_flight = None
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, args=(self._requests, self._gen),
                    daemon=True, name="tpu_dist_probe")
                self._thread.start()
            self._seq += 1
            seq = self._seq
            requests = self._requests
        requests.put((seq, fn))
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while seq not in self._results:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._abandoned.add(seq)
                    return TimeoutError(
                        f"probe did not answer within {timeout_s}s")
                self._cv.wait(remaining)
            return self._results.pop(seq)


_prober = _Prober()


def _client():
    from jax._src import distributed

    return distributed.global_state.client


def check_peer_health(timeout_s: float = DEFAULT_TIMEOUT_S,
                      retries: int = 3) -> Sequence[int]:
    """Probe peer liveness; returns the list of dead process ids.

    A transient coordination-service RPC failure is retried ``retries`` times
    (the reference's 3-retry rule, tf:...collective_all_reduce_strategy.py:
    337-349) with the ``timeout_s`` budget spread across the attempts; only
    when every attempt fails does this raise :class:`PeerUnavailableError`
    (the service itself is unreachable). A *successful* probe that reports a
    dead peer needs no debouncing — the service only declares a node dead
    after its own heartbeat timeout. Single-process jobs trivially report no
    dead peers.
    """
    import time

    import jax

    n = jax.process_count()
    if n <= 1:
        return []
    client = _client()
    if client is None:
        return []
    last_error: object = None
    retries = max(retries, 1)
    for attempt in range(retries):
        # Each attempt gets the FULL timeout_s deadline (the reference's
        # 3 x 10 s rule), executed on the process-wide persistent probe
        # thread (_Prober) so a wedged coordinator pins at most one blocked
        # thread no matter how many attempts time out.
        out = _prober.probe(lambda: client.get_live_nodes(list(range(n))),
                            timeout_s)
        if not isinstance(out, Exception):
            return sorted(set(range(n)) - set(out))
        last_error = out
        logger.warning("liveness probe attempt %d/%d failed: %s",
                       attempt + 1, retries, last_error)
        if attempt + 1 < retries:
            time.sleep(min(1.0, timeout_s / 10))
    raise PeerUnavailableError(
        f"coordination service unreachable after {retries} probe attempts: "
        f"{last_error}. Restart the job.")


class LivenessMonitor:
    """Background peer-health thread — the D12 health-check analog.

    Elastic extension: with ``rejoin_window_s > 0`` a dead peer is first
    marked SUSPECT instead of immediately condemning the job. The monitor
    keeps probing; if the peer answers again within the window (the
    supervisor relaunched it and it re-entered at the epoch-boundary
    rendezvous), the suspicion clears and training was never interrupted.
    Only when the window expires with the peer still dead does the monitor
    fail terminally — the reference's fail-fast semantics, just with a
    bounded forgiveness period. ``rejoin_window_s = 0`` (the default) keeps
    the original first-death-is-terminal behavior.
    """

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 rejoin_window_s: float = 0.0):
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.rejoin_window_s = float(rejoin_window_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead_peers: Sequence[int] = []
        self._failed = threading.Event()
        #: peer id -> monotonic deadline by which it must answer again.
        self._suspects: dict = {}
        #: peer id -> monotonic time it last answered a probe; basis for
        #: ``detect_s`` (how much of the heartbeat window a detection ate).
        self._last_seen: dict = {}
        #: Detection latency of the most recent new suspect, seconds; the
        #: ``elastic.detect_s`` observable — on a real backend this is
        #: dominated by $TPU_DIST_HEARTBEAT_TIMEOUT_S (default 100 s) and
        #: was invisible before it was recorded here.
        self.last_detect_s: Optional[float] = None
        #: monotonic time of the previous _observe round — the fallback
        #: "last known alive" for a peer that was never individually seen.
        self._prev_round_t: Optional[float] = None

    def start(self) -> "LivenessMonitor":
        import jax

        if jax.process_count() <= 1:
            return self
        if self._thread is not None and self._thread.is_alive():
            return self  # already running
        if self.failed:
            return self  # peer failure is terminal — restart the job
        # Re-arm after a stop() or a naturally-exited loop, so the shared
        # singleton handed to a fresh strategy actually probes again.
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu_dist_health", daemon=True)
        self._thread.start()
        logger.info("liveness monitor started (interval=%.0fs, timeout=%.0fs)",
                    self.interval_s, self.timeout_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s)
            if self._thread.is_alive():
                # Still blocked in a probe: leave the handle so a later
                # start() sees it alive and won't spawn a second loop.
                logger.warning("liveness monitor thread did not stop within "
                               "%.0fs; leaving it to finish", self.timeout_s)
            else:
                self._thread = None
                # start() clears _stop when re-arming.

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                dead = check_peer_health(self.timeout_s)
            except PeerUnavailableError:
                # Service unreachable even after retries: treat every peer as
                # suspect; raise_if_failed will surface it.
                import jax

                dead = [i for i in range(jax.process_count())
                        if i != jax.process_index()]
            if self._observe(dead):
                return

    def _observe(self, dead: Sequence[int],
                 now: Optional[float] = None) -> bool:
        """Fold one probe result into suspect/failed state; True = terminal.

        Split from :meth:`_loop` so the rejoin-window state machine is
        testable without threads or a real coordination service.
        """
        import time

        from tpu_dist.resilience import events

        now = time.monotonic() if now is None else now
        if self._failed.is_set():
            # Terminal guard: once condemned, a late-answering peer must not
            # clear suspicions or log a spurious peer_rejoined — the trainer
            # is already unwinding on raise_if_failed().
            return True
        dead_set = set(dead)
        if self.rejoin_window_s <= 0 and dead_set:
            self._dead_peers = sorted(dead_set)
            self._failed.set()
            logger.error(
                "peer process(es) %s unreachable; collectives will not "
                "complete — restart the job (reference semantics: "
                "UnavailableError, SURVEY.md §5.3)", sorted(dead_set))
            return True
        # Rejoin window armed: newly-dead peers become suspects ...
        for peer in dead_set - set(self._suspects):
            base = self._last_seen.get(peer, self._prev_round_t)
            detect_s = None if base is None else max(0.0, now - base)
            self.last_detect_s = detect_s
            if detect_s is not None:
                from tpu_dist.observe import metrics as metrics_lib

                metrics_lib.observe_value("elastic.detect_s", detect_s)
            self._suspects[peer] = now + self.rejoin_window_s
            logger.warning(
                "peer %d unreachable; suspect for %.0fs pending rejoin",
                peer, self.rejoin_window_s)
            events.maybe_log(
                "peer_suspect", peer=peer,
                rejoin_window_s=self.rejoin_window_s,
                detect_s=None if detect_s is None else round(detect_s, 6))
        # ... answering suspects recover ...
        for peer in sorted(set(self._suspects) - dead_set):
            del self._suspects[peer]
            self._last_seen[peer] = now
            logger.info("peer %d answered again; rejoin complete", peer)
            events.maybe_log("peer_rejoined", peer=peer)
        # ... and suspects past their deadline condemn the job.
        expired = sorted(p for p, t in self._suspects.items() if now > t)
        if expired:
            self._dead_peers = expired
            self._failed.set()
            logger.error(
                "peer process(es) %s did not rejoin within %.0fs; "
                "restart the job", expired, self.rejoin_window_s)
            events.maybe_log("peer_rejoin_expired", peers=expired)
            return True
        self._prev_round_t = now
        return False

    @property
    def failed(self) -> bool:
        return self._failed.is_set()

    @property
    def dead_peers(self) -> Sequence[int]:
        return list(self._dead_peers)

    @property
    def suspect_peers(self) -> Sequence[int]:
        """Peers currently inside their rejoin window (not yet condemned)."""
        return sorted(self._suspects)

    def raise_if_failed(self) -> None:
        if self.failed:
            raise PeerUnavailableError(
                f"peer process(es) {list(self._dead_peers)} are unreachable; "
                "synchronous training cannot continue. Restart the job "
                "(resume from the latest checkpoint if one was written).")


_shared_monitor: Optional[LivenessMonitor] = None
_shared_lock = threading.Lock()


def shared_monitor() -> LivenessMonitor:
    """Per-process singleton monitor — repeated strategy constructions reuse
    one probe thread instead of leaking one per instance."""
    global _shared_monitor
    with _shared_lock:
        if _shared_monitor is None:
            _shared_monitor = LivenessMonitor()
        return _shared_monitor
