"""Runtime bootstrap: cluster bring-up on top of the JAX coordination service.

Reference semantics being reproduced (SURVEY.md §3.1, D3/D10/D11):

* Each process reads TF_CONFIG, then constructs the strategy, which starts a
  per-process gRPC server and blocks until every declared peer is reachable
  (README.md:65-66; tf:...collective_all_reduce_strategy.py:507-664).
* One worker (explicit chief, else worker 0) is the chief with extra duties
  (README.md:51).
* A single worker / absent TF_CONFIG degrades to local (single-process)
  training (README.md:34).

TPU-native translation: there are no user-managed servers. ``initialize()``
parses the same TF_CONFIG JSON and calls ``jax.distributed.initialize`` —
process 0 hosts the coordination service (C++ in jaxlib, gRPC underneath:
the native equivalent of the reference's GrpcServer + coordination service),
everyone else dials it, and the call blocks until all ``num_processes`` have
joined: the same "training begins when all services are ready" barrier as
README.md:66. On an actual TPU pod with no TF_CONFIG, ``jax.distributed``
autodetects the slice topology from the TPU metadata environment.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Optional

from tpu_dist.cluster.config import ClusterConfig

logger = logging.getLogger("tpu_dist")

_STATE_LOCK = threading.Lock()
_INITIALIZED = False
_CONFIG: Optional[ClusterConfig] = None


def initialize(config: ClusterConfig | None = None, *,
               coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up the cluster runtime. Idempotent; safe to call in every process.

    Resolution order (mirrors the reference's resolver chain, SURVEY.md D1):

    1. Explicit ``config`` / explicit ``coordinator_address`` kwargs.
    2. ``TF_CONFIG`` env var (same JSON shape as the reference,
       tf_dist_example.py:6-10).
    3. TPU-pod / cloud autodetection via bare ``jax.distributed.initialize()``
       when the environment indicates a multi-process TPU job.
    4. Otherwise: single-process local mode — the README.md:34 degradation rule
       (1 worker behaves like single-host MirroredStrategy).
    """
    global _INITIALIZED, _CONFIG
    import inspect

    import jax

    def _dist_init(**kwargs):
        # jax < 0.5 has no heartbeat_timeout_seconds (or other newer)
        # kwargs on jax.distributed.initialize; drop what this version
        # doesn't accept rather than failing bring-up.
        sig = inspect.signature(jax.distributed.initialize)
        jax.distributed.initialize(**{
            k: v for k, v in kwargs.items() if k in sig.parameters})

    with _STATE_LOCK:
        if _INITIALIZED:
            return

        if config is None:
            config = ClusterConfig.from_env()

        # Failure-detection latency knob (SURVEY.md D12: TF probes every 30 s
        # with 10 s timeouts; JAX's coordination service heartbeats instead).
        # Exposed mainly so fault tests can shrink detection time.
        hb = float(os.environ.get("TPU_DIST_HEARTBEAT_TIMEOUT_S", "100"))

        if coordinator_address is not None:
            # Explicit JAX-style bring-up, bypassing TF_CONFIG.
            _dist_init(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                heartbeat_timeout_seconds=max(1, round(hb)),
            )
            _log_bringup()
        elif config is not None and config.num_processes > 1:
            logger.info(
                "tpu_dist: initializing %d-process cluster from TF_CONFIG; "
                "task=(%s, %d) process_id=%d chief=%s coordinator=%s",
                config.num_processes, config.task.type, config.task.index,
                config.process_id, config.is_chief, config.coordinator_address,
            )
            # The declared addresses are ours to bind (no TF gRPC servers exist
            # in this framework); process 0's entry doubles as the coordination
            # service endpoint.
            _dist_init(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
                heartbeat_timeout_seconds=max(1, round(hb)),
            )
            _log_bringup()
        elif config is None and _tpu_pod_env_present():
            logger.info("tpu_dist: no TF_CONFIG; using TPU pod autodetection")
            _dist_init(
                heartbeat_timeout_seconds=max(1, round(hb)))
            _log_bringup()
        else:
            # Single-process local mode (README.md:34): nothing to bring up.
            logger.info(
                "tpu_dist: single-process local mode (%d local device(s))",
                jax.local_device_count(),
            )

        _CONFIG = config
        _INITIALIZED = True
        atexit.register(_shutdown)


def _tpu_pod_env_present() -> bool:
    """True only for a genuinely multi-host TPU job (Cloud TPU / megascale env).

    Single-host markers must NOT trigger distributed bring-up: a lone worker
    degrades to local mode (README.md:34), and some images set
    ``TPU_WORKER_HOSTNAMES=localhost`` even for one host.
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def _log_bringup() -> None:
    import jax
    # The analog of the reference's bring-up log line "Enabled multi-worker
    # collective ops with available devices: [...]" (SURVEY.md §3.5) — the
    # affordance tests use to confirm the cluster really formed.
    logger.info(
        "tpu_dist: cluster up — process %d/%d, %d global device(s): %s",
        jax.process_index(), jax.process_count(), jax.device_count(),
        [str(d) for d in jax.devices()],
    )


def _shutdown() -> None:
    """Clean shutdown at exit — the README.md:68 'servers shut down when
    training ends' semantics."""
    global _INITIALIZED
    if not _INITIALIZED:
        return
    try:
        import jax
        if jax.process_count() > 1:
            jax.distributed.shutdown()
    except Exception:  # pragma: no cover - best-effort at interpreter exit
        pass
    _INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def cluster_config() -> Optional[ClusterConfig]:
    """The parsed TF_CONFIG for this process, if any."""
    return _CONFIG


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_chief() -> bool:
    """Chief duty holder: explicit TF_CONFIG chief, else global process 0.

    README.md:51: the chief saves checkpoints and writes TensorBoard; worker 0
    is the default chief.
    """
    if _CONFIG is not None:
        return _CONFIG.is_chief
    return process_index() == 0


def barrier(name: str = "tpu_dist_barrier") -> None:
    """Cluster-wide rendezvous.

    The analog of the reference's startup barrier — a dummy RING all-reduce run
    before health checking starts (tf:...collective_all_reduce_strategy.py:
    1043-1066, SURVEY.md §5.3).
    """
    import time

    import jax

    from tpu_dist.parallel.collectives import (fire_fault_hook,
                                               fire_observe_hook)

    # Chaos seam first: a single-process run has no peers to rendezvous
    # with, but an injected barrier stall must still be injectable there.
    fire_fault_hook("barrier")
    t0 = time.perf_counter()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
    # Barrier wait time is the cluster's skew made visible — the telemetry
    # hook records it like any other host collective (tpu_dist.observe).
    fire_observe_hook("barrier", seconds=time.perf_counter() - t0)


#: Environment variable naming the shared directory used for the elastic
#: epoch-boundary rendezvous. Setting it arms ``RejoinGate`` in every fit().
REJOIN_DIR_ENV = "TPU_DIST_REJOIN_DIR"


def epoch_rendezvous(directory, *, epoch: int, rank: Optional[int] = None,
                     world: Optional[int] = None, timeout_s: float = 120.0,
                     poll_s: float = 0.05) -> "list[int]":
    """Shared-filesystem epoch-boundary barrier for elastic rejoin.

    Each worker atomically publishes a ``epoch-{E}.rank-{r}`` marker under
    ``directory`` and polls until markers from all ``world`` ranks for that
    epoch exist, then returns the sorted rank list. This is deliberately NOT
    ``sync_global_devices``: a worker relaunched after a preemption is a new
    process outside the surviving gang's collective clique, and the meeting
    protocol that lets it back in cannot itself require membership. A shared
    directory (the same assumption the v2 sharded checkpoint already makes)
    is the lowest-common-denominator rendezvous medium.

    Raises :class:`TimeoutError` naming the missing ranks if the gang does
    not fully assemble within ``timeout_s`` — the caller (usually
    ``RejoinGate``) surfaces that as a liveness failure rather than stepping
    with a partial gang.
    """
    import pathlib
    import time

    if rank is None:
        rank = process_index()
    if world is None:
        world = process_count()
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    marker = d / f"epoch-{epoch}.rank-{rank}"
    tmp = d / f".epoch-{epoch}.rank-{rank}.{os.getpid()}.tmp"
    tmp.write_text(str(os.getpid()), encoding="utf-8")
    os.replace(tmp, marker)  # atomic publish; re-publishing is idempotent
    # Markers two epochs back can never be waited on again — reap this
    # rank's own so a long run does not grow the directory unboundedly.
    for old in d.glob(f"epoch-*.rank-{rank}"):
        try:
            e = int(old.name.split(".", 1)[0].split("-", 1)[1])
        except ValueError:
            continue
        if e < epoch - 1:
            try:
                old.unlink()
            except OSError:
                pass

    deadline = time.monotonic() + timeout_s
    while True:
        present = set()
        for p in d.glob(f"epoch-{epoch}.rank-*"):
            suffix = p.name.rsplit("rank-", 1)[1]
            if suffix.isdigit():
                present.add(int(suffix))
        if len(present & set(range(world))) >= world:
            return sorted(present & set(range(world)))
        if time.monotonic() > deadline:
            missing = sorted(set(range(world)) - present)
            raise TimeoutError(
                f"epoch_rendezvous: epoch {epoch} barrier in {d} timed out "
                f"after {timeout_s:.1f}s; missing rank(s) {missing} "
                f"(present: {sorted(present)})")
        time.sleep(poll_s)
