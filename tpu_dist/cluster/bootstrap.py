"""Runtime bootstrap: cluster bring-up on top of the JAX coordination service.

Reference semantics being reproduced (SURVEY.md §3.1, D3/D10/D11):

* Each process reads TF_CONFIG, then constructs the strategy, which starts a
  per-process gRPC server and blocks until every declared peer is reachable
  (README.md:65-66; tf:...collective_all_reduce_strategy.py:507-664).
* One worker (explicit chief, else worker 0) is the chief with extra duties
  (README.md:51).
* A single worker / absent TF_CONFIG degrades to local (single-process)
  training (README.md:34).

TPU-native translation: there are no user-managed servers. ``initialize()``
parses the same TF_CONFIG JSON and calls ``jax.distributed.initialize`` —
process 0 hosts the coordination service (C++ in jaxlib, gRPC underneath:
the native equivalent of the reference's GrpcServer + coordination service),
everyone else dials it, and the call blocks until all ``num_processes`` have
joined: the same "training begins when all services are ready" barrier as
README.md:66. On an actual TPU pod with no TF_CONFIG, ``jax.distributed``
autodetects the slice topology from the TPU metadata environment.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Optional

from tpu_dist.cluster.config import ClusterConfig

logger = logging.getLogger("tpu_dist")

_STATE_LOCK = threading.Lock()
_INITIALIZED = False
_CONFIG: Optional[ClusterConfig] = None
#: The explicit (coordinator_address, num_processes, process_id) the
#: distributed client was last brought up with, recorded by ``_dist_init``.
#: This is what lets ``reinitialize`` run a REAL teardown + re-init even
#: when TF_CONFIG is absent — e.g. an explicit single-process bring-up
#: against a coordination service, where ``jax.process_count() == 1`` but a
#: live client exists. None when no explicit bring-up happened.
_DIST_PARAMS: Optional[dict] = None
#: Gang generation of this process's collective clique (see
#: ``current_generation``); None until first read (env or reinitialize).
_GENERATION: Optional[int] = None

#: Environment variable carrying the gang generation into a (re)launched
#: worker — the Supervisor stamps it on a mid-epoch replacement so the new
#: process joins the REFORMED clique, not the one that lost a member.
GENERATION_ENV = "TPU_DIST_GANG_GENERATION"


def _dist_init(**kwargs):
    # jax < 0.5 has no heartbeat_timeout_seconds (or other newer)
    # kwargs on jax.distributed.initialize; drop what this version
    # doesn't accept rather than failing bring-up.
    global _DIST_PARAMS
    import inspect

    import jax

    allow_live_backend = kwargs.pop("allow_live_backend", False)
    sig = inspect.signature(jax.distributed.initialize)
    try:
        jax.distributed.initialize(**{
            k: v for k, v in kwargs.items() if k in sig.parameters})
    except RuntimeError as exc:
        if (not allow_live_backend
                or "before any JAX computations" not in str(exc)):
            raise
        # Mid-process RE-dial: a gang-reform survivor has been computing
        # for epochs, so its backend is necessarily live, and the public
        # API refuses re-init categorically. The coordination service
        # (gRPC, C++ side) is independent of the local device backend, so
        # bring the service + client up directly; only ``reinitialize``
        # sets ``allow_live_backend`` — a FIRST bring-up after
        # computations still fails loudly, since there the backend's
        # process/device view really would be stale.
        from jax._src import distributed as _dist

        state_sig = inspect.signature(_dist.global_state.initialize)
        _dist.global_state.initialize(**{
            k: v for k, v in kwargs.items() if k in state_sig.parameters})
        logger.info(
            "tpu_dist: re-dialed coordination service at %s under a live "
            "backend", kwargs.get("coordinator_address"))
    if kwargs.get("coordinator_address") and kwargs.get("num_processes"):
        _DIST_PARAMS = {k: kwargs.get(k) for k in
                        ("coordinator_address", "num_processes",
                         "process_id")}


def initialize(config: ClusterConfig | None = None, *,
               coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up the cluster runtime. Idempotent; safe to call in every process.

    Resolution order (mirrors the reference's resolver chain, SURVEY.md D1):

    1. Explicit ``config`` / explicit ``coordinator_address`` kwargs.
    2. ``TF_CONFIG`` env var (same JSON shape as the reference,
       tf_dist_example.py:6-10).
    3. TPU-pod / cloud autodetection via bare ``jax.distributed.initialize()``
       when the environment indicates a multi-process TPU job.
    4. Otherwise: single-process local mode — the README.md:34 degradation rule
       (1 worker behaves like single-host MirroredStrategy).
    """
    global _INITIALIZED, _CONFIG
    import jax

    with _STATE_LOCK:
        if _INITIALIZED:
            return

        if config is None:
            config = ClusterConfig.from_env()

        # Failure-detection latency knob (SURVEY.md D12: TF probes every 30 s
        # with 10 s timeouts; JAX's coordination service heartbeats instead).
        # Exposed mainly so fault tests can shrink detection time.
        hb = float(os.environ.get("TPU_DIST_HEARTBEAT_TIMEOUT_S", "100"))

        if coordinator_address is not None:
            # Explicit JAX-style bring-up, bypassing TF_CONFIG.
            _dist_init(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                heartbeat_timeout_seconds=max(1, round(hb)),
            )
            _log_bringup()
        elif config is not None and config.num_processes > 1:
            logger.info(
                "tpu_dist: initializing %d-process cluster from TF_CONFIG; "
                "task=(%s, %d) process_id=%d chief=%s coordinator=%s",
                config.num_processes, config.task.type, config.task.index,
                config.process_id, config.is_chief, config.coordinator_address,
            )
            # The declared addresses are ours to bind (no TF gRPC servers exist
            # in this framework); process 0's entry doubles as the coordination
            # service endpoint.
            _dist_init(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
                heartbeat_timeout_seconds=max(1, round(hb)),
            )
            _log_bringup()
        elif config is None and _tpu_pod_env_present():
            logger.info("tpu_dist: no TF_CONFIG; using TPU pod autodetection")
            _dist_init(
                heartbeat_timeout_seconds=max(1, round(hb)))
            _log_bringup()
        else:
            # Single-process local mode (README.md:34): nothing to bring up.
            logger.info(
                "tpu_dist: single-process local mode (%d local device(s))",
                jax.local_device_count(),
            )

        _CONFIG = config
        _INITIALIZED = True
        atexit.register(_shutdown)


def _tpu_pod_env_present() -> bool:
    """True only for a genuinely multi-host TPU job (Cloud TPU / megascale env).

    Single-host markers must NOT trigger distributed bring-up: a lone worker
    degrades to local mode (README.md:34), and some images set
    ``TPU_WORKER_HOSTNAMES=localhost`` even for one host.
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def _log_bringup() -> None:
    import jax
    # The analog of the reference's bring-up log line "Enabled multi-worker
    # collective ops with available devices: [...]" (SURVEY.md §3.5) — the
    # affordance tests use to confirm the cluster really formed.
    logger.info(
        "tpu_dist: cluster up — process %d/%d, %d global device(s): %s",
        jax.process_index(), jax.process_count(), jax.device_count(),
        [str(d) for d in jax.devices()],
    )


def _shutdown() -> None:
    """Clean shutdown at exit — the README.md:68 'servers shut down when
    training ends' semantics."""
    global _INITIALIZED
    if not _INITIALIZED:
        return
    try:
        import jax
        if jax.process_count() > 1:
            jax.distributed.shutdown()
    except Exception:  # pragma: no cover - best-effort at interpreter exit
        pass
    _INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def current_generation() -> int:
    """The gang generation this process's collective clique belongs to.

    Generation 0 is the launch clique. Every mid-epoch gang reform bumps it
    (``reinitialize``); a worker relaunched INTO a reformed gang inherits it
    through ``$TPU_DIST_GANG_GENERATION`` (stamped by the Supervisor), so
    survivors and the replacement agree on the clique id without talking.
    """
    global _GENERATION
    with _STATE_LOCK:
        if _GENERATION is None:
            try:
                _GENERATION = int(os.environ.get(GENERATION_ENV, "0") or 0)
            except ValueError:
                _GENERATION = 0
        return _GENERATION


def reinitialize(generation: Optional[int] = None, *,
                 coordinator_port: Optional[int] = None) -> int:
    """Tear down and re-bring-up the collective clique under a new generation.

    The live-elasticity primitive: a survivor of a lost rank keeps its
    weights, host state, and python process — only the *clique* is reformed.
    ``jax.distributed`` is shut down (releasing membership in the dead
    clique) and re-initialized against a FRESH coordinator port, derived
    deterministically from the generation (``base_port + generation`` unless
    ``coordinator_port`` overrides it) so every survivor dials the same new
    endpoint without communicating — the old coordinator may have died with
    the lost rank, and its port may sit in TIME_WAIT.

    In single-process LOCAL mode (including the CI file-gang vehicle, where
    each supervised worker is its own jax process and the gang exists only
    in the shared-filesystem rendezvous) there is no clique to tear down:
    the call just re-stamps the generation, which re-namespaces every
    subsequent rendezvous marker. An EXPLICIT bring-up, however — even with
    ``num_processes == 1`` — started a real distributed client against a
    coordination service, so the real teardown + re-init path runs for it
    too (this is how the multi-device harness proves the collectives-capable
    leg on the CPU backend). Returns the new generation (``generation``
    when given, else current + 1).
    """
    global _INITIALIZED, _GENERATION
    import jax

    new_gen = (current_generation() + 1 if generation is None
               else int(generation))
    with _STATE_LOCK:
        was_up = _INITIALIZED
        config = _CONFIG
        if config is not None and config.num_processes > 1:
            params = {"coordinator_address": config.coordinator_address,
                      "num_processes": config.num_processes,
                      "process_id": config.process_id}
        elif _DIST_PARAMS is not None:
            params = dict(_DIST_PARAMS)
        else:
            params = None
        if was_up and params is not None:
            # A real distributed client is up (multi-process TF_CONFIG, or
            # an explicit bring-up with a coordination service): release
            # membership in the dead clique before re-dialing.
            try:
                jax.distributed.shutdown()
            except Exception as exc:  # the old clique is already broken
                logger.warning(
                    "tpu_dist: shutdown of generation %d clique failed "
                    "(%s); continuing with re-init", _GENERATION, exc)
            _INITIALIZED = False
        _GENERATION = new_gen
        # Re-exported so child processes (and a later current_generation()
        # after module reload) observe the reformed clique's id.
        os.environ[GENERATION_ENV] = str(new_gen)

    if params is not None:
        host, _, base_port = params["coordinator_address"].rpartition(":")
        try:
            port = (int(coordinator_port) if coordinator_port is not None
                    else int(base_port) + new_gen)
        except ValueError:
            port = coordinator_port or base_port
        hb = float(os.environ.get("TPU_DIST_HEARTBEAT_TIMEOUT_S", "100"))
        logger.info(
            "tpu_dist: reforming %d-process clique at generation %d "
            "(coordinator %s:%s)", params["num_processes"], new_gen, host,
            port)
        _dist_init(
            coordinator_address=f"{host}:{port}",
            num_processes=params["num_processes"],
            process_id=params["process_id"],
            heartbeat_timeout_seconds=max(1, round(hb)),
            allow_live_backend=True,
        )
        _log_bringup()
    else:
        logger.info("tpu_dist: gang generation -> %d (single-process "
                    "clique; rendezvous namespace re-stamped)", new_gen)
    with _STATE_LOCK:
        _INITIALIZED = was_up or params is not None
    return new_gen


def cluster_config() -> Optional[ClusterConfig]:
    """The parsed TF_CONFIG for this process, if any."""
    return _CONFIG


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_chief() -> bool:
    """Chief duty holder: explicit TF_CONFIG chief, else global process 0.

    README.md:51: the chief saves checkpoints and writes TensorBoard; worker 0
    is the default chief.
    """
    if _CONFIG is not None:
        return _CONFIG.is_chief
    return process_index() == 0


def barrier(name: str = "tpu_dist_barrier") -> None:
    """Cluster-wide rendezvous.

    The analog of the reference's startup barrier — a dummy RING all-reduce run
    before health checking starts (tf:...collective_all_reduce_strategy.py:
    1043-1066, SURVEY.md §5.3).
    """
    import time

    import jax

    from tpu_dist.parallel.collectives import (fire_fault_hook,
                                               fire_observe_hook)

    # Chaos seam first: a single-process run has no peers to rendezvous
    # with, but an injected barrier stall must still be injectable there.
    fire_fault_hook("barrier")
    t0 = time.perf_counter()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
    # Barrier wait time is the cluster's skew made visible — the telemetry
    # hook records it like any other host collective (tpu_dist.observe).
    fire_observe_hook("barrier", seconds=time.perf_counter() - t0)


#: Environment variable naming the shared directory used for the elastic
#: epoch-boundary rendezvous. Setting it arms ``RejoinGate`` in every fit().
REJOIN_DIR_ENV = "TPU_DIST_REJOIN_DIR"

#: Environment variable naming the shared directory used for mid-epoch gang
#: reform (step-granular rendezvous + the reform request/ack protocol).
#: Setting it arms ``StepRejoinGate`` in every fit().
GANG_DIR_ENV = "TPU_DIST_GANG_DIR"


def _default_rendezvous_namespace() -> str:
    """Generation/attempt namespace for this process's barrier markers.

    A marker published by generation g / supervisor attempt a must never
    satisfy a barrier run by generation g' or attempt a' — a dead process's
    stale marker would let a partial gang pass. Namespacing by both ids
    makes reuse structurally impossible.
    """
    from tpu_dist.resilience.events import current_attempt

    return f"g{current_generation()}a{current_attempt()}"


def _reap_markers(d, rank: int, *, keep_namespace: str,
                  keep_min_epoch: Optional[int] = None) -> None:
    """Reap THIS rank's barrier markers that can never be waited on again.

    Removes the rank's markers from any other namespace (older generations/
    attempts, including legacy un-namespaced ``epoch-N.rank-r`` files from
    pre-generation runs), and — when ``keep_min_epoch`` is given — markers
    in the current namespace older than that epoch. Only this rank's own
    files are touched: another live rank's markers are its own to manage.
    """
    for old in d.glob(f"*rank-{rank}"):
        name = old.name
        if name.startswith("reform-"):
            # Reform-protocol acks also end in rank-{r}; they belong to the
            # supervisor handshake, not the barrier, and are not ours to GC.
            continue
        if not name.startswith(keep_namespace + "."):
            try:
                old.unlink()
            except OSError:
                pass
            continue
        if keep_min_epoch is None:
            continue
        try:
            e = int(name.split(".")[1].split("-", 1)[1])
        except (IndexError, ValueError):
            continue
        if e < keep_min_epoch:
            try:
                old.unlink()
            except OSError:
                pass


def _fs_barrier(directory, *, marker_stem: str, rank: int, world: int,
                timeout_s: float, poll_s: float, what: str,
                gc_namespace: Optional[str] = None,
                gc_min_epoch: Optional[int] = None,
                abort_check=None) -> "list[int]":
    """Shared-filesystem barrier: publish ``{marker_stem}.rank-{rank}`` and
    poll until all ``world`` ranks' markers for the same stem exist.

    On timeout this rank's own marker is reaped BEFORE raising, so a retry
    of the same barrier (or a reformed gang reusing the coordinate) cannot
    count this process as present when it has already given up.
    ``abort_check`` (if given) runs every poll round and may raise to break
    out — how a survivor parked at an epoch barrier still notices a gang
    reform whose missing rank will never publish this generation's marker.
    """
    import pathlib
    import time

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    marker = d / f"{marker_stem}.rank-{rank}"
    tmp = d / f".{marker_stem}.rank-{rank}.{os.getpid()}.tmp"
    tmp.write_text(str(os.getpid()), encoding="utf-8")
    os.replace(tmp, marker)  # atomic publish; re-publishing is idempotent
    if gc_namespace is not None:
        _reap_markers(d, rank, keep_namespace=gc_namespace,
                      keep_min_epoch=gc_min_epoch)

    deadline = time.monotonic() + timeout_s
    while True:
        if abort_check is not None:
            abort_check()
        present = set()
        for p in d.glob(f"{marker_stem}.rank-*"):
            suffix = p.name.rsplit("rank-", 1)[1]
            if suffix.isdigit():
                present.add(int(suffix))
        if len(present & set(range(world))) >= world:
            return sorted(present & set(range(world)))
        if time.monotonic() > deadline:
            missing = sorted(set(range(world)) - present)
            try:
                marker.unlink()
            except OSError:
                pass
            raise TimeoutError(
                f"{what} barrier in {d} timed out after {timeout_s:.1f}s; "
                f"missing rank(s) {missing} (present: {sorted(present)})")
        time.sleep(poll_s)


def epoch_rendezvous(directory, *, epoch: int, rank: Optional[int] = None,
                     world: Optional[int] = None, timeout_s: float = 120.0,
                     poll_s: float = 0.05,
                     namespace: Optional[str] = None) -> "list[int]":
    """Shared-filesystem epoch-boundary barrier for elastic rejoin.

    Each worker atomically publishes a ``{ns}.epoch-{E}.rank-{r}`` marker
    under ``directory`` and polls until markers from all ``world`` ranks for
    that epoch exist, then returns the sorted rank list. This is deliberately
    NOT ``sync_global_devices``: a worker relaunched after a preemption is a
    new process outside the surviving gang's collective clique, and the
    meeting protocol that lets it back in cannot itself require membership.
    A shared directory (the same assumption the v2 sharded checkpoint already
    makes) is the lowest-common-denominator rendezvous medium.

    ``namespace`` defaults to ``g{generation}a{attempt}``: markers from an
    earlier supervisor attempt or an earlier gang generation can never
    satisfy this barrier, and a rank that times out reaps its own marker, so
    a restarted gang re-running the same epoch numbers always assembles from
    scratch (previously a dead process's marker could pass a partial gang).

    Raises :class:`TimeoutError` naming the missing ranks if the gang does
    not fully assemble within ``timeout_s`` — the caller (usually
    ``RejoinGate``) surfaces that as a liveness failure rather than stepping
    with a partial gang.
    """
    if rank is None:
        rank = process_index()
    if world is None:
        world = process_count()
    ns = namespace if namespace is not None else _default_rendezvous_namespace()
    return _fs_barrier(
        directory, marker_stem=f"{ns}.epoch-{epoch}", rank=rank, world=world,
        timeout_s=timeout_s, poll_s=poll_s,
        what=f"epoch_rendezvous: epoch {epoch} ({ns})",
        gc_namespace=ns, gc_min_epoch=epoch - 1)


def generation_rendezvous(directory, *, generation: int, step: int,
                          rank: Optional[int] = None,
                          world: Optional[int] = None,
                          timeout_s: float = 120.0,
                          poll_s: float = 0.05,
                          abort_check=None) -> "list[int]":
    """Step-granular gang barrier, namespaced by gang generation.

    The mid-epoch generalization of :func:`epoch_rendezvous`: survivors of a
    lost rank drain at a step boundary and meet the relaunched rank HERE, at
    an arbitrary global-step coordinate, under the reformed generation's
    namespace — a marker from the broken generation g can never satisfy
    generation g+1's barrier. Markers from older generations (and older
    steps of this generation) published by this rank are reaped on the way
    in; this rank's marker is reaped on timeout so a retry starts clean.
    """
    if rank is None:
        rank = process_index()
    if world is None:
        world = process_count()
    ns = f"gen-{generation}"
    return _fs_barrier(
        directory, marker_stem=f"{ns}.step-{step}", rank=rank, world=world,
        timeout_s=timeout_s, poll_s=poll_s,
        what=f"generation_rendezvous: generation {generation} step {step}",
        gc_namespace=ns, gc_min_epoch=None, abort_check=abort_check)


# ---------------------------------------------------------------------------
# Gang-reform protocol (shared filesystem, torn-read tolerant)
#
# The Supervisor and the surviving workers coordinate a mid-epoch reform
# through three kinds of files under the gang directory:
#
#   reform-request.json           supervisor -> survivors: "rank R is lost;
#                                 drain, publish checkpoints, reform at
#                                 generation G"
#   reform-g{G}.drained.rank-{r}  survivor -> supervisor: "my in-flight
#                                 checkpoint is published; safe to relaunch"
#   generation                    supervisor -> everyone: the committed
#                                 current generation (a late-starting or
#                                 relaunched worker adopts max(env, file))
# ---------------------------------------------------------------------------


def _atomic_write_json(path, payload: dict) -> None:
    import json

    tmp = path.parent / f".{path.name}.{os.getpid()}.tmp"
    tmp.write_text(json.dumps(payload), encoding="utf-8")
    os.replace(tmp, path)


def _read_json(path) -> Optional[dict]:
    import json

    try:
        return json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None


def request_reform(directory, *, generation: int, lost_ranks: "list[int]",
                   detect_s: Optional[float] = None) -> dict:
    """Publish a gang-reform request (supervisor side). Overwrites any older
    request — at most one reform is in flight per gang directory."""
    import pathlib
    import time

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    payload = {
        "generation": int(generation),
        "lost_ranks": sorted(int(r) for r in lost_ranks),
        "ts": time.time(),
        "detect_s": detect_s,
    }
    _atomic_write_json(d / "reform-request.json", payload)
    return payload


def read_reform_request(directory) -> Optional[dict]:
    """The pending reform request, or None (missing / torn / malformed)."""
    import pathlib

    req = _read_json(pathlib.Path(directory) / "reform-request.json")
    if not isinstance(req, dict) or "generation" not in req:
        return None
    return req


def withdraw_reform(directory) -> None:
    """Remove a pending reform request (supervisor side).

    Called when an in-flight reform is abandoned — a SECOND rank died while
    survivors were draining, or the acks timed out — and the attempt falls
    back to an ordinary gang restart. The request must not outlive the
    attempt: a relaunched worker's rejoin gate reading a stale request for a
    future generation would drain into a reform no supervisor is mediating.
    Idempotent; missing file is fine.
    """
    import pathlib

    try:
        (pathlib.Path(directory) / "reform-request.json").unlink()
    except OSError:
        pass


def ack_reform(directory, *, generation: int, rank: int,
               available_step: Optional[int] = None) -> None:
    """Survivor's drained-and-published acknowledgement for a reform.

    ``available_step`` is the newest COMPLETE checkpoint step in this rank's
    checkpoint directory after the drain published in-flight saves — the
    supervisor takes the gang-wide minimum as the consensus restore step
    (per-rank checkpoint directories can legitimately disagree by an epoch
    or two: ranks are only loosely coupled between barriers, and the dead
    rank's async save may not have published before it died).
    """
    import pathlib

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    _atomic_write_json(d / f"reform-g{int(generation)}.drained.rank-{int(rank)}",
                       {"rank": int(rank), "available_step": available_step})


def read_reform_acks(directory, *, generation: int) -> "dict[int, dict]":
    """Reform acks at ``generation``: rank -> ack payload (torn reads skip)."""
    import pathlib

    d = pathlib.Path(directory)
    acks: dict = {}
    for p in d.glob(f"reform-g{int(generation)}.drained.rank-*"):
        suffix = p.name.rsplit("rank-", 1)[1]
        if not suffix.isdigit():
            continue
        payload = _read_json(p)
        if isinstance(payload, dict):
            acks[int(suffix)] = payload
    return acks


def publish_restore_step(directory, *, generation: int,
                         step: Optional[int]) -> None:
    """Commit the consensus restore step for a reform (supervisor side).

    ``None`` means no checkpoint is common to the whole reformed gang —
    every rank re-initializes from the seed and replays from epoch 0 (the
    same exactness argument as rollback-and-replay without a checkpoint).
    """
    import pathlib

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    _atomic_write_json(d / f"reform-g{int(generation)}.restore",
                       {"step": step})


def read_restore_step(directory, *, generation: int) -> "tuple[bool, Optional[int]]":
    """``(published, step)`` for the reform's consensus restore step."""
    import pathlib

    obj = _read_json(pathlib.Path(directory)
                     / f"reform-g{int(generation)}.restore")
    if not isinstance(obj, dict) or "step" not in obj:
        return (False, None)
    step = obj["step"]
    return (True, int(step) if step is not None else None)


def publish_generation(directory, generation: int) -> None:
    """Commit ``generation`` as the gang's current generation (supervisor)."""
    import pathlib

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    tmp = d / f".generation.{os.getpid()}.tmp"
    tmp.write_text(str(int(generation)), encoding="utf-8")
    os.replace(tmp, d / "generation")


def read_generation(directory) -> int:
    """The committed gang generation for ``directory`` (0 when unset)."""
    import pathlib

    try:
        return int((pathlib.Path(directory) / "generation")
                   .read_text(encoding="utf-8").strip())
    except (OSError, ValueError):
        return 0
