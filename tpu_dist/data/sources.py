"""Dataset sources: MNIST / Fashion-MNIST / CIFAR-10 with a tfds-like loader.

Re-provides the reference's TFDS surface (SURVEY.md D18; tf_dist_example.py:15,
27-29): ``load(name, split, as_supervised=True)`` returning a
:class:`~tpu_dist.data.pipeline.Dataset` of ``(image, label)`` tuples, for the
three benchmark datasets (BASELINE.md configs). Resolution order per dataset:

1. Local files — idx/npz archives under ``$TPU_DIST_DATA_DIR``,
   ``~/.keras/datasets``, or ``~/tensorflow_datasets`` (this framework never
   downloads; training environments are frequently egress-free).
2. Deterministic synthetic data with the real shapes/dtypes and
   class-separable structure (a fixed per-class template plus noise), so
   convergence tests remain meaningful — the same technique the survey's
   verification run used (SURVEY.md §3.5 "synthetic MNIST-shaped data").
"""

from __future__ import annotations

import gzip
import logging
import os
import pathlib
import struct
import zlib
from typing import Iterable, Mapping

import numpy as np

from tpu_dist.data.pipeline import Dataset

logger = logging.getLogger("tpu_dist.data")

DATA_DIR_ENV = "TPU_DIST_DATA_DIR"

#: name -> (image shape, num classes, official split sizes)
_SPECS: Mapping[str, tuple[tuple[int, int, int], int, Mapping[str, int]]] = {
    "mnist": ((28, 28, 1), 10, {"train": 60000, "test": 10000}),
    "fashion_mnist": ((28, 28, 1), 10, {"train": 60000, "test": 10000}),
    "cifar10": ((32, 32, 3), 10, {"train": 50000, "test": 10000}),
}

#: Synthetic sizes kept modest so zero-egress environments stay fast; override
#: with load(..., synthetic_size=N).
_SYNTHETIC_SIZES = {"train": 8192, "test": 1024}


def _search_dirs() -> list[pathlib.Path]:
    dirs = []
    env = os.environ.get(DATA_DIR_ENV)
    if env:
        dirs.append(pathlib.Path(env))
    home = pathlib.Path.home()
    dirs += [home / ".keras" / "datasets", home / "tensorflow_datasets"]
    return dirs


def _read_idx(path: pathlib.Path) -> np.ndarray:
    """Parse an IDX (MNIST-format) file, gzip or raw."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


_IDX_NAMES = {
    ("mnist", "train"): ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    ("mnist", "test"): ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    ("fashion_mnist", "train"): ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    ("fashion_mnist", "test"): ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _try_local(name: str, split: str) -> tuple[np.ndarray, np.ndarray] | None:
    shape, _, _ = _SPECS[name]
    for base in _search_dirs():
        # npz bundle (keras-style mnist.npz / cifar10.npz)
        for fname in (f"{name}.npz", f"{name}-{split}.npz"):
            p = base / fname
            if p.is_file():
                with np.load(p, allow_pickle=False) as z:
                    kx, ky = (("x_train", "y_train") if split == "train"
                              else ("x_test", "y_test"))
                    if kx in z:
                        x, y = z[kx], z[ky]
                    elif "images" in z:
                        x, y = z["images"], z["labels"]
                    else:
                        continue
                logger.info("loaded %s/%s from %s", name, split, p)
                return x.reshape((-1, *shape)), y.reshape(-1).astype(np.int64)
        # idx files (raw MNIST distribution), possibly under a subdir
        key = (name, split)
        if key in _IDX_NAMES:
            for sub in (base, base / name):
                ix, iy = _IDX_NAMES[key]
                for suffix in ("", ".gz"):
                    px, py = sub / (ix + suffix), sub / (iy + suffix)
                    if px.is_file() and py.is_file():
                        x = _read_idx(px).reshape((-1, *shape))
                        y = _read_idx(py).reshape(-1).astype(np.int64)
                        logger.info("loaded %s/%s from %s", name, split, sub)
                        return x, y
    return None


def _synthetic(name: str, split: str, size: int | None) -> tuple[np.ndarray, np.ndarray]:
    """Class-separable synthetic images: per-class low-frequency template +
    noise. Deterministic per (name, split) so every process/worker sees the
    same underlying dataset — required for the OFF-policy 'every worker has the
    full stream' semantics (README.md:113-120)."""
    shape, num_classes, _ = _SPECS[name]
    n = size or _SYNTHETIC_SIZES[split]
    # Stable across processes and runs (Python's hash() is salted per process,
    # which would give every worker a different dataset).
    seed = zlib.crc32(f"{name}/{split}".encode()) % (2**31)
    rng = np.random.default_rng(seed)
    h, w, c = shape
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    templates = np.stack([
        127.5 * (1 + np.sin(2 * np.pi * ((k + 1) * xx / w + k * yy / h)
                            / (1 + k % 3)))
        for k in range(num_classes)
    ])  # (classes, h, w)
    labels = rng.integers(num_classes, size=n).astype(np.int64)
    images = templates[labels][..., None].repeat(c, axis=-1)
    images = images + rng.normal(0, 24.0, size=(n, h, w, c))
    images = np.clip(images, 0, 255).astype(np.uint8)
    logger.warning(
        "no local copy of %s/%s found; using deterministic synthetic data "
        "(%d samples). Set $%s to use real data.", name, split, n, DATA_DIR_ENV)
    return images, labels


def load_arrays(name: str, split: str = "train", *,
                synthetic_size: int | None = None
                ) -> tuple[np.ndarray, np.ndarray]:
    """(images uint8 [N,H,W,C], labels int64 [N]) for a named dataset."""
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; available: {sorted(_SPECS)}")
    if split not in ("train", "test"):
        raise ValueError(f"split must be 'train' or 'test', got {split!r}")
    found = _try_local(name, split)
    if found is not None:
        return found
    return _synthetic(name, split, synthetic_size)


def _find_shard_files(name: str, split: str) -> list[pathlib.Path]:
    """Sharded npz archives (``{name}-{split}.shard-00002-of-00008.npz``) in
    the search dirs — the multi-file source shape AutoShardPolicy.FILE
    strides across workers (SURVEY.md D13).

    Files are grouped by their ``-of-NNNNN`` generation suffix and only a
    COMPLETE generation is served (all NNNNN files present) — re-sharding the
    same dataset with a different shard count leaves the old generation on
    disk, and silently mixing generations would duplicate every sample. With
    several complete generations, the most recently written wins."""
    pattern = f"{name}-{split}.shard-*-of-*.npz"
    for base in _search_dirs():
        for sub in (base, base / name):
            found = sorted(sub.glob(pattern)) if sub.is_dir() else []
            if not found:
                continue
            groups: dict[int, list[pathlib.Path]] = {}
            for p in found:
                try:
                    n = int(p.stem.rsplit("-of-", 1)[1])
                except (IndexError, ValueError):
                    continue
                groups.setdefault(n, []).append(p)
            complete = {n: fs for n, fs in groups.items() if len(fs) == n}
            if not complete:
                logger.warning(
                    "shard files under %s form no complete generation "
                    "(found %s); ignoring them",
                    sub, {n: len(fs) for n, fs in groups.items()})
                continue
            if len(complete) > 1:
                newest = max(
                    complete,
                    key=lambda n: max(p.stat().st_mtime for p in complete[n]))
                logger.warning(
                    "multiple complete shard generations for %s/%s under %s "
                    "(%s); using the newest (-of-%05d)", name, split, sub,
                    sorted(complete), newest)
                return complete[newest]
            return next(iter(complete.values()))
    return []


def _read_shard(path) -> "Iterable[tuple[np.ndarray, np.ndarray]]":
    with np.load(path, allow_pickle=False) as z:
        images, labels = z["images"], z["labels"]
    for i in range(len(labels)):
        yield images[i], np.int64(labels[i])


def write_sharded(directory, name: str, split: str, images: np.ndarray,
                  labels: np.ndarray, num_shards: int) -> list[pathlib.Path]:
    """Split (images, labels) into ``num_shards`` npz shard files that
    ``load`` discovers and serves as a file-backed Dataset — the preparation
    step for AutoShardPolicy.FILE jobs (each worker then reads a disjoint
    file subset)."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    if not 1 <= num_shards <= len(labels):
        raise ValueError(
            f"num_shards must be in [1, {len(labels)}], got {num_shards}")
    paths = []
    for s in range(num_shards):
        p = directory / f"{name}-{split}.shard-{s:05d}-of-{num_shards:05d}.npz"
        np.savez(p, images=images[s::num_shards], labels=labels[s::num_shards])
        paths.append(p)
    logger.info("wrote %d shard files for %s/%s under %s",
                num_shards, name, split, directory)
    return paths


def load(name: str, split: str = "train", *, as_supervised: bool = True,
         synthetic_size: int | None = None) -> Dataset:
    """tfds.load-shaped entry point (tf_dist_example.py:15 usage):
    ``load('mnist', split='train', as_supervised=True)`` yields
    ``(image, label)`` tuples; ``as_supervised=False`` yields dicts.

    If sharded npz files exist (see :func:`write_sharded`), the result is a
    file-backed Dataset (``num_files > 1``) eligible for
    AutoShardPolicy.FILE/AUTO file-level sharding across workers."""
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; available: {sorted(_SPECS)}")
    shards = _find_shard_files(name, split)
    if shards:
        # Per-file cardinality from the shard headers: npz loads lazily
        # per-array, so counting labels is cheap; fit() gets a known
        # steps_per_epoch even after FILE sharding strides the file list.
        counts = []
        for p in shards:
            with np.load(p, allow_pickle=False) as z:
                counts.append(len(z["labels"]))
        logger.info("loaded %s/%s from %d shard file(s) (%d samples)",
                    name, split, len(shards), sum(counts))
        if as_supervised:
            return Dataset.from_files(shards, _read_shard,
                                      file_cardinalities=counts)
        return Dataset.from_files(
            shards,
            lambda p: ({"image": x, "label": y} for x, y in _read_shard(p)),
            file_cardinalities=counts)
    x, y = load_arrays(name, split, synthetic_size=synthetic_size)
    if as_supervised:
        ds = Dataset.from_tensor_slices((x, y))
    else:
        ds = Dataset.from_tensor_slices({"image": x, "label": y})
    return ds


def num_classes(name: str) -> int:
    return _SPECS[name][1]


def image_shape(name: str) -> tuple[int, int, int]:
    return _SPECS[name][0]
