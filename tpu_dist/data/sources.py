"""Dataset sources: MNIST / Fashion-MNIST / CIFAR-10 with a tfds-like loader.

Re-provides the reference's TFDS surface (SURVEY.md D18; tf_dist_example.py:15,
27-29): ``load(name, split, as_supervised=True)`` returning a
:class:`~tpu_dist.data.pipeline.Dataset` of ``(image, label)`` tuples, for the
three benchmark datasets (BASELINE.md configs). Resolution order per dataset:

1. Local files — idx/npz archives under ``$TPU_DIST_DATA_DIR``,
   ``~/.keras/datasets``, or ``~/tensorflow_datasets`` (this framework never
   downloads; training environments are frequently egress-free).
2. Deterministic synthetic data with the real shapes/dtypes and
   class-separable structure (a fixed per-class template plus noise), so
   convergence tests remain meaningful — the same technique the survey's
   verification run used (SURVEY.md §3.5 "synthetic MNIST-shaped data").
"""

from __future__ import annotations

import collections.abc
import dataclasses
import gzip
import logging
import os
import pathlib
import struct
import zlib
from typing import Iterable, Mapping

import numpy as np

from tpu_dist.data.pipeline import Dataset

logger = logging.getLogger("tpu_dist.data")

DATA_DIR_ENV = "TPU_DIST_DATA_DIR"

#: name -> (image shape, num classes, official split sizes)
_SPECS: Mapping[str, tuple[tuple[int, int, int], int, Mapping[str, int]]] = {
    "mnist": ((28, 28, 1), 10, {"train": 60000, "test": 10000}),
    "fashion_mnist": ((28, 28, 1), 10, {"train": 60000, "test": 10000}),
    "cifar10": ((32, 32, 3), 10, {"train": 50000, "test": 10000}),
}

#: Synthetic sizes kept modest so zero-egress environments stay fast; override
#: with load(..., synthetic_size=N).
_SYNTHETIC_SIZES = {"train": 8192, "test": 1024}


def _search_dirs() -> list[pathlib.Path]:
    dirs = []
    env = os.environ.get(DATA_DIR_ENV)
    if env:
        dirs.append(pathlib.Path(env))
    home = pathlib.Path.home()
    dirs += [home / ".keras" / "datasets", home / "tensorflow_datasets"]
    return dirs


def _read_idx(path: pathlib.Path) -> np.ndarray:
    """Parse an IDX (MNIST-format) file, gzip or raw."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


_IDX_NAMES = {
    ("mnist", "train"): ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    ("mnist", "test"): ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    ("fashion_mnist", "train"): ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    ("fashion_mnist", "test"): ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _try_local(name: str, split: str) -> tuple[np.ndarray, np.ndarray] | None:
    shape, _, _ = _SPECS[name]
    for base in _search_dirs():
        # npz bundle (keras-style mnist.npz / cifar10.npz)
        for fname in (f"{name}.npz", f"{name}-{split}.npz"):
            p = base / fname
            if p.is_file():
                with np.load(p, allow_pickle=False) as z:
                    kx, ky = (("x_train", "y_train") if split == "train"
                              else ("x_test", "y_test"))
                    if kx in z:
                        x, y = z[kx], z[ky]
                    elif "images" in z:
                        x, y = z["images"], z["labels"]
                    else:
                        continue
                logger.info("loaded %s/%s from %s", name, split, p)
                return x.reshape((-1, *shape)), y.reshape(-1).astype(np.int64)
        # idx files (raw MNIST distribution), possibly under a subdir
        key = (name, split)
        if key in _IDX_NAMES:
            for sub in (base, base / name):
                ix, iy = _IDX_NAMES[key]
                for suffix in ("", ".gz"):
                    px, py = sub / (ix + suffix), sub / (iy + suffix)
                    if px.is_file() and py.is_file():
                        x = _read_idx(px).reshape((-1, *shape))
                        y = _read_idx(py).reshape(-1).astype(np.int64)
                        logger.info("loaded %s/%s from %s", name, split, sub)
                        return x, y
    return None


def _synthetic(name: str, split: str, size: int | None) -> tuple[np.ndarray, np.ndarray]:
    """Class-separable synthetic images: per-class low-frequency template +
    noise. Deterministic per (name, split) so every process/worker sees the
    same underlying dataset — required for the OFF-policy 'every worker has the
    full stream' semantics (README.md:113-120)."""
    shape, num_classes, _ = _SPECS[name]
    n = size or _SYNTHETIC_SIZES[split]
    # Stable across processes and runs (Python's hash() is salted per process,
    # which would give every worker a different dataset).
    seed = zlib.crc32(f"{name}/{split}".encode()) % (2**31)
    rng = np.random.default_rng(seed)
    h, w, c = shape
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    templates = np.stack([
        127.5 * (1 + np.sin(2 * np.pi * ((k + 1) * xx / w + k * yy / h)
                            / (1 + k % 3)))
        for k in range(num_classes)
    ])  # (classes, h, w)
    labels = rng.integers(num_classes, size=n).astype(np.int64)
    images = templates[labels][..., None].repeat(c, axis=-1)
    images = images + rng.normal(0, 24.0, size=(n, h, w, c))
    images = np.clip(images, 0, 255).astype(np.uint8)
    logger.warning(
        "no local copy of %s/%s found; using deterministic synthetic data "
        "(%d samples). Set $%s to use real data.", name, split, n, DATA_DIR_ENV)
    return images, labels


def _resolve_arrays(name: str, split: str, synthetic_size: int | None
                    ) -> tuple[np.ndarray, np.ndarray, bool]:
    """(images, labels, found_locally) — the one place the local-then-
    synthetic fallback order is defined (load_arrays and _one_split share
    it so the two entry points can never drift)."""
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; available: {sorted(_SPECS)}")
    valid = tuple(_SPECS[name][2])
    if split not in valid:
        raise ValueError(f"split must be one of {valid}, got {split!r}")
    found = _try_local(name, split)
    if found is not None:
        return (*found, True)
    return (*_synthetic(name, split, synthetic_size), False)


def load_arrays(name: str, split: str = "train", *,
                synthetic_size: int | None = None
                ) -> tuple[np.ndarray, np.ndarray]:
    """(images uint8 [N,H,W,C], labels int64 [N]) for a named dataset."""
    x, y, _ = _resolve_arrays(name, split, synthetic_size)
    return x, y


def _find_shard_files(name: str, split: str) -> list[pathlib.Path]:
    """Sharded npz archives (``{name}-{split}.shard-00002-of-00008.npz``) in
    the search dirs — the multi-file source shape AutoShardPolicy.FILE
    strides across workers (SURVEY.md D13).

    Files are grouped by their ``-of-NNNNN`` generation suffix and only a
    COMPLETE generation is served (all NNNNN files present) — re-sharding the
    same dataset with a different shard count leaves the old generation on
    disk, and silently mixing generations would duplicate every sample. With
    several complete generations, the most recently written wins."""
    pattern = f"{name}-{split}.shard-*-of-*.npz"
    for base in _search_dirs():
        for sub in (base, base / name):
            found = sorted(sub.glob(pattern)) if sub.is_dir() else []
            if not found:
                continue
            groups: dict[int, list[pathlib.Path]] = {}
            for p in found:
                try:
                    n = int(p.stem.rsplit("-of-", 1)[1])
                except (IndexError, ValueError):
                    continue
                groups.setdefault(n, []).append(p)
            complete = {n: fs for n, fs in groups.items() if len(fs) == n}
            if not complete:
                logger.warning(
                    "shard files under %s form no complete generation "
                    "(found %s); ignoring them",
                    sub, {n: len(fs) for n, fs in groups.items()})
                continue
            if len(complete) > 1:
                newest = max(
                    complete,
                    key=lambda n: max(p.stat().st_mtime for p in complete[n]))
                logger.warning(
                    "multiple complete shard generations for %s/%s under %s "
                    "(%s); using the newest (-of-%05d)", name, split, sub,
                    sorted(complete), newest)
                return complete[newest]
            return next(iter(complete.values()))
    return []


def _read_shard(path) -> "Iterable[tuple[np.ndarray, np.ndarray]]":
    with np.load(path, allow_pickle=False) as z:
        images, labels = z["images"], z["labels"]
    for i in range(len(labels)):
        yield images[i], np.int64(labels[i])


def write_sharded(directory, name: str, split: str, images: np.ndarray,
                  labels: np.ndarray, num_shards: int) -> list[pathlib.Path]:
    """Split (images, labels) into ``num_shards`` npz shard files that
    ``load`` discovers and serves as a file-backed Dataset — the preparation
    step for AutoShardPolicy.FILE jobs (each worker then reads a disjoint
    file subset)."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    if not 1 <= num_shards <= len(labels):
        raise ValueError(
            f"num_shards must be in [1, {len(labels)}], got {num_shards}")
    paths = []
    for s in range(num_shards):
        p = directory / f"{name}-{split}.shard-{s:05d}-of-{num_shards:05d}.npz"
        np.savez(p, images=images[s::num_shards], labels=labels[s::num_shards])
        paths.append(p)
    logger.info("wrote %d shard files for %s/%s under %s",
                num_shards, name, split, directory)
    return paths


@dataclasses.dataclass(frozen=True)
class SplitInfo:
    """One entry of :attr:`DatasetInfo.splits` — the tfds surface the
    reference touches is ``info.splits['train'].num_examples``."""
    name: str
    num_examples: int


class _SplitBuilder:
    """Shared lazy build-and-cache of per-split Datasets, so that
    ``load(name)`` (splits dict) and ``DatasetInfo`` can both defer the
    actual file reads / synthesis until a split is touched — the reference
    flow only ever consumes ``datasets['train']``."""

    def __init__(self, name: str, splits: tuple[str, ...],
                 as_supervised: bool, synthetic_size: int | None):
        self.name, self.splits = name, splits
        self._as_supervised, self._size = as_supervised, synthetic_size
        self._cache: dict[str, tuple[Dataset, bool]] = {}
        self._served: set[str] = set()

    def get(self, split: str, *, serve: bool = True) -> tuple[Dataset, bool]:
        if split not in self._cache:
            self._cache[split] = _one_split(
                self.name, split, self._as_supervised, self._size)
        if serve:
            self._served.add(split)
        return self._cache[split]

    def any_synthetic(self) -> bool:
        # Only splits actually SERVED (handed to the caller as a Dataset):
        # a pure info.splits[...].num_examples query builds the split but
        # must not make a run that trained on real data report synthetic.
        return any(self._cache[s][1] for s in self._served)


class _LazySplits(collections.abc.Mapping):
    """The ``datasets`` mapping ``load(name)`` returns: fixed key set,
    values built on first access."""

    def __init__(self, builder: _SplitBuilder):
        self._builder = builder

    def __getitem__(self, split: str) -> Dataset:
        if split not in self._builder.splits:
            raise KeyError(split)
        return self._builder.get(split)[0]

    def __iter__(self):
        return iter(self._builder.splits)

    def __len__(self):
        return len(self._builder.splits)

    def __repr__(self):
        return "{%s}" % ", ".join(
            f"{s!r}: <lazy Dataset>" for s in self._builder.splits)


class _LazySplitInfos(collections.abc.Mapping):
    """``info.splits``: SplitInfo built from the (lazily constructed)
    split's cardinality on first access."""

    def __init__(self, builder: _SplitBuilder):
        self._builder = builder

    def __getitem__(self, split: str) -> SplitInfo:
        if split not in self._builder.splits:
            raise KeyError(split)
        ds, _ = self._builder.get(split, serve=False)
        return SplitInfo(split, ds.cardinality())

    def __iter__(self):
        return iter(self._builder.splits)

    def __len__(self):
        return len(self._builder.splits)


class DatasetInfo:
    """Minimal ``tfds.core.DatasetInfo`` equivalent for the datasets this
    framework serves: split cardinalities plus the feature facts every
    consumer in the reference flow needs (image shape, class count).
    ``splits`` and ``synthetic`` evaluate lazily so asking about one split
    never pays for the others."""

    def __init__(self, name: str, builder: _SplitBuilder):
        self.name = name
        self._builder = builder
        self.image_shape, self.num_classes, _ = _SPECS[name]
        self.splits: Mapping[str, SplitInfo] = _LazySplitInfos(builder)

    @property
    def synthetic(self) -> bool:
        """True when any split SERVED SO FAR fell back to synthetic data
        (False before any split has been consumed — probing would defeat
        the lazy build)."""
        return self._builder.any_synthetic()

    def __repr__(self):
        return (f"DatasetInfo(name={self.name!r}, "
                f"image_shape={self.image_shape}, "
                f"num_classes={self.num_classes}, "
                f"splits={list(self._builder.splits)})")


def disable_progress_bar() -> None:
    """tfds.disable_progress_bar() analog (tf_dist_example.py:15). This
    loader never downloads, so there is no bar to disable; provided so the
    reference program transliterates line for line."""


def _one_split(name: str, split: str, as_supervised: bool,
               synthetic_size: int | None) -> tuple[Dataset, bool]:
    """(dataset, served_synthetic) for one named split. Resolution order:
    sharded npz files, then single-file local copies, then deterministic
    synthetic data (each source loaded at most once)."""
    shards = _find_shard_files(name, split)
    if shards:
        # Per-file cardinality from the shard headers: npz loads lazily
        # per-array, so counting labels is cheap; fit() gets a known
        # steps_per_epoch even after FILE sharding strides the file list.
        counts = []
        for p in shards:
            with np.load(p, allow_pickle=False) as z:
                counts.append(len(z["labels"]))
        logger.info("loaded %s/%s from %d shard file(s) (%d samples)",
                    name, split, len(shards), sum(counts))
        if as_supervised:
            return Dataset.from_files(shards, _read_shard,
                                      file_cardinalities=counts), False
        return Dataset.from_files(
            shards,
            lambda p: ({"image": x, "label": y} for x, y in _read_shard(p)),
            file_cardinalities=counts), False
    x, y, found_locally = _resolve_arrays(name, split, synthetic_size)
    if as_supervised:
        ds = Dataset.from_tensor_slices((x, y))
    else:
        ds = Dataset.from_tensor_slices({"image": x, "label": y})
    return ds, not found_locally


def load(name: str, split: str | None = None, *, as_supervised: bool = True,
         with_info: bool = False, synthetic_size: int | None = None):
    """tfds.load-shaped entry point (tf_dist_example.py:15, 27-31).

    Mirrors the reference's exact call shapes:

    - ``load('mnist', split='train')`` → one :class:`Dataset` of
      ``(image, label)`` tuples (``as_supervised=False`` → dicts).
    - ``load(name='mnist')`` (no split) → ``{'train': Dataset, 'test':
      Dataset}`` — the reference indexes ``datasets['train']``.
    - ``with_info=True`` → ``(result, DatasetInfo)`` where
      ``info.splits['train'].num_examples`` reports the cardinality of the
      data actually served (real files when found, synthetic otherwise).

    If sharded npz files exist (see :func:`write_sharded`), a split is a
    file-backed Dataset (``num_files > 1``) eligible for
    AutoShardPolicy.FILE/AUTO file-level sharding across workers."""
    if name not in _SPECS:
        raise ValueError(f"unknown dataset {name!r}; available: {sorted(_SPECS)}")
    all_splits = tuple(_SPECS[name][2])
    if split is not None and split not in all_splits:
        raise ValueError(f"split must be one of {all_splits}, got {split!r}")
    # The builder always spans every official split (tfds's info.splits
    # lists them all even when one split was requested); the returned
    # mapping/Dataset covers only what was asked for.
    builder = _SplitBuilder(name, all_splits, as_supervised, synthetic_size)
    result = _LazySplits(builder) if split is None else builder.get(split)[0]
    if not with_info:
        return result
    return result, DatasetInfo(name, builder)


def num_classes(name: str) -> int:
    return _SPECS[name][1]


def image_shape(name: str) -> tuple[int, int, int]:
    return _SPECS[name][0]
