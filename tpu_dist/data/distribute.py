"""Distributed dataset delivery: per-host streams -> global device arrays.

The TPU-native replacement for TF's distributed-dataset wrappers (SURVEY.md
D14): where ``experimental_distribute_dataset`` built per-worker iterators and
PerReplica value structures (tf:python/distribute/input_lib.py), here each
process iterates its host-local numpy pipeline and every step's local batch is
assembled into ONE global ``jax.Array`` sharded over the mesh's data axis
(``jax.make_array_from_process_local_data`` multi-process,
``jax.device_put`` single-process). The jitted train step consumes the global
array; XLA sees a single SPMD program — there is no per-replica bookkeeping.

Two delivery modes, matching the reference's two supported paths (SURVEY.md
§3.4):

* **with_options(OFF)** (the reference's chosen mode, tf_dist_example.py:34-37):
  every worker iterates the full stream with an independent shuffle; each
  process's batch is its own contribution, so the effective global batch is
  ``local_batch x num_processes`` distinct samples (README.md:113-120).
* **distribute (AUTO/DATA/FILE)** (the commented alternative,
  tf_dist_example.py:36): the user batches to GLOBAL_BATCH_SIZE; each process
  keeps its 1/num_processes slice, so the global array's leading dim is the
  global batch size.
"""

from __future__ import annotations

import logging
from typing import Iterator

import numpy as np

from tpu_dist.data.pipeline import AutoShardPolicy, Dataset, _map_structure
from tpu_dist.data.sharding import resolve_policy, shard_dataset

logger = logging.getLogger("tpu_dist.data")


def _find_unseeded_shuffle(dataset) -> bool:
    """True if the recorded combinator chain contains a shuffle whose order
    differs per process (``seed=None`` + reshuffle => each worker draws an
    independent RNG, pipeline.py:284-288)."""
    node = dataset
    while node is not None:
        t = getattr(node, "_transform", None)
        if (t is not None and t[0] == "shuffle"
                and (t[1].get("seed") is None or t[1].get("auto_seeded"))):
            # seed=None => fresh rng per pass; auto_seeded => a fixed seed
            # drawn independently PER PROCESS at construction
            # (pipeline.py shuffle) — both diverge across processes.
            return True
        node = getattr(node, "_parent", None)
    return False


def check_replicated_determinism(dataset, num_shards: int,
                                 num_processes: int, path: str) -> None:
    """Guard for meshes whose data axis does not span all processes.

    On pipe/model-spanning meshes several processes sit at the same data
    coordinate and must contribute byte-identical local batches to the same
    global-array region — a nondeterministic pipeline silently diverges
    training (ADVICE r4). An unseeded shuffle detected in the chain is a
    *certain* divergence, so it is rejected; opaque generators can't be
    proven either way, so everything else gets the warning.
    """
    if num_shards >= num_processes:
        return
    if _find_unseeded_shuffle(dataset):
        raise ValueError(
            f"{path}: unseeded shuffle on a mesh whose data axis does not "
            f"span all {num_processes} processes — processes at the same "
            "data coordinate would draw different samples for the same "
            "global batch region and training would silently diverge. "
            "Pass shuffle(..., seed=...) so same-coordinate processes "
            "produce identical streams.")
    logger.warning(
        "%s on a mesh whose data axis does not span all %d processes: "
        "processes at the same data coordinate MUST yield identical "
        "batches (deterministic pipeline, seeded or no shuffle) or "
        "training silently diverges", path, num_processes)


class DistributedDataset:
    """Iterable of mesh-placed global batches for a strategy.

    ``strategy.experimental_distribute_dataset(dataset)`` returns one of these
    (the tf_dist_example.py:36 analog); ``fit`` also auto-wraps plain Datasets
    the way the Keras trainer does (keras:src/backend/tensorflow/
    trainer.py:750-755, SURVEY.md D15).
    """

    def __init__(self, dataset: Dataset, strategy,
                 policy: AutoShardPolicy | None = None,
                 prefetch: int | None = 2,
                 allow_device_transform: bool = False):
        import jax

        self._strategy = strategy
        self._num_processes = jax.process_count()
        self._process_index = jax.process_index()
        # Input shards follow the DATA-axis process structure, not the raw
        # process count: pipe/model-only multi-process meshes put every
        # process at the same data coordinate, and those processes must
        # feed IDENTICAL replicated batches (strategy.input_shard_info).
        info = getattr(strategy, "input_shard_info", None)
        self._num_shards, self._shard_id = (
            info() if info is not None
            else (self._num_processes, self._process_index))
        effective = (policy if policy is not None
                     else dataset.auto_shard_policy)
        if effective == AutoShardPolicy.OFF:
            # Reference mode: full stream per worker, local batch as produced.
            self._local = dataset
            self._policy = AutoShardPolicy.OFF
            check_replicated_determinism(
                dataset, self._num_shards, self._num_processes,
                "AutoShardPolicy.OFF")
        else:
            self._policy = resolve_policy(dataset, self._num_shards, effective)
            # ADVICE r4: same-data-coordinate processes get the same shard
            # id, so the sharded stream they build must be deterministic too
            # — the hazard is not OFF-specific.
            check_replicated_determinism(
                dataset, self._num_shards, self._num_processes,
                f"AutoShardPolicy.{self._policy.name}")
            self._local = shard_dataset(
                dataset, self._num_shards, self._shard_id,
                self._policy, pre_batched=True)
        # Vectorized chain rewrite (the Grappler map_and_batch/vectorize
        # analog, data/vectorize.py): index math + batched gathers replace
        # the per-element generator walk when the chain's shape allows.
        # The u8-over-the-wire + scale-on-device split is only taken when
        # the consumer declares it will apply device transforms (the
        # Trainer does; a user iterating this object in a custom loop has
        # no such obligation, so their batches must stay host-normalized
        # float32).
        from tpu_dist.data import vectorize

        fast = vectorize.try_rewrite(
            self._local,
            defer_scale_to_device=None if allow_device_transform else False)
        if fast is not None:
            self._local = fast
        # Host input off the step critical path by default (SURVEY.md §3.4 /
        # hard-part #5): background-prefetch the local stream unless the user
        # already did, mirroring TF's distribute-path auto-prefetch.
        # ``prefetch=None`` opts out.
        if prefetch and not getattr(self._local, "_prefetched", False):
            self._local = self._local.prefetch(prefetch)
        if self._num_processes > 1:
            logger.info(
                "DistributedDataset: policy=%s process=%d/%d",
                self._policy.name, self._process_index, self._num_processes)

    @property
    def auto_shard_policy(self) -> AutoShardPolicy:
        return self._policy

    @property
    def device_transform(self):
        """Jittable fn the trainer applies to the placed x batch inside the
        compiled step (None for plain pipelines) — the device half of the
        u8-over-the-wire normalization split."""
        return getattr(self._local, "_device_transform", None)

    def iter_local(self) -> Iterator:
        """Validated HOST batches (numpy) — the pre-placement stream. Used by
        the multi-step (steps_per_execution) path, which stacks K host
        batches before one device placement."""
        devices_per_process = len(self._strategy.mesh.local_devices)

        for batch in self._local:
            batch = _map_structure(np.asarray, batch)
            leading = {a.shape[0] for a in _leaves(batch)}
            if len(leading) != 1:
                raise ValueError(
                    f"batch components disagree on batch dim: {leading}")
            (b,) = leading
            if b % devices_per_process:
                raise ValueError(
                    f"per-process batch {b} not divisible by {devices_per_process} "
                    "local device(s); adjust the batch size so every replica "
                    "gets an equal shard (same constraint as TF per-replica "
                    "splitting)")
            yield batch

    def __iter__(self) -> Iterator:
        for batch in self.iter_local():
            yield self._strategy.distribute_batch(batch)


def _leaves(tree):
    out = []
    _map_structure(out.append, tree)
    return out
