"""Auto-shard policy application — TF's Grappler ``auto_shard`` pass, natively.

TF implements dataset sharding as a C++ graph rewrite over the dataset op graph
(tensorflow/core/grappler/optimizers/data/auto_shard.cc, SURVEY.md D13). Our
pipeline is a host-side element stream, so every policy reduces to a plain
index transformation — same contract, no graph rewriting:

* OFF  — untouched: every worker iterates the full stream. The reference's
  chosen mode (tf_dist_example.py:35; README.md:113-120 explains why: each
  worker draws an independently-shuffled batch, gradients still all-reduced).
* DATA — each worker keeps every ``num_shards``-th element (applied pre-batch)
  or its contiguous 1/num_shards slice of each batch (applied post-batch, the
  rebatch path TF uses for pre-batched distributed datasets).
* FILE — shard source files across workers; in-memory sources have one "file",
  so explicit FILE over fewer files than workers raises (TF errors likewise),
  while AUTO falls back to DATA with a warning (TF's fallback behavior).
* HINT — treated as DATA (TF replaces SHARD_HINT placeholders with the
  worker's shard index).
"""

from __future__ import annotations

import logging

from tpu_dist.data.pipeline import AutoShardPolicy, Dataset

logger = logging.getLogger("tpu_dist.data")


def resolve_policy(dataset: Dataset, num_shards: int,
                   policy: AutoShardPolicy | None = None) -> AutoShardPolicy:
    """Collapse AUTO/HINT into the concrete policy that will be applied."""
    if policy is None:
        policy = dataset.auto_shard_policy
    if policy == AutoShardPolicy.HINT:
        return AutoShardPolicy.DATA
    if policy == AutoShardPolicy.AUTO:
        # FILE needs a file-backed source, which in-memory pipelines don't
        # have yet — AUTO must always yield a working sharding, so it resolves
        # to DATA unconditionally (TF's own AUTO falls back to DATA when file
        # sharding isn't applicable).
        if num_shards > 1 and dataset.num_files < num_shards:
            logger.warning(
                "AutoShardPolicy.AUTO: source has %d file(s) < %d workers; "
                "falling back to DATA sharding", dataset.num_files, num_shards)
        return AutoShardPolicy.DATA
    return policy


def shard_dataset(dataset: Dataset, num_shards: int, index: int,
                  policy: AutoShardPolicy | None = None,
                  *, pre_batched: bool = False) -> Dataset:
    """Apply an auto-shard policy for worker ``index`` of ``num_shards``.

    ``pre_batched=True`` means elements are already batches (the
    ``experimental_distribute_dataset`` path, where the user batched to the
    global batch size, tf_dist_example.py:33+36): DATA sharding then slices
    each batch instead of striding elements.
    """
    if not 0 <= index < num_shards:
        raise ValueError(f"shard index {index} not in [0, {num_shards})")
    if num_shards == 1:
        return dataset
    concrete = resolve_policy(dataset, num_shards, policy)

    if concrete == AutoShardPolicy.OFF:
        return dataset

    if concrete == AutoShardPolicy.FILE:
        if dataset.num_files < num_shards:
            raise ValueError(
                f"AutoShardPolicy.FILE requires >= {num_shards} source files, "
                f"dataset has {dataset.num_files}. Use DATA or OFF "
                "(tf.data raises the same way when files < workers).")
        raise NotImplementedError(
            "FILE sharding requires a file-backed source; in-memory sources "
            "expose one logical file. Multi-file sources arrive with the "
            "sharded-input-file loader.")

    assert concrete == AutoShardPolicy.DATA
    if pre_batched:
        return _slice_batches(dataset, num_shards, index)
    return dataset.shard(num_shards, index)


def _slice_batches(dataset: Dataset, num_shards: int, index: int) -> Dataset:
    """Per-batch contiguous slice — TF's rebatch-then-shard for pre-batched
    distributed datasets (tf:python/distribute/input_lib.py path)."""
    import numpy as np

    def factory():
        for batch in dataset:
            def _slice(a):
                a = np.asarray(a)
                b = a.shape[0]
                if b % num_shards:
                    raise ValueError(
                        f"global batch {b} not divisible by {num_shards} "
                        "workers; make GLOBAL_BATCH_SIZE a multiple of the "
                        "worker count (tf_dist_example.py:17-18 semantics)")
                per = b // num_shards
                return a[index * per:(index + 1) * per]

            from tpu_dist.data.pipeline import _map_structure
            yield _map_structure(_slice, batch)

    return dataset._derive(factory)
