"""Auto-shard policy application — TF's Grappler ``auto_shard`` pass, natively.

TF implements dataset sharding as a C++ graph rewrite over the dataset op graph
(tensorflow/core/grappler/optimizers/data/auto_shard.cc, SURVEY.md D13). Our
pipeline is a host-side element stream, so every policy reduces to a plain
index transformation — same contract, no graph rewriting:

* OFF  — untouched: every worker iterates the full stream. The reference's
  chosen mode (tf_dist_example.py:35; README.md:113-120 explains why: each
  worker draws an independently-shuffled batch, gradients still all-reduced).
* DATA — each worker keeps every ``num_shards``-th element (applied pre-batch)
  or its contiguous 1/num_shards slice of each batch (applied post-batch, the
  rebatch path TF uses for pre-batched distributed datasets).
* FILE — re-root the combinator chain on a strided subset of the source's
  files (worker i reads files i, i+n, ...), rebatching the final global batch
  to the per-worker size on the pre-batched path; explicit FILE over fewer
  files than workers (or a non-file source) raises (TF errors likewise),
  while AUTO prefers FILE when applicable and falls back to DATA with a
  warning (TF's fallback behavior).
* HINT — treated as DATA (TF replaces SHARD_HINT placeholders with the
  worker's shard index).
"""

from __future__ import annotations

import logging

from tpu_dist.data.pipeline import AutoShardPolicy, Dataset

logger = logging.getLogger("tpu_dist.data")


def _source_of(dataset: Dataset) -> Dataset:
    """Walk the combinator chain to its root source."""
    d = dataset
    while d._parent is not None:
        d = d._parent
    return d


def _is_file_shardable(dataset: Dataset, num_shards: int) -> bool:
    """FILE sharding applies iff the chain roots in a file-backed source with
    enough files AND every link is replayable (records its transform)."""
    d = dataset
    while d._parent is not None:
        if d._transform is None:
            return False  # opaque derivation; cannot rewrite through it
        d = d._parent
    return (d._file_shard_fn is not None
            and dataset.num_files >= num_shards)


def _files_divide_evenly(dataset: Dataset, num_shards: int) -> bool:
    """Synchronous SPMD needs every process in lockstep: an uneven file split
    gives workers streams of different lengths, desyncing the per-step global
    batch assembly. (TF tolerates unevenness because its per-worker iterators
    are independent; our single-program model cannot.)

    Checks the file COUNT divides evenly AND — when the source knows its
    per-file element counts — that every worker's strided file subset sums
    to the same element total (4 files over 2 workers with counts
    [100, 50, 50, 50] would still desync despite 4 % 2 == 0)."""
    if dataset.num_files % num_shards != 0:
        return False
    root = dataset
    while root._parent is not None:
        root = root._parent
    counts = getattr(root, "_file_cardinalities", None)
    if counts:
        totals = {sum(counts[i::num_shards]) for i in range(num_shards)}
        return len(totals) == 1
    return True


def resolve_policy(dataset: Dataset, num_shards: int,
                   policy: AutoShardPolicy | None = None) -> AutoShardPolicy:
    """Collapse AUTO/HINT into the concrete policy that will be applied."""
    if policy is None:
        policy = dataset.auto_shard_policy
    if policy == AutoShardPolicy.HINT:
        return AutoShardPolicy.DATA
    if policy == AutoShardPolicy.AUTO:
        # TF's AUTO tries FILE first and falls back to DATA when the source
        # isn't file-based or has too few files (auto_shard.cc fallback).
        # Extra guard beyond TF: AUTO only picks FILE when the file count
        # divides evenly — an uneven split would desync the sync-SPMD step.
        if num_shards <= 1:
            return AutoShardPolicy.DATA
        if (_is_file_shardable(dataset, num_shards)
                and _files_divide_evenly(dataset, num_shards)):
            return AutoShardPolicy.FILE
        logger.warning(
            "AutoShardPolicy.AUTO: source has %d file(s) for %d workers "
            "(not file-backed, too few, or not evenly divisible); falling "
            "back to DATA sharding", dataset.num_files, num_shards)
        return AutoShardPolicy.DATA
    return policy


def shard_dataset(dataset: Dataset, num_shards: int, index: int,
                  policy: AutoShardPolicy | None = None,
                  *, pre_batched: bool = False) -> Dataset:
    """Apply an auto-shard policy for worker ``index`` of ``num_shards``.

    ``pre_batched=True`` means elements are already batches (the
    ``experimental_distribute_dataset`` path, where the user batched to the
    global batch size, tf_dist_example.py:33+36): DATA sharding then slices
    each batch instead of striding elements.
    """
    if not 0 <= index < num_shards:
        raise ValueError(f"shard index {index} not in [0, {num_shards})")
    if num_shards == 1:
        return dataset
    concrete = resolve_policy(dataset, num_shards, policy)

    if concrete == AutoShardPolicy.OFF:
        return dataset

    if concrete == AutoShardPolicy.FILE:
        if dataset.num_files < num_shards:
            raise ValueError(
                f"AutoShardPolicy.FILE requires >= {num_shards} source files, "
                f"dataset has {dataset.num_files}. Use DATA or OFF "
                "(tf.data raises the same way when files < workers).")
        if not _is_file_shardable(dataset, num_shards):
            raise ValueError(
                "AutoShardPolicy.FILE requires a file-backed source "
                "(Dataset.from_files / sources.load over sharded files); "
                "this pipeline roots in an in-memory source. Use DATA or OFF.")
        if not _files_divide_evenly(dataset, num_shards):
            # Deviation from TF (which lets some workers read more files):
            # uneven per-worker streams desync synchronous SPMD training, so
            # fail fast with the fix instead of hanging at a collective.
            raise ValueError(
                f"AutoShardPolicy.FILE: {dataset.num_files} files do not "
                f"divide evenly over {num_shards} workers (by file count or "
                "by per-file element totals); synchronous training requires "
                "equal-length worker streams. Re-shard the source "
                "(sources.write_sharded) to a multiple of the worker count "
                "with balanced shards, or use DATA.")
        return _file_shard(dataset, num_shards, index, rebatch=pre_batched)

    assert concrete == AutoShardPolicy.DATA
    if pre_batched:
        return _slice_batches(dataset, num_shards, index)
    return dataset.shard(num_shards, index)


def _file_shard(dataset: Dataset, num_shards: int, index: int,
                *, rebatch: bool) -> Dataset:
    """Re-root the combinator chain on a strided file subset — the
    element-stream analog of TF's auto_shard graph rewrite pushing the shard
    op down to the file reader (auto_shard.cc, SURVEY.md D13).

    ``rebatch=True`` (the pre-batched ``experimental_distribute_dataset``
    path) additionally rewrites the final ``batch(GLOBAL)`` into
    ``batch(GLOBAL / num_shards)`` — TF's rebatch pass: the user batched to
    the global size, but each worker now holds only its file slice.
    """
    transforms: list[tuple[str, dict]] = []
    d = dataset
    while d._parent is not None:
        transforms.append(d._transform)  # validated by _is_file_shardable
        d = d._parent
    transforms.reverse()  # root-most first

    if rebatch:
        for i in range(len(transforms) - 1, -1, -1):
            name, kw = transforms[i]
            if name == "batch":
                b = kw["batch_size"]
                if b % num_shards:
                    raise ValueError(
                        f"global batch {b} not divisible by {num_shards} "
                        "workers; make GLOBAL_BATCH_SIZE a multiple of the "
                        "worker count (tf_dist_example.py:17-18 semantics)")
                transforms[i] = ("batch", {**kw,
                                           "batch_size": b // num_shards})
                break

    out = d._file_shard_fn(num_shards, index)
    for t in transforms:
        out = out._replay_transform(t)
    return out


def _slice_batches(dataset: Dataset, num_shards: int, index: int) -> Dataset:
    """Per-batch contiguous slice — TF's rebatch-then-shard for pre-batched
    distributed datasets (tf:python/distribute/input_lib.py path)."""
    import numpy as np

    def factory():
        for batch in dataset:
            def _slice(a):
                a = np.asarray(a)
                b = a.shape[0]
                if b % num_shards:
                    raise ValueError(
                        f"global batch {b} not divisible by {num_shards} "
                        "workers; make GLOBAL_BATCH_SIZE a multiple of the "
                        "worker count (tf_dist_example.py:17-18 semantics)")
                per = b // num_shards
                return a[index * per:(index + 1) * per]

            from tpu_dist.data.pipeline import _map_structure
            yield _map_structure(_slice, batch)

    return dataset._derive(factory)
