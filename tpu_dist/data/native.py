"""Native (C++) input-pipeline core with transparent numpy fallback.

Reference parity: TF's input pipeline executes in C++ tf.data kernels
(SURVEY.md D13 marks the pipeline "Python + C++"); this module is tpu-dist's
native loader core. The hot host-side path — assemble a shuffled, normalized
global batch from an in-memory array dataset — is one fused multithreaded C++
pass (``loader.cpp``): gather rows by shuffled index and convert
uint8 -> float32 * scale in the same sweep, exactly the work of the
reference's ``.map(scale) ... .shuffle(...).batch(...)`` chain
(tf_dist_example.py:20-33).

The extension compiles lazily with g++ the first time it's needed and caches
the .so next to the source; without a toolchain everything falls back to
numpy with identical results (the shuffle is seeded SplitMix64 Fisher-Yates
in both paths, so batches are bit-identical native or not).

    ds = native_pipeline("mnist", global_batch_size=128, seed=0)
    model.fit(ds, epochs=10, steps_per_epoch=20)
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("tpu_dist.native")

_SRC_DIR = pathlib.Path(__file__).parent / "_native"
_SO_PATH = _SRC_DIR / "libtpu_dist_loader.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[pathlib.Path]:
    # Compile to a per-process temp file, then os.replace() it into place:
    # several workers on one host may race the first build, and replace() is
    # atomic so no process can ever CDLL a half-written .so.
    src = _SRC_DIR / "loader.cpp"
    tmp = _SO_PATH.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", str(src),
           "-o", str(tmp)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        logger.info("built native loader: %s", _SO_PATH)
        return _SO_PATH
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.warning("native loader build failed (%s %s); using numpy "
                       "fallback", e, detail.decode(errors="replace")[:500])
        return None
    finally:
        tmp.unlink(missing_ok=True)


def _load() -> Optional[ctypes.CDLL]:
    """The loader library, building it on first use; None => numpy fallback."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        src = _SRC_DIR / "loader.cpp"
        stale = (_SO_PATH.exists() and src.exists()
                 and src.stat().st_mtime > _SO_PATH.stat().st_mtime)
        if _SO_PATH.exists() and not stale:
            path = _SO_PATH
        else:
            path = _build()
            if path is None and _SO_PATH.exists():
                # Rebuild failed (e.g. no toolchain) but a prebuilt — possibly
                # stale — library exists: keep using it rather than losing the
                # native path entirely.
                logger.warning("using existing (possibly stale) %s", _SO_PATH)
                path = _SO_PATH
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError as e:
            # A corrupt/foreign .so must degrade to the numpy fallback, not
            # propagate out of the data pipeline.
            logger.warning("loading native loader %s failed (%s); using "
                           "numpy fallback", path, e)
            _build_failed = True
            return None
        lib.tpu_dist_gather_scale_u8_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_void_p, ctypes.c_int]
        lib.tpu_dist_gather_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p]
        lib.tpu_dist_shuffled_indices.argtypes = [
            ctypes.c_int64, ctypes.c_uint64, ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# -- primitive ops (native with numpy fallback, identical semantics) ----------


def shuffled_indices(n: int, seed: int) -> np.ndarray:
    """Seeded Fisher-Yates permutation of [0, n) — same stream native or not."""
    out = np.empty(n, dtype=np.int64)
    lib = _load()
    if lib is not None:
        lib.tpu_dist_shuffled_indices(
            n, ctypes.c_uint64(seed & (2**64 - 1)),
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    # Pure-python fallback: identical SplitMix64 Fisher-Yates stream.
    out[:] = np.arange(n, dtype=np.int64)
    mask = (1 << 64) - 1
    state = seed & mask
    for i in range(n - 1, 0, -1):
        state = (state + 0x9E3779B97F4A7C15) & mask
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
        z = z ^ (z >> 31)
        j = z % (i + 1)
        tmp = int(out[i])
        out[i] = out[j]
        out[j] = tmp
    return out


def gather_scale(images: np.ndarray, idx: np.ndarray, scale: float,
                 n_threads: int | None = None) -> np.ndarray:
    """out[i] = float32(images[idx[i]]) * scale, fused gather+normalize."""
    images = np.ascontiguousarray(images)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    row_elems = int(np.prod(images.shape[1:], dtype=np.int64))
    out = np.empty((len(idx), *images.shape[1:]), dtype=np.float32)
    lib = _load()
    if lib is not None and images.dtype == np.uint8:
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        lib.tpu_dist_gather_scale_u8_f32(
            images.ctypes.data_as(ctypes.c_void_p),
            idx.ctypes.data_as(ctypes.c_void_p),
            len(idx), row_elems, ctypes.c_float(scale),
            out.ctypes.data_as(ctypes.c_void_p), n_threads)
        return out
    # float32 multiply to match the native path's arithmetic exactly.
    np.multiply(images[idx].astype(np.float32), np.float32(scale), out=out)
    return out


def gather_labels(labels: np.ndarray, idx: np.ndarray) -> np.ndarray:
    labels = np.ascontiguousarray(labels, dtype=np.int64)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    lib = _load()
    if lib is not None and labels.ndim == 1:
        out = np.empty(len(idx), dtype=np.int64)
        lib.tpu_dist_gather_i64(
            labels.ctypes.data_as(ctypes.c_void_p),
            idx.ctypes.data_as(ctypes.c_void_p),
            len(idx), 1, out.ctypes.data_as(ctypes.c_void_p))
        return out
    return labels[idx]


# -- pipeline front-end -------------------------------------------------------


def native_pipeline(name: str, *, global_batch_size: int, seed: int = 0,
                    split: str = "train", scale: float = 1.0 / 255.0,
                    drop_remainder: bool = True,
                    synthetic_size: int | None = None,
                    transfer: str = "auto"):
    """A ``Dataset`` over a named source whose batches are assembled by the
    native core: per-epoch seeded reshuffle, fused gather+normalize.

    Semantically equals ``load(name, "train").map(scale).cache().shuffle(N).batch(B)``
    (the reference pipeline, tf_dist_example.py:20-33) with a full-dataset
    shuffle buffer; plugs into ``fit``/``experimental_distribute_dataset``
    like any other Dataset, including the shard-policy machinery.

    ``transfer``: ``"float32"`` normalizes on the host (the fused C++
    gather+scale); ``"uint8"`` ships the raw bytes and attaches the scale
    as a device transform the trainer fuses into the compiled step — 4x
    fewer bytes over the host->device link, which is the streaming path's
    bottleneck (measured ~18 MB/s through this host's TPU tunnel).
    ``"auto"`` picks uint8 on non-CPU backends when the source is uint8.
    """
    from tpu_dist.data.pipeline import Dataset
    from tpu_dist.data.sources import load_arrays

    images, labels = load_arrays(name, split, synthetic_size=synthetic_size)
    n = len(images)
    if global_batch_size > n:
        raise ValueError(f"batch {global_batch_size} exceeds dataset size {n}")
    if transfer == "auto":
        import jax

        transfer = ("uint8" if jax.default_backend() != "cpu"
                    and images.dtype == np.uint8 else "float32")
    if transfer == "uint8" and images.dtype != np.uint8:
        raise ValueError(
            f"transfer='uint8' requires a uint8 source, got {images.dtype}")
    if transfer not in ("uint8", "float32"):
        raise ValueError(f"unknown transfer mode {transfer!r}")
    epoch_counter = [0]
    steps = (n // global_batch_size if drop_remainder
             else -(-n // global_batch_size))
    device_scale = transfer == "uint8"
    if device_scale:
        images = np.ascontiguousarray(images)

    def factory():
        # Fresh permutation each pass — Dataset re-invokes the factory per
        # epoch, reproducing shuffle-per-epoch semantics deterministically.
        perm = shuffled_indices(n, seed + 0x9E37 * epoch_counter[0])
        epoch_counter[0] += 1
        for s in range(steps):
            idx = perm[s * global_batch_size:(s + 1) * global_batch_size]
            if device_scale:
                yield images[idx], gather_labels(labels, idx)
            else:
                yield (gather_scale(images, idx, scale),
                       gather_labels(labels, idx))

    ds = Dataset(factory, cardinality=steps)
    if device_scale:
        from tpu_dist.data.vectorize import _device_scale_fn

        ds._device_transform = _device_scale_fn(scale)
    return ds
