"""Device-resident datasets: upload once, assemble every batch ON device.

TPU-native input delivery for datasets that fit in HBM (MNIST is 47 MB,
CIFAR-10 157 MB as uint8 — trivial next to 16 GB): the whole dataset is
placed on the mesh once (replicated), and each training step's batch is
gathered on device by a tiny jitted ``take`` driven by host-generated
shuffled indices. Per step, the host transfers ONLY the index vector
(kilobytes), never the pixels.

Why this exists (SURVEY.md hard-part #5, §3.4): the reference keeps input off
the critical path with ``cache()`` + host prefetch, which is the right design
when host->device DMA is cheap. On TPU — and especially through a tunneled
runtime — per-step bulk H2D transfers dominate the step itself (measured here:
a 6.4 MB stacked batch costs 100-800 ms interleaved with training dispatches,
vs ~0.4 ms of compute per step). Caching device-side is the idiomatic fix:
same composition semantics (map/scale, per-epoch reshuffle, batch), one
transfer total.

Semantics: equivalent to the reference pipeline
``load(name, "train").map(scale).cache().shuffle(FULL).batch(B, drop_remainder=True)``
with a SEEDED per-epoch reshuffle shared by all processes — i.e. the
single-program Mirrored semantic: one global permutation, every replica
taking its shard of each global batch (SURVEY.md D14).

    ds = device_pipeline("mnist", global_batch_size=128)
    model.fit(ds, epochs=10, steps_per_epoch=20)
"""

from __future__ import annotations

import logging
from typing import Iterator, Optional

import numpy as np

logger = logging.getLogger("tpu_dist.data")


class DeviceDataset:
    """A device-resident (images, labels) dataset with on-device batching.

    ``fit``/``evaluate`` recognize this type and pull device-ready batches
    from it directly (no host pipeline, no per-step bulk transfer).
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray, *,
                 global_batch_size: int, strategy=None, seed: int = 0,
                 shuffle: bool = True, scale: Optional[float] = 1.0 / 255.0,
                 scale_op: str = "mul"):
        if scale_op not in ("mul", "div"):
            raise ValueError(f"scale_op must be 'mul' or 'div', "
                             f"got {scale_op!r}")
        n = len(images)
        if len(labels) != n:
            raise ValueError(f"images/labels disagree: {n} vs {len(labels)}")
        if global_batch_size > n:
            raise ValueError(
                f"batch {global_batch_size} exceeds dataset size {n}")
        self._host_x = np.ascontiguousarray(images)
        self._host_y = np.ascontiguousarray(labels.astype(np.int64))
        self._n = n
        self._batch = int(global_batch_size)
        self._seed = seed
        self._shuffle = shuffle
        self._scale = None if scale is None else float(scale)
        #: mul vs div is bit-level: x / 255.0 != x * (1/255) in the last
        #: ulp, and promoted chains (vectorize.py) replay the user's exact
        #: formula.
        self._scale_op = scale_op
        self._strategy = strategy  # None => bind to fit()'s strategy lazily
        self._dx = self._dy = None
        self._epoch = 0
        self._eval_pass = 0  # eval has its own counter/seed stream (below)
        self._perm: Optional[np.ndarray] = None
        self._pos = 0
        self._gather_batch = None
        self._gather_stack = None

    def bind_strategy(self, strategy) -> "DeviceDataset":
        """Pin (or re-pin) the mesh this dataset lives on. ``fit`` calls this
        with the model's strategy, so a dataset built outside
        ``strategy.scope()`` still lands on the training mesh; rebinding to a
        different strategy re-uploads from the kept host arrays."""
        if strategy is None or strategy is self._strategy:
            return self
        if self._strategy is not None and self._dx is not None:
            logger.info("DeviceDataset: re-homing onto a different strategy "
                        "(%d replicas)", strategy.num_replicas_in_sync)
        self._strategy = strategy
        self._dx = self._dy = None
        self._gather_batch = None
        self._gather_stack = None
        return self

    def _ensure_placed(self) -> None:
        """Upload once onto the bound strategy's mesh, replicated (identical
        source arrays on every process — sources.py is deterministic per
        (name, split)). Kept in the source dtype (uint8 for image archives):
        4x less HBM than float32; cast+scale runs inside the gather program."""
        if self._dx is not None:
            return
        from tpu_dist.parallel import mesh as mesh_lib
        from tpu_dist.parallel.strategy import get_strategy

        if self._strategy is None:
            self._strategy = get_strategy()
        n_dev = self._strategy.num_replicas_in_sync
        if self._batch % n_dev:
            raise ValueError(
                f"global batch {self._batch} not divisible by {n_dev} "
                "devices")
        self._mesh = self._strategy.mesh
        self._axis = self._strategy.data_axis
        self._dx, self._dy = mesh_lib.replicate(
            (self._host_x, self._host_y), self._mesh)

    # -- introspection (Dataset-compatible surface) ---------------------------

    def cardinality(self) -> int:
        """Batches per epoch (drop-remainder: device shapes are static)."""
        return self._n // self._batch

    @property
    def global_batch_size(self) -> int:
        return self._batch

    @property
    def element_spec(self):
        return (self._host_x.shape[1:], self._host_y.shape[1:])

    # -- gather programs ------------------------------------------------------

    def _build_gather(self, stacked: bool):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        scale = self._scale
        scale_op = self._scale_op
        spec = (PartitionSpec(None, self._axis) if stacked
                else PartitionSpec(self._axis))
        out_sh = NamedSharding(self._mesh, spec)

        def gather(dx, dy, idx):
            xb = jnp.take(dx, idx, axis=0)
            if scale is not None:
                xf = xb.astype(jnp.float32)
                xb = (xf * jnp.float32(scale) if scale_op == "mul"
                      else xf / jnp.float32(scale))
            return xb, jnp.take(dy, idx, axis=0)

        return jax.jit(gather, out_shardings=(out_sh, out_sh))

    # The host index vector is passed to the gather jit AS NUMPY: every
    # process computes the same seeded permutation, so jit treats it as
    # replicated and the SPMD partitioner lets each device gather only its
    # output shard's rows. (An explicit device_put with a NamedSharding was
    # measured ~10x slower per execution on the tunneled TPU runtime; the
    # plain dispatch-time transfer of a few KB is the fast path.)

    # -- iteration ------------------------------------------------------------

    def _next_indices(self, count: int) -> np.ndarray:
        """``count`` sample indices, continuing the per-epoch permutation
        (fresh seeded reshuffle per pass — tf.data reshuffle semantics with a
        shared seed, so every process agrees)."""
        out = np.empty(count, dtype=np.int32)
        filled = 0
        while filled < count:
            if self._perm is None or self._pos >= (
                    self.cardinality() * self._batch):
                if self._shuffle:
                    rng = np.random.default_rng(self._seed + self._epoch)
                    self._perm = rng.permutation(self._n).astype(np.int32)
                else:
                    self._perm = np.arange(self._n, dtype=np.int32)
                self._epoch += 1
                self._pos = 0
            take = min(count - filled,
                       self.cardinality() * self._batch - self._pos)
            out[filled:filled + take] = self._perm[self._pos:self._pos + take]
            filled += take
            self._pos += take
        return out

    def next_batch(self):
        """One device-resident global batch: (images, labels), batch dim
        sharded over the mesh data axis."""
        self._ensure_placed()
        if self._gather_batch is None:
            self._gather_batch = self._build_gather(stacked=False)
        idx = self._next_indices(self._batch)
        return self._gather_batch(self._dx, self._dy, idx)

    def next_stack(self, k: int):
        """K stacked device batches [K, B, ...] for one multi-step
        (steps_per_execution) execution."""
        self._ensure_placed()
        if self._gather_stack is None:
            self._gather_stack = self._build_gather(stacked=True)
        idx = self._next_indices(k * self._batch).reshape(k, self._batch)
        return self._gather_stack(self._dx, self._dy, idx)

    def __iter__(self) -> Iterator:
        """One full pass — the evaluate() path. Honors the dataset's
        shuffle flag (fresh permutation per pass): a bounded
        ``evaluate(steps=K)`` on a shuffled dataset must score a random
        subset, not the first K source-order batches (class-sorted sources
        would silently bias the metrics). ``shuffle=False`` keeps the
        sequential order."""
        self._ensure_placed()
        if self._gather_batch is None:
            self._gather_batch = self._build_gather(stacked=False)
        if self._shuffle:
            # ADVICE r4: a full pass here (evaluate() between epochs) must
            # NOT advance the training counter — that would shift every
            # subsequent seeded training permutation, so fixed-seed runs
            # stop reproducing when eval cadence changes. Eval draws from a
            # distinct seed stream (sequence-seeded rng keys never collide
            # with the scalar `seed + epoch` train stream).
            rng = np.random.default_rng((self._seed, 1, self._eval_pass))
            self._eval_pass += 1
            order = rng.permutation(self._n).astype(np.int32)
        else:
            order = np.arange(self._n, dtype=np.int32)
        for s in range(self.cardinality()):
            idx = order[s * self._batch:(s + 1) * self._batch]
            yield self._gather_batch(self._dx, self._dy, idx)


def device_pipeline(name: str, *, global_batch_size: int, seed: int = 0,
                    split: str = "train", scale: float = 1.0 / 255.0,
                    shuffle: bool = True, strategy=None,
                    synthetic_size: int | None = None) -> DeviceDataset:
    """A :class:`DeviceDataset` over a named source (sources.py resolution:
    local files, else deterministic synthetic)."""
    from tpu_dist.data.sources import load_arrays

    images, labels = load_arrays(name, split, synthetic_size=synthetic_size)
    return DeviceDataset(images, labels, global_batch_size=global_batch_size,
                         strategy=strategy, seed=seed, shuffle=shuffle,
                         scale=scale)
