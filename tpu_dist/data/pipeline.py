"""Host-side input pipeline: a tf.data-shaped Dataset for per-host delivery.

Re-provides the input-pipeline surface the reference exercises (SURVEY.md D13,
§3.4): ``map`` / ``cache`` / ``shuffle`` / ``batch`` combinators
(tf_dist_example.py:20-33), ``from_tensor_slices`` for numpy data
(README.md:121-129), and ``Options`` carrying
``experimental_distribute.auto_shard_policy`` (tf_dist_example.py:34-37) with
TF's enum values (tf:python/data/ops/options.py:89-116).

TPU-native stance: the input pipeline is *host-side numpy* — TPU sees only the
assembled global batch (``tpu_dist.data.distribute``). There is no graph of
dataset ops to rewrite; the autoshard policy that TF implements as a C++
Grappler pass over the dataset graph (auto_shard.cc) becomes a plain index
transformation in ``tpu_dist.data.sharding``. Shuffling is buffer-based with
the same semantics as tf.data's ``shuffle(buffer_size)``: an *unseeded* shuffle
draws a fresh order per iteration/worker — load-bearing for the reference's
OFF-policy mode where every worker iterates an independently-shuffled full
stream (README.md:113-120, SURVEY.md §3.4).
"""

from __future__ import annotations

import enum
import itertools
import queue as queue_lib
import threading
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np


class AutoShardPolicy(enum.IntEnum):
    """TF ``tf.data.experimental.AutoShardPolicy`` values
    (tf:python/data/ops/options.py:89-116). The reference sets OFF
    (tf_dist_example.py:35)."""

    OFF = -1
    AUTO = 0
    FILE = 1
    DATA = 2
    HINT = 3


class _DistributeOptions:
    """Mirror of ``options.experimental_distribute`` attribute shape."""

    def __init__(self) -> None:
        self.auto_shard_policy = AutoShardPolicy.AUTO

    def __repr__(self) -> str:
        return f"_DistributeOptions(auto_shard_policy={self.auto_shard_policy!r})"


class Options:
    """Dataset options — the subset the reference uses: the auto-shard policy
    (tf_dist_example.py:34-35: ``options.experimental_distribute
    .auto_shard_policy = AutoShardPolicy.OFF``)."""

    def __init__(self) -> None:
        self.experimental_distribute = _DistributeOptions()

    def __repr__(self) -> str:
        return f"Options({self.experimental_distribute!r})"


def _map_structure(fn, element):
    if isinstance(element, tuple):
        return tuple(_map_structure(fn, e) for e in element)
    if isinstance(element, dict):
        return {k: _map_structure(fn, v) for k, v in element.items()}
    return fn(element)


def _combine_structure(elements: Sequence, combine) -> Any:
    """Recurse a list of identically-structured elements down to leaves and
    merge each leaf list with ``combine`` (np.stack to batch, np.concatenate
    to rebatch)."""
    first = elements[0]
    if isinstance(first, tuple):
        return tuple(_combine_structure([e[i] for e in elements], combine)
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _combine_structure([e[k] for e in elements], combine)
                for k in first}
    return combine([np.asarray(e) for e in elements])


def _batch_structure(elements: Sequence) -> Any:
    """Stack a list of identically-structured elements into batched arrays."""
    return _combine_structure(elements, np.stack)


def _concat_structure(elements: Sequence) -> Any:
    """Concatenate already-batched elements along their leading dim."""
    return _combine_structure(elements, np.concatenate)


class Dataset:
    """A lazily-evaluated element pipeline (host-side, numpy).

    Built from a factory returning a fresh iterator per epoch — iterating a
    Dataset twice replays the source (and re-randomizes unseeded shuffles),
    matching tf.data re-iteration semantics the reference relies on for its
    per-worker independent shuffles (SURVEY.md §3.4).
    """

    def __init__(self, it_factory: Callable[[], Iterator], *,
                 options: Options | None = None,
                 cardinality: int | None = None,
                 num_files: int = 1):
        self._it_factory = it_factory
        self._options = options or Options()
        self._cardinality = cardinality
        self._prefetched = False  # set by prefetch(); read by DistributedDataset
        #: Source-file count, drives AutoShardPolicy.FILE/AUTO decisions
        #: (TF autoshards by file when the source has files, auto_shard.cc).
        self.num_files = num_files
        # Chain-rewrite metadata (the FILE-autoshard path, sharding.py): each
        # derived dataset records its parent and a (name, kwargs) transform
        # descriptor so the chain can be replayed onto a re-rooted source —
        # the element-stream analog of TF's Grappler auto_shard graph rewrite
        # pushing the shard op down to the file reader (auto_shard.cc).
        self._parent: "Dataset | None" = None
        self._transform: tuple[str, dict] | None = None
        #: Set on file-backed sources (from_files): (num_shards, index) -> a
        #: new source Dataset over the strided file subset.
        self._file_shard_fn: Callable[[int, int], "Dataset"] | None = None
        #: In-memory source arrays (from_tensor_slices) — lets the
        #: vectorized chain rewrite (data/vectorize.py) execute the whole
        #: combinator chain as index math + batched gathers.
        self._tensor_source = None
        #: Optional jittable fn applied to the PLACED x batch inside the
        #: compiled step (trainer plumbing): lets a pipeline ship compact
        #: wire dtypes (uint8) and run normalization on device, where it
        #: fuses into the step for free (SURVEY hard-part #5; the H2D link
        #: is the scarce resource, esp. on a tunneled runtime).
        self._device_transform: Callable | None = None

    # -- constructors --------------------------------------------------------

    @staticmethod
    def from_tensor_slices(tensors) -> "Dataset":
        """Elements are slices along the leading axis — the README.md:121-129
        numpy-conversion path."""
        arrays = _map_structure(np.asarray, tensors)
        leaves = []
        _map_structure(leaves.append, arrays)
        if not leaves:
            raise ValueError("from_tensor_slices requires at least one array")
        n = len(leaves[0])
        for leaf in leaves:
            if len(leaf) != n:
                raise ValueError(
                    f"all arrays must share the leading dim, got {len(leaf)} != {n}")

        def factory():
            for i in range(n):
                yield _map_structure(lambda a: a[i], arrays)

        ds = Dataset(factory, cardinality=n)
        ds._tensor_source = arrays
        return ds

    @staticmethod
    def from_generator(gen_factory: Callable[[], Iterable]) -> "Dataset":
        return Dataset(lambda: iter(gen_factory()))

    @staticmethod
    def from_files(files: Sequence, reader: Callable[[Any], Iterable], *,
                   cardinality: int | None = None,
                   file_cardinalities: Sequence[int] | None = None) -> "Dataset":
        """A file-backed source: elements are ``reader(file)``'s, file by file,
        in the given order. This is the source shape AutoShardPolicy.FILE
        strides across workers (SURVEY.md D13; TF shards the file list in
        auto_shard.cc when the source is file-based).

        ``file_cardinalities`` (per-file element counts, when known) lets a
        FILE-sharded worker subset keep a known cardinality — without it the
        subset's cardinality is unknown and ``fit`` needs an explicit
        ``steps_per_epoch``."""
        files = list(files)
        if not files:
            raise ValueError("from_files requires at least one file")
        if file_cardinalities is not None:
            file_cardinalities = list(file_cardinalities)
            if len(file_cardinalities) != len(files):
                raise ValueError(
                    f"file_cardinalities has {len(file_cardinalities)} "
                    f"entries for {len(files)} files")
            total = sum(file_cardinalities)
            if cardinality is None:
                cardinality = total
            elif cardinality != total:
                raise ValueError(
                    f"cardinality {cardinality} != sum(file_cardinalities) "
                    f"{total}")

        def factory():
            for f in files:
                yield from reader(f)

        ds = Dataset(factory, cardinality=cardinality, num_files=len(files))
        #: Per-file counts (when known) let the FILE-shard guard verify each
        #: worker's strided subset carries the SAME total element count —
        #: equal file counts alone don't guarantee equal streams.
        ds._file_cardinalities = file_cardinalities
        # TF strides the file list across workers (worker i reads files
        # i, i+n, i+2n, ...); the subset source keeps its own file count and
        # (when per-file counts are known) its own cardinality.
        ds._file_shard_fn = lambda n, i: Dataset.from_files(
            files[i::n], reader,
            file_cardinalities=(None if file_cardinalities is None
                                else file_cardinalities[i::n]))
        return ds

    @staticmethod
    def range(n: int) -> "Dataset":
        return Dataset(lambda: iter(range(n)), cardinality=n)

    # -- combinators (each returns a new Dataset; reference set at
    #    tf_dist_example.py:20-37) -------------------------------------------

    def map(self, fn: Callable) -> "Dataset":
        def factory():
            for el in self._it_factory():
                yield fn(*el) if isinstance(el, tuple) else fn(el)

        return self._derive(factory, transform=("map", {"fn": fn}))

    def filter(self, predicate: Callable) -> "Dataset":
        def factory():
            for el in self._it_factory():
                keep = predicate(*el) if isinstance(el, tuple) else predicate(el)
                if keep:
                    yield el

        return self._derive(factory, cardinality=None,
                            transform=("filter", {"predicate": predicate}))

    def cache(self) -> "Dataset":
        """Materialize on first full pass; later passes replay the cache
        (tf_dist_example.py:30 uses this to avoid re-decoding MNIST).

        Only a COMPLETE pass publishes the cache: a partially-consumed or
        concurrent iterator never corrupts it (it just re-reads the source),
        and no lock is held across yields."""
        store: list = []
        complete = threading.Event()
        lock = threading.Lock()

        def factory():
            if complete.is_set():
                yield from store
                return
            local: list = []
            for el in self._it_factory():
                local.append(el)
                yield el
            with lock:
                if not complete.is_set():
                    store.extend(local)
                    complete.set()

        return self._derive(factory, transform=("cache", {}))

    def shuffle(self, buffer_size: int, seed: int | None = None,
                reshuffle_each_iteration: bool = True) -> "Dataset":
        """Buffer-based shuffle with tf.data semantics: fill a buffer of
        ``buffer_size``, emit a random occupant, refill. Unseeded => each
        iteration (and each worker process) draws an independent order — the
        property the reference's OFF-policy mode depends on (README.md:113-120).
        """
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        auto_seeded = seed is None  # recorded: an auto-drawn seed is still
        # process-divergent (each process draws its own), which the
        # replicated-determinism guard must treat as unseeded.
        if seed is None and not reshuffle_each_iteration:
            # tf.data semantics: an unseeded non-reshuffling dataset picks one
            # random seed at construction and replays that order every pass.
            seed = int(np.random.default_rng().integers(2**31))
        epoch_counter = itertools.count()

        def factory():
            it = self._it_factory()
            epoch = next(epoch_counter)
            if seed is None:
                rng = np.random.default_rng()
            else:
                rng = np.random.default_rng(
                    seed + (epoch if reshuffle_each_iteration else 0))
            buf = list(itertools.islice(it, buffer_size))
            for el in it:
                idx = rng.integers(len(buf))
                out, buf[idx] = buf[idx], el
                yield out
            rng.shuffle(buf)
            yield from buf

        return self._derive(
            factory,
            transform=("shuffle",
                       {"buffer_size": buffer_size, "seed": seed,
                        "auto_seeded": auto_seeded,
                        "reshuffle_each_iteration": reshuffle_each_iteration}))

    def batch(self, batch_size: int, drop_remainder: bool = False) -> "Dataset":
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")

        def factory():
            acc = []
            for el in self._it_factory():
                acc.append(el)
                if len(acc) == batch_size:
                    yield _batch_structure(acc)
                    acc = []
            if acc and not drop_remainder:
                yield _batch_structure(acc)

        card = None
        if self._cardinality is not None:
            card = (self._cardinality // batch_size if drop_remainder
                    else -(-self._cardinality // batch_size))
        return self._derive(
            factory, cardinality=card,
            transform=("batch", {"batch_size": batch_size,
                                 "drop_remainder": drop_remainder}))

    def repeat(self, count: int | None = None) -> "Dataset":
        def factory():
            n = 0
            while count is None or n < count:
                it = self._it_factory()
                empty = True
                for el in it:
                    empty = False
                    yield el
                if empty:
                    return
                n += 1

        card = None
        if count is not None and self._cardinality is not None:
            card = count * self._cardinality
        return self._derive(factory, cardinality=card,
                            transform=("repeat", {"count": count}))

    def take(self, count: int) -> "Dataset":
        def factory():
            yield from itertools.islice(self._it_factory(), count)

        # Unknown source cardinality stays unknown: the source may yield fewer
        # than ``count`` elements (tf.data likewise keeps UNKNOWN_CARDINALITY).
        card = None if self._cardinality is None else min(count, self._cardinality)
        return self._derive(factory, cardinality=card,
                            transform=("take", {"count": count}))

    def interleave(self, map_func: Callable, cycle_length: int = 4,
                   block_length: int = 1) -> "Dataset":
        """tf.data's ``Dataset.interleave``: map each element to a Dataset
        and consume the resulting streams round-robin — ``block_length``
        elements at a time from ``cycle_length`` concurrently-open streams.
        The standard shape for mixing multiple file readers."""
        if cycle_length < 1 or block_length < 1:
            raise ValueError("cycle_length and block_length must be >= 1")

        def factory():
            source = self._it_factory()

            def new_stream():
                try:
                    el = next(source)
                except StopIteration:
                    return None
                return iter(map_func(*el) if isinstance(el, tuple)
                            else map_func(el))

            slots: list = []
            while len(slots) < cycle_length:
                s = new_stream()
                if s is None:
                    break
                slots.append(s)
            # tf.data ordering (InterleaveDataset kernel): when a stream
            # ends mid-block, advance to the NEXT cycle slot immediately;
            # the emptied slot opens its replacement stream only when the
            # round-robin cycle returns to it. (None marks an empty slot
            # awaiting lazy refill.)
            i = 0
            while slots:
                if i >= len(slots):
                    i = 0
                if slots[i] is None:
                    repl = new_stream()
                    if repl is None:
                        slots.pop(i)
                        continue
                    slots[i] = repl
                emitted = 0
                while emitted < block_length:
                    try:
                        yield next(slots[i])
                        emitted += 1
                    except StopIteration:
                        slots[i] = None
                        break
                i += 1

        return self._derive(
            factory, cardinality=None,
            transform=("interleave", {"map_func": map_func,
                                      "cycle_length": cycle_length,
                                      "block_length": block_length}))

    def skip(self, count: int) -> "Dataset":
        """Drop the first ``count`` elements — tf.data's ``Dataset.skip``."""
        def factory():
            yield from itertools.islice(self._it_factory(), count, None)

        card = (None if self._cardinality is None
                else max(0, self._cardinality - count))
        return self._derive(factory, cardinality=card,
                            transform=("skip", {"count": count}))

    def unbatch(self) -> "Dataset":
        """Split each batched element back into per-example elements —
        tf.data's ``Dataset.unbatch`` (leading dim must agree across the
        element's components)."""
        def first_leaf(el):
            if isinstance(el, tuple):
                return first_leaf(el[0])
            if isinstance(el, dict):
                return first_leaf(next(iter(el.values())))
            return el

        def factory():
            for el in self._it_factory():
                n = len(np.asarray(first_leaf(el)))
                for i in range(n):
                    yield _map_structure(lambda a: np.asarray(a)[i], el)

        return self._derive(factory, cardinality=None,
                            transform=("unbatch", {}))

    def concatenate(self, other: "Dataset") -> "Dataset":
        """This dataset's elements, then ``other``'s — tf.data's
        ``Dataset.concatenate``."""
        def factory():
            yield from self._it_factory()
            yield from iter(other)

        card = None
        other_card = other.cardinality()
        if (self._cardinality is not None and other_card is not None
                and other_card >= 0):
            card = self._cardinality + other_card
        # transform=None: replaying concatenate through the FILE-autoshard
        # chain rewrite would append the FULL `other` to every worker's file
        # shard (duplicated data); opaque forces the DATA fallback instead.
        return self._derive(factory, cardinality=card, transform=None)

    @staticmethod
    def zip(*datasets: "Dataset") -> "Dataset":
        """Element-wise tuples across datasets, stopping at the shortest —
        tf.data's ``Dataset.zip`` (accepts ``Dataset.zip((a, b))`` too)."""
        if len(datasets) == 1 and isinstance(datasets[0], (tuple, list)):
            datasets = tuple(datasets[0])
        if not datasets:
            raise ValueError("zip needs at least one dataset")

        def factory():
            its = [iter(d) for d in datasets]
            while True:
                row = []
                for it in its:
                    try:
                        row.append(next(it))
                    except StopIteration:
                        return
                yield tuple(row)

        cards = [d.cardinality() for d in datasets]
        card = (min(c for c in cards) if all(
            c is not None and c >= 0 for c in cards) else None)
        # Keep the first input's options (shard policy etc.) — a raw Dataset
        # would silently reset auto_shard_policy to AUTO.
        first_opts = getattr(datasets[0], "_options", None)
        return Dataset(factory, options=first_opts, cardinality=card)

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Every ``num_shards``-th element starting at ``index`` — tf.data's
        ``Dataset.shard``, the primitive DATA autosharding lowers to."""
        if not 0 <= index < num_shards:
            raise ValueError(f"index {index} not in [0, {num_shards})")

        def factory():
            yield from itertools.islice(self._it_factory(), index, None, num_shards)

        card = None
        if self._cardinality is not None:
            card = (self._cardinality - index + num_shards - 1) // num_shards
        return self._derive(factory, cardinality=card,
                            transform=("shard", {"num_shards": num_shards,
                                                 "index": index}))

    def prefetch(self, buffer_size: int = 2) -> "Dataset":
        """Background-thread prefetch, keeping host input off the step critical
        path (SURVEY.md §3.4 'cache+prefetch keep it off the critical path')."""
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")

        def factory():
            q: queue_lib.Queue = queue_lib.Queue(maxsize=buffer_size)
            stop = threading.Event()
            _SENTINEL = object()

            def _put(item) -> bool:
                # Bounded put that gives up when the consumer abandoned the
                # iterator (e.g. evaluate(steps=N) breaking early) — otherwise
                # the producer thread would block forever and pin the upstream
                # pipeline.
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.05)
                        return True
                    except queue_lib.Full:
                        continue
                return False

            def producer():
                try:
                    for el in self._it_factory():
                        if not _put(el):
                            return
                except BaseException as e:  # propagate into the consumer
                    _put((_SENTINEL, e))
                    return
                _put((_SENTINEL, None))

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            try:
                # The producer's BaseException handler guarantees a sentinel
                # arrives even when it dies, so this get() always terminates.
                while True:  # shardcheck: disable=SC502 -- sentinel-bounded
                    item = q.get()
                    if (isinstance(item, tuple) and len(item) == 2
                            and item[0] is _SENTINEL):
                        if item[1] is not None:
                            raise item[1]
                        return
                    yield item
            finally:
                stop.set()

        ds = self._derive(factory,
                          transform=("prefetch", {"buffer_size": buffer_size}))
        ds._prefetched = True  # lets DistributedDataset skip double-wrapping
        return ds

    def with_options(self, options: Options) -> "Dataset":
        """Attach options — the reference's auto-shard-policy carrier
        (tf_dist_example.py:37)."""
        ds = self._derive(self._it_factory,
                          transform=("with_options", {"options": options}))
        ds._options = options
        return ds

    # -- introspection -------------------------------------------------------

    @property
    def options(self) -> Options:
        return self._options

    @property
    def auto_shard_policy(self) -> AutoShardPolicy:
        return self._options.experimental_distribute.auto_shard_policy

    def cardinality(self) -> int | None:
        """Element count if statically known, else None (unknown)."""
        return self._cardinality

    def __iter__(self) -> Iterator:
        return self._it_factory()

    def as_numpy_iterator(self) -> Iterator:
        return iter(self)

    def _derive(self, factory, cardinality: int | None = "inherit",
                transform: tuple[str, dict] | None = None) -> "Dataset":  # type: ignore[assignment]
        ds = Dataset(
            factory,
            options=self._options,
            cardinality=(self._cardinality if cardinality == "inherit"
                         else cardinality),
            num_files=self.num_files,
        )
        ds._parent = self
        ds._transform = transform
        # A prefetch anywhere upstream keeps the chain marked, so the
        # DistributedDataset default wrap never double-buffers.
        ds._prefetched = self._prefetched
        # The device transform composes AFTER placement, so it survives
        # only stream-shape ops; an element transform (map/filter/...)
        # would otherwise see the compact wire dtype AND still get the
        # deferred scale applied on top of its own output.
        if transform is not None and transform[0] in (
                "prefetch", "with_options", "repeat", "take", "skip",
                "shard", "batch"):
            ds._device_transform = self._device_transform
        return ds

    def _replay_transform(self, transform: tuple[str, dict]) -> "Dataset":
        """Apply a recorded (name, kwargs) transform descriptor to this
        dataset — used by the FILE-autoshard chain rewrite (sharding.py)."""
        name, kw = transform
        if name == "with_options":
            return self.with_options(kw["options"])
        # Drop record-only markers that are not combinator kwargs (the
        # auto_seeded flag the replicated-determinism guard reads).
        kw = {k: v for k, v in kw.items() if k != "auto_seeded"}
        return getattr(self, name)(**kw)


class DevicePrefetcher:
    """Double-buffered host→device input: a bounded background stage over an
    iterator of ALREADY device-placing batches (``iter(DistributedDataset)``
    runs ``strategy.distribute_batch`` — i.e. the ``device_put`` — inside
    ``next()``, so moving the iteration onto this producer thread moves the
    transfer off the training hot loop). While step k executes, up to
    ``depth`` later batches are fetched and placed; the trainer's measured
    ``data_wait_s`` collapses to a queue pop.

    Same bounded-queue discipline as :meth:`Dataset.prefetch`: the producer
    polls a stop event on every put so :meth:`close` (epoch-loop exit,
    ``StopTraining``, preemption drain) never leaves a thread blocked on a
    full queue. ``close()`` stops the producer, drains in-flight items, and
    joins the thread — the no-leaked-threads teardown contract
    (tests/test_step_perf.py).

    Observability (host-side only): ``data.prefetch.hits`` / ``.misses``
    counters (was the next batch already buffered when the trainer asked?)
    and a ``data.prefetch.depth`` gauge of the buffered count — all through
    :mod:`tpu_dist.observe.metrics`, so a disabled registry pays one flag
    check.
    """

    def __init__(self, it: Iterator, *, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self.hits = 0
        self.misses = 0
        self._q: queue_lib.Queue = queue_lib.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._exhausted = False
        self._thread = threading.Thread(
            target=self._produce, args=(it,), daemon=True,
            name="tpu-dist-device-prefetch")
        self._thread.start()

    _SENTINEL = object()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue_lib.Full:
                continue
        return False

    def _produce(self, it: Iterator) -> None:
        try:
            for batch in it:
                if not self._put((batch, None)):
                    return
        except BaseException as e:  # propagate into the consumer
            self._put((self._SENTINEL, e))
            return
        self._put((self._SENTINEL, None))

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        from tpu_dist.observe import metrics

        if self._exhausted:
            raise StopIteration
        buffered = self._q.qsize()
        metrics.set_gauge("data.prefetch.depth", buffered)
        item, err = self._q.get()
        if item is self._SENTINEL:
            self._exhausted = True
            if err is not None:
                raise err
            raise StopIteration
        # Count hit/miss only for real batches — the terminal sentinel
        # fetch is bookkeeping, so hits + misses == batches delivered.
        if buffered > 0:
            self.hits += 1
            metrics.inc("data.prefetch.hits")
        else:
            self.misses += 1
            metrics.inc("data.prefetch.misses")
        return item

    @property
    def closed(self) -> bool:
        """True once close() has fully torn down the producer thread."""
        return self._stop.is_set() and not self._thread.is_alive()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer, drain in-flight batches, join the thread.
        Idempotent; safe mid-stream (the batches dropped here were
        speculative — exactly the teardown a preemption drain needs)."""
        self._stop.set()
        self._exhausted = True
        # Drain so a producer blocked in put() observes the stop event and
        # exits its poll loop promptly.
        while True:
            try:
                self._q.get_nowait()
            except queue_lib.Empty:
                break
        self._thread.join(timeout=timeout)
