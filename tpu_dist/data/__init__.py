"""Input-pipeline layer: datasets, combinators, shard policies, distribution."""

from tpu_dist.data.pipeline import (AutoShardPolicy, Dataset,
                                    DevicePrefetcher, Options)
from tpu_dist.data.sources import (
    DatasetInfo,
    SplitInfo,
    disable_progress_bar,
    image_shape,
    load,
    load_arrays,
    num_classes,
)
from tpu_dist.data.sharding import resolve_policy, shard_dataset
from tpu_dist.data.distribute import DistributedDataset
from tpu_dist.data.device import DeviceDataset, device_pipeline
from tpu_dist.data.sources import write_sharded

__all__ = [
    "DeviceDataset",
    "device_pipeline",
    "write_sharded",
    "AutoShardPolicy",
    "Dataset",
    "DevicePrefetcher",
    "DatasetInfo",
    "Options",
    "SplitInfo",
    "disable_progress_bar",
    "image_shape",
    "load",
    "load_arrays",
    "num_classes",
    "resolve_policy",
    "shard_dataset",
    "DistributedDataset",
]
