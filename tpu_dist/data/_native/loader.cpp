// Native input-pipeline core: fused gather + normalize, and index shuffling.
//
// The reference's input pipeline bottoms out in TF's C++ tf.data kernels and
// the Grappler autoshard rewrite (SURVEY.md D13: "Python + C++"). This is the
// tpu-dist native equivalent for the host-side hot path: assembling a
// training batch from a shuffled in-memory dataset. One multithreaded pass
// does the gather (random rows -> contiguous batch) and the uint8->float32
// normalization the reference's `scale` map performs (tf_dist_example.py:
// 22-25), instead of numpy's separate fancy-index + astype + divide passes.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread loader.cpp -o libtpu_dist_loader.so
// (done lazily by tpu_dist/data/native.py; pure-numpy fallback if unavailable).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// SplitMix64 — tiny, seedable, statistically solid for shuffling.
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void gather_scale_rows(const uint8_t* in, const int64_t* idx, int64_t begin,
                       int64_t end, int64_t row_elems, float scale,
                       float* out) {
  for (int64_t i = begin; i < end; ++i) {
    const uint8_t* src = in + idx[i] * row_elems;
    float* dst = out + i * row_elems;
    for (int64_t j = 0; j < row_elems; ++j) {
      dst[j] = static_cast<float>(src[j]) * scale;
    }
  }
}

}  // namespace

extern "C" {

// out[i, :] = float32(in[idx[i], :]) * scale, parallelized over rows.
void tpu_dist_gather_scale_u8_f32(const uint8_t* in, const int64_t* idx,
                                  int64_t n_out, int64_t row_elems,
                                  float scale, float* out, int n_threads) {
  if (n_threads <= 1 || n_out < n_threads * 4) {
    gather_scale_rows(in, idx, 0, n_out, row_elems, scale, out);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n_out + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = begin + chunk < n_out ? begin + chunk : n_out;
    if (begin >= end) break;
    workers.emplace_back(gather_scale_rows, in, idx, begin, end, row_elems,
                         scale, out);
  }
  for (auto& w : workers) w.join();
}

// Same fused gather for int64 label rows (no scaling).
void tpu_dist_gather_i64(const int64_t* in, const int64_t* idx, int64_t n_out,
                         int64_t row_elems, int64_t* out) {
  for (int64_t i = 0; i < n_out; ++i) {
    std::memcpy(out + i * row_elems, in + idx[i] * row_elems,
                sizeof(int64_t) * row_elems);
  }
}

// Fisher-Yates permutation of [0, n) with a seeded SplitMix64 stream.
void tpu_dist_shuffled_indices(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t state = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(state) % (uint64_t)(i + 1));
    int64_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
}

}  // extern "C"
