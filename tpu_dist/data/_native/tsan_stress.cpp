// ThreadSanitizer stress driver for the native loader core (SURVEY.md §5.2).
//
// The reference stack documents its collective-launch races and mitigations
// (SURVEY.md §5.2: cross_device_ops.py:1075-1088); on the TPU-native stack
// those vanish under XLA and the remaining race surface is host-side — this
// loader. This driver reproduces the real concurrency pattern around
// loader.cpp: several pipeline threads (prefetch + per-Dataset iterators)
// each assembling their own batches with the multithreaded fused gather,
// all reading one shared dataset. Built and run under -fsanitize=thread by
// `make tsan` / tests/test_native_and_pallas.py::
// TestNativeLoaderConcurrency::test_tsan_stress_clean.
//
// Exit code 0 and no "WARNING: ThreadSanitizer" output = clean.
//
// Build: g++ -fsanitize=thread -O1 -g -pthread loader.cpp tsan_stress.cpp \
//            -o tsan_stress

#include <cstdint>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
void tpu_dist_gather_scale_u8_f32(const uint8_t* in, const int64_t* idx,
                                  int64_t n_out, int64_t row_elems,
                                  float scale, float* out, int n_threads);
void tpu_dist_gather_i64(const int64_t* in, const int64_t* idx, int64_t n_out,
                         int64_t row_elems, int64_t* out);
void tpu_dist_shuffled_indices(int64_t n, uint64_t seed, int64_t* out);
}

namespace {

constexpr int64_t kRows = 1024;
constexpr int64_t kRowElems = 28 * 28;  // MNIST-shaped
constexpr int64_t kBatch = 128;
constexpr int kPipelineThreads = 4;     // concurrent iterators/prefetchers
constexpr int kRounds = 16;             // batches per pipeline thread
constexpr int kInnerThreads = 4;        // n_threads inside each gather call

void pipeline_thread(const uint8_t* images, const int64_t* labels, int id,
                     float* checksum_out) {
  std::vector<int64_t> perm(kRows);
  std::vector<float> batch(kBatch * kRowElems);
  std::vector<int64_t> lab(kBatch);
  float checksum = 0.f;
  for (int r = 0; r < kRounds; ++r) {
    tpu_dist_shuffled_indices(kRows, 0x9E37 * id + r, perm.data());
    tpu_dist_gather_scale_u8_f32(images, perm.data(), kBatch, kRowElems,
                                 1.0f / 255.0f, batch.data(), kInnerThreads);
    tpu_dist_gather_i64(labels, perm.data(), kBatch, 1, lab.data());
    checksum += batch[(r * 31) % (kBatch * kRowElems)] +
                static_cast<float>(lab[r % kBatch]);
  }
  *checksum_out = checksum;  // keep the work observable
}

}  // namespace

int main() {
  std::vector<uint8_t> images(kRows * kRowElems);
  std::vector<int64_t> labels(kRows);
  for (int64_t i = 0; i < kRows * kRowElems; ++i)
    images[i] = static_cast<uint8_t>((i * 131) & 0xFF);
  for (int64_t i = 0; i < kRows; ++i) labels[i] = i % 10;

  std::vector<float> checksums(kPipelineThreads);
  std::vector<std::thread> threads;
  for (int t = 0; t < kPipelineThreads; ++t)
    threads.emplace_back(pipeline_thread, images.data(), labels.data(), t,
                         &checksums[t]);
  for (auto& t : threads) t.join();

  float total = 0.f;
  for (float c : checksums) total += c;
  std::printf("tsan_stress ok checksum=%f\n", total);
  return 0;
}
