"""Vectorized execution of combinator chains over in-memory sources.

The reference's input pipeline is rewritten by TF's C++ Grappler passes
(map-and-batch fusion, map vectorization — auto_shard.cc's siblings in
tensorflow/core/grappler/optimizers/data/); tpu-dist's Datasets instead
record each combinator as chain metadata (pipeline.py ``_parent`` /
``_transform``), and this module is the rewrite pass over that chain.

For a chain of the shape the reference builds (tf_dist_example.py:20-33)

    from_tensor_slices -> map(fn)* -> cache? -> shuffle -> batch [-> repeat
        / take / skip / prefetch / with_options]

the per-element generator walk (one Python frame per example, one
``np.stack`` of B tiny arrays per batch) is replaced by *index math plus
batched gathers*:

* the shuffle runs over an ``int64`` index array with the SAME buffer
  algorithm and rng construction as ``Dataset.shuffle`` (seeded chains stay
  bit-identical; unseeded full-buffer shuffles collapse to one
  ``rng.shuffle``, which is also the element path's exact call sequence);
* each batch is one fancy-index gather (C memcpy) instead of B element
  yields + ``np.stack``;
* ``map`` functions are PROBED for safety — a function is only vectorized
  if applying it to a 2-element batch reproduces the stacked per-element
  results exactly, and applying it twice is deterministic; anything else
  (stateful augmentations, shape-bending fns) falls back to the untouched
  element path;
* a map that probes as pure uint8 normalization (``astype(float32) * k``)
  is FUSED into the gather via the native C++ loader
  (``native.gather_scale``) — and on non-CPU backends the normalization is
  deferred to the device entirely (``Dataset._device_transform``): the
  batch crosses the host->device link as uint8 (4x fewer bytes on the
  job's scarcest resource) and the scale fuses into the compiled step.

``try_rewrite`` returns None whenever ANY link of the chain is outside the
supported grammar — correctness never depends on the rewrite firing.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable

import numpy as np

from tpu_dist.data.pipeline import Dataset, _map_structure

logger = logging.getLogger("tpu_dist.data")

#: Pre-batch ops the index/value planner understands.
_PRE_BATCH = {"map", "cache", "shuffle", "skip", "take", "shard"}
#: Post-batch ops replayable on the batch stream.
_POST_BATCH = {"repeat", "take", "skip", "shard", "prefetch", "with_options"}


def enabled() -> bool:
    return os.environ.get("TPU_DIST_VECTORIZE", "").strip() != "0"


# -- chain parsing ------------------------------------------------------------


def _collect_chain(ds: Dataset):
    """(source Dataset, [transform (name, kwargs) source->sink]) or None."""
    steps: list[tuple[str, dict]] = []
    node = ds
    while node is not None:
        if getattr(node, "_tensor_source", None) is not None:
            return node, list(reversed(steps))
        t = node._transform
        if t is None:
            return None
        steps.append(t)
        node = node._parent
    return None


def _parse(ds: Dataset):
    """Split a supported chain into (pre-batch ops, batch kwargs,
    post-batch ops); None when outside the grammar."""
    got = _collect_chain(ds)
    if got is None:
        return None
    source, steps = got
    pre: list[tuple[str, dict]] = []
    post: list[tuple[str, dict]] = []
    batch_kw = None
    for name, kw in steps:
        if batch_kw is None:
            if name == "batch":
                batch_kw = kw
            elif name in _PRE_BATCH:
                pre.append((name, kw))
            else:
                return None
        else:
            if name in _POST_BATCH:
                post.append((name, kw))
            else:
                return None
    if batch_kw is None:
        return None
    # One shuffle, never behind a cache (cache-after-shuffle freezes the
    # first pass's order — semantics the index planner doesn't reproduce).
    shuffle_seen = False
    for name, _ in pre:
        if name == "shuffle":
            if shuffle_seen:
                return None
            shuffle_seen = True
        if name == "cache" and shuffle_seen:
            return None
    return source, pre, batch_kw, post


# -- map probing --------------------------------------------------------------


def _apply_fn(fn: Callable, el):
    return fn(*el) if isinstance(el, tuple) else fn(el)


def _leaves(el) -> list[np.ndarray]:
    out: list[np.ndarray] = []
    _map_structure(lambda a: out.append(np.asarray(a)), el)
    return out


def _same(a, b) -> bool:
    la, lb = _leaves(a), _leaves(b)
    if len(la) != len(lb):
        return False
    return all(x.dtype == y.dtype and x.shape == y.shape
               and np.array_equal(x, y) for x, y in zip(la, lb))


def _element(arrays, i: int):
    return _map_structure(lambda a: a[i], arrays)


def _probe_indices(arrays) -> np.ndarray:
    """Adversarial probe sample (ADVICE r4): a 2-element spot check lets a
    value-conditional batch-level fn (``np.where(x.max() > t, ...)`` where
    elements 0-1 stay under t) pass yet diverge once vectorized. Mirror
    ``_detect_scale``: an evenly-spaced sweep of the source plus the first
    occurrence of every distinct value of any small-integer leaf (a
    class/label-conditional fn must reveal itself on some class)."""
    leaves = _leaves(arrays)
    n = len(leaves[0])
    idx = np.linspace(0, n - 1, num=min(n, 32), dtype=np.int64)
    for leaf in leaves:
        if leaf.dtype.kind in "iu" and leaf.ndim <= 2:
            _, first = np.unique(
                leaf.reshape(n, -1)[:, 0], return_index=True)
            idx = np.concatenate([idx, first[:16].astype(np.int64)])
    return np.unique(idx)


def _probe_vectorizable(fn: Callable, arrays) -> bool:
    """fn(batched sample) must equal stack(fn(e_i) for each element) exactly,
    with fn(e_0) repeated for determinism. Exactness matters: elementwise
    math is bit-identical batched or not, while anything order-sensitive
    (reductions, reshapes) or value-conditional at batch level diverges and
    must keep the element path. The sample is adversarial (``_probe_indices``)
    — the rewrite's contract is that correctness never depends on it firing."""
    try:
        idx = _probe_indices(arrays)
        e0 = _element(arrays, int(idx[0]))
        f0a, f0b = _apply_fn(fn, e0), _apply_fn(fn, e0)
        if not _same(f0a, f0b):
            return False  # nondeterministic (random augmentation)
        per_el = [f0a] + [_apply_fn(fn, _element(arrays, int(i)))
                          for i in idx[1:]]
        batched_in = _map_structure(lambda a: np.asarray(a)[idx], arrays)
        got = _apply_fn(fn, batched_in)
        want_leaves = [np.stack(cols)
                       for cols in zip(*(_leaves(r) for r in per_el))]
        got_leaves = _leaves(got)
        return (len(got_leaves) == len(want_leaves)
                and all(g.dtype == w.dtype and g.shape == w.shape
                        and np.array_equal(g, w)
                        for g, w in zip(got_leaves, want_leaves)))
    except Exception:
        return False


def _detect_scale(fns: list[Callable], arrays
                  ) -> tuple[str, float] | None:
    """When the composed maps over a ``(uint8 image, label)`` source are
    exactly ``image.astype(float32) * k`` or ``image.astype(float32) / d``
    with the label untouched, return ``("mul", k)`` / ``("div", d)``.

    The distinction is bit-level: ``x / 255.0`` (the reference's scale fn)
    and ``x * (1/255)`` differ in the last ulp for many inputs, and the
    rewrite's contract is an IDENTICAL stream — so the exact formula is
    detected and replayed, on host or device. None otherwise."""
    if not (isinstance(arrays, tuple) and len(arrays) == 2):
        return None
    images, labels = np.asarray(arrays[0]), np.asarray(arrays[1])
    if images.dtype != np.uint8 or len(images) < 2:
        return None
    try:
        # The scale path DROPS fn for the whole dataset, so the probe must
        # be adversarial, not a 2-element spot check: an evenly-spaced
        # sample, one representative of every distinct label value (a
        # label-conditional fn must reveal itself on some class), and a
        # crafted image cycling all 256 uint8 values (a value-conditional
        # fn — clipping, thresholding — must reveal itself on some pixel).
        n = len(images)
        idx = list(np.linspace(0, n - 1, num=min(n, 64), dtype=np.int64))
        _, first_of_label = np.unique(
            labels.reshape(len(labels), -1)[:, 0], return_index=True)
        idx = np.unique(np.concatenate(
            [idx, first_of_label[:32]]).astype(np.int64))
        probe_x = images[idx]
        probe_y = labels[idx]
        ramp = (np.arange(int(np.prod(images.shape[1:])) or 1,
                          dtype=np.int64) % 256).astype(np.uint8)
        probe_x = np.concatenate(
            [probe_x, ramp.reshape(1, *images.shape[1:])])
        probe_y = np.concatenate([probe_y, labels[idx[:1]]])
        el = (probe_x, probe_y)
        out = el
        for fn in fns:
            out = _apply_fn(fn, out)
        if not (isinstance(out, tuple) and len(out) == 2):
            return None
        oimg, olab = np.asarray(out[0]), np.asarray(out[1])
        if oimg.dtype != np.float32 or oimg.shape != el[0].shape:
            return None
        if not np.array_equal(olab, el[1]):
            return None
        src = el[0].astype(np.float32)
        nz = src > 0
        if not nz.any():
            return None
        s = float(src[nz].flat[0])
        o = float(oimg[nz].flat[0])
        if o == 0.0:
            return None
        k = np.float32(o / s)
        if np.array_equal(oimg, src * k):
            detected = ("mul", float(k))
        else:
            d = np.float32(s / o)
            if not np.array_equal(oimg, src / d):
                return None
            detected = ("div", float(d))
        # The pipeline applies fn per ELEMENT; the formula above was
        # validated against a batched application. Cross-check EVERY probe
        # element singly (ADVICE r4): a label/value-conditional fn that
        # fires per-element but not batched (scalar-label branch) would
        # otherwise validate the wrong reference — and the whole point of
        # the label/ramp representatives is to be run where the branch can
        # trigger.
        for i in range(len(probe_x)):
            single = (probe_x[i], probe_y[i])
            for fn in fns:
                single = _apply_fn(fn, single)
            if not np.array_equal(np.asarray(single[0]), oimg[i]):
                return None
            if not np.array_equal(np.asarray(single[1]), olab[i]):
                return None
        return detected
    except Exception:
        return None


# -- index pipeline -----------------------------------------------------------


def _buffer_shuffle_indices(idx: np.ndarray, buffer_size: int, rng) -> np.ndarray:
    """``Dataset.shuffle``'s buffer algorithm over an index array — same rng
    call sequence, so a seeded chain is bit-identical to the element path."""
    n = len(idx)
    if buffer_size >= n:
        out = list(idx)
        rng.shuffle(out)  # element path: buf = all, one rng.shuffle(buf)
        return np.asarray(out, dtype=idx.dtype)
    out = np.empty(n, dtype=idx.dtype)
    buf = list(idx[:buffer_size])
    k = 0
    for el in idx[buffer_size:]:
        j = int(rng.integers(len(buf)))
        out[k] = buf[j]
        buf[j] = el
        k += 1
    rng.shuffle(buf)
    out[k:] = buf
    return out


class _IndexPlan:
    """Per-epoch index stream for the pre-batch ops."""

    def __init__(self, n: int, pre: list[tuple[str, dict]]):
        self.n = n
        self.ops = [(name, kw) for name, kw in pre if name != "map"
                    and name != "cache"]

    def epoch(self, epoch_no: int) -> np.ndarray:
        idx = np.arange(self.n, dtype=np.int64)
        for name, kw in self.ops:
            if name == "shuffle":
                seed = kw["seed"]
                if seed is None:
                    rng = np.random.default_rng()
                else:
                    rng = np.random.default_rng(
                        seed + (epoch_no if kw["reshuffle_each_iteration"]
                                else 0))
                idx = _buffer_shuffle_indices(idx, kw["buffer_size"], rng)
            elif name == "skip":
                idx = idx[kw["count"]:]
            elif name == "take":
                idx = idx[:kw["count"]]
            elif name == "shard":
                idx = idx[kw["index"]::kw["num_shards"]]
        return idx


# -- the rewrite --------------------------------------------------------------


def _device_scale_fn(k: float, op: str = "mul"):
    """Replays the host normalization ON DEVICE with the same formula (mul
    vs div is a bit-level distinction; XLA's f32 ops are IEEE like numpy's,
    so device results match the host path exactly)."""
    def transform(x):
        import jax.numpy as jnp

        xf = x.astype(jnp.float32)
        return xf * jnp.float32(k) if op == "mul" else xf / jnp.float32(k)

    transform._scale = k  # introspectable for tests/logging
    transform._op = op
    return transform


def try_promote_to_device(ds: Dataset):
    """Promote a reference-shaped chain over an HBM-sized in-memory source
    to a :class:`DeviceDataset` — upload the raw bytes ONCE, then assemble
    every batch on device from a host-sent index vector (kilobytes/step).

    This is the idiomatic endpoint of the rewrite on TPU: where
    ``try_rewrite`` shrinks per-step wire traffic 4x (uint8), promotion
    removes it altogether — the streaming bandwidth floor (measured
    ~18 MB/s through this host's tunnel, i.e. ~23k img/s ceiling for MNIST
    u8) stops applying because pixels cross the link once per job.

    Deliberately conservative; returns None unless ALL hold:

    * single process (multi-worker OFF semantics — independent per-worker
      shuffles — are not DeviceDataset's one-global-permutation semantic);
    * non-CPU backend (on CPU, device memory IS host memory);
    * the chain is source -> map* -> cache? -> shuffle? -> batch with the
      maps detected as pure normalization (``_detect_scale``) or absent;
    * any shuffle is UNSEEDED with per-iteration reshuffle (no
      reproducibility contract — a seeded order is honored by declining);
    * the batch divides the dataset or drops the remainder (device shapes
      are static);
    * no repeat/skip/take/shard anywhere (cardinality and stream-shape
      contracts stay exact on the unpromoted path).
    """
    if not enabled():
        return None
    cached = getattr(ds, "_device_promoted", None)
    if cached is not None:
        return cached  # one upload per chain, however many fit() calls
    import jax

    if jax.default_backend() == "cpu" or jax.process_count() > 1:
        return None
    parsed = _parse(ds)
    if parsed is None:
        return None
    source, pre, batch_kw, post = parsed
    arrays = source._tensor_source
    if not (isinstance(arrays, tuple) and len(arrays) == 2):
        return None
    images, labels = np.asarray(arrays[0]), np.asarray(arrays[1])
    if images.nbytes > 512 * 1024 * 1024:  # keep HBM headroom
        return None
    if not np.issubdtype(labels.dtype, np.integer):
        return None
    n = len(images)
    batch = batch_kw["batch_size"]
    if n % batch and not batch_kw["drop_remainder"]:
        return None
    if any(name in ("skip", "take", "shard") for name, _ in pre):
        return None
    if any(name not in ("prefetch", "with_options") for name, _ in post):
        return None
    shuffle = False
    for name, kw in pre:
        if name == "shuffle":
            if kw["seed"] is not None or not kw["reshuffle_each_iteration"]:
                return None
            shuffle = True
    fns = [kw["fn"] for name, kw in pre if name == "map"]
    scale, scale_op = None, "mul"
    if fns:
        detected = _detect_scale(fns, arrays)
        if detected is None:
            return None
        scale_op, scale = detected
    from tpu_dist.data.device import DeviceDataset

    out = DeviceDataset(  # shardcheck: disable=SC601 -- chain declared an UNSEEDED shuffle (seed-None guard above); a random seed IS that contract
        images, labels, global_batch_size=batch,
        seed=int(np.random.default_rng().integers(2**31)),
        shuffle=shuffle, scale=scale, scale_op=scale_op)
    logger.info("vectorize: promoted %d-element chain to device residency "
                "(%.1f MB uploaded once, index-only steps)", n,
                images.nbytes / 1e6)
    ds._device_promoted = out  # shardcheck: disable=SC900 -- promotion cache attribute, never persisted; taint ends here
    return out


def try_rewrite(ds: Dataset, *, defer_scale_to_device: bool | None = None
                ) -> Dataset | None:
    """A Dataset yielding the same batch stream as ``ds`` via index math +
    batched gathers, or None when ``ds``'s chain is outside the grammar.

    ``defer_scale_to_device`` (default: on for non-CPU jax backends) ships
    uint8 across the wire with the normalization as a device transform;
    the CPU backend keeps the native fused gather+scale instead (device ==
    host there, and the TF baseline's tf.data also scales in host C++)."""
    if not enabled():
        return None
    parsed = _parse(ds)
    if parsed is None:
        return None
    source, pre, batch_kw, post = parsed
    arrays = source._tensor_source
    n = source.cardinality()
    if n is None or n < 2:
        return None

    fns = [kw["fn"] for name, kw in pre if name == "map"]
    cache_present = any(name == "cache" for name, _ in pre)
    scale = _detect_scale(fns, arrays) if fns else None

    if defer_scale_to_device is None:
        import jax

        defer_scale_to_device = jax.default_backend() != "cpu"
    if scale is not None and scale[0] != "mul" and not defer_scale_to_device:
        # The native fused gather multiplies; a division map replayed on
        # host stays bit-exact only through the generic batched-apply path.
        scale = None
    if scale is None:
        for fn in fns:
            if not _probe_vectorizable(fn, arrays):
                logger.debug("vectorize: map fn %r not batch-safe; keeping "
                             "element path", fn)
                return None

    plan = _IndexPlan(n, pre)
    batch_size = batch_kw["batch_size"]
    drop_remainder = batch_kw["drop_remainder"]

    device_transform = None
    if scale is not None:
        from tpu_dist.data import native

        scale_op, scale_k = scale
        images, labels = (np.ascontiguousarray(np.asarray(arrays[0])),
                          np.asarray(arrays[1]))
        if defer_scale_to_device:
            device_transform = _device_scale_fn(scale_k, scale_op)

            def make_batch(idx):
                return images[idx], native.gather_labels(labels, idx)
        else:
            def make_batch(idx):
                return (native.gather_scale(images, idx, scale_k),
                        native.gather_labels(labels, idx))
    else:
        # Generic: gather (materialized-once when cached), then batch-apply
        # the probed maps. Without a cache the maps re-run per batch —
        # preserving per-pass re-execution, just vectorized.
        state: dict[str, Any] = {}

        def _materialized():
            if "arrays" not in state:
                out = arrays
                for fn in fns:
                    out = _apply_fn(fn, _map_structure(np.asarray, out))
                state["arrays"] = _map_structure(np.asarray, out)
            return state["arrays"]

        if cache_present:
            def make_batch(idx):
                return _map_structure(lambda a: a[idx], _materialized())
        else:
            def make_batch(idx):
                el = _map_structure(lambda a: np.asarray(a)[idx], arrays)
                for fn in fns:
                    el = _apply_fn(fn, el)
                return _map_structure(np.asarray, el)

    epoch_counter = [0]

    def one_pass():
        idx = plan.epoch(epoch_counter[0])
        epoch_counter[0] += 1
        m = len(idx)
        stop = m - (m % batch_size) if drop_remainder else m
        for s in range(0, stop, batch_size):
            yield make_batch(idx[s:s + batch_size])

    # Post-batch replay: fold repeat/take/skip/shard over the batch stream
    # in their RECORDED order (take-then-repeat loops the taken prefix;
    # repeat-then-take bounds the looped stream — combinator nesting).
    import itertools

    def _repeated(inner: Callable, count):
        def gen():
            done = 0
            while count is None or done < count:
                it = inner()
                empty = True
                for el in it:
                    empty = False
                    yield el
                if empty:
                    return
                done += 1
        return gen

    stream_factory: Callable = one_pass
    for name, kw in post:
        if name == "repeat":
            stream_factory = _repeated(stream_factory, kw["count"])
        elif name == "take":
            stream_factory = (lambda f=stream_factory, c=kw["count"]:
                              itertools.islice(f(), c))
        elif name == "skip":
            stream_factory = (lambda f=stream_factory, c=kw["count"]:
                              itertools.islice(f(), c, None))
        elif name == "shard":
            stream_factory = (lambda f=stream_factory, k=dict(kw):
                              itertools.islice(f(), k["index"], None,
                                               k["num_shards"]))

    def factory():
        yield from stream_factory()

    out = Dataset(factory, options=ds._options,
                  cardinality=ds.cardinality(), num_files=ds.num_files)
    out._device_transform = device_transform
    out._vectorized = True
    mode = ("fused-scale->device-u8" if device_transform is not None else
            "fused-scale-native" if scale is not None else "batched-maps")
    logger.info("vectorize: rewrote %d-op chain over %d elements (%s)",
                len(pre) + 1 + len(post), n, mode)
    # Replay any prefetch from the original chain's tail on the rewritten
    # stream (keeps background production off the consumer's critical path).
    for name, kw in post:
        if name == "prefetch":
            out = out.prefetch(kw["buffer_size"])
            break
    return out
