"""Full-model save/load: architecture + weights in one directory.

Keras-era surface (``model.save(path)`` / ``models.load_model(path)``) on the
TPU-native stack: the reference's chief-checkpointing duty (README.md:51,
SURVEY.md §5.4) covers weights via ``training.checkpoint``; this adds the
architecture half so a model round-trips WITHOUT the constructing code.

Layers are frozen dataclasses, so a config is just the class name plus its
dataclass fields (layer-valued fields — Block.layers, Residual.main/shortcut
— recurse). Weights reuse the checkpoint format (chief-writes atomic npz);
``model.json`` carries architecture + compile metadata.

    model.save("saved/mnist")                 # chief writes, others no-op
    model2 = td.models.load_model("saved/mnist")
    model2.predict(x)                         # same params, same outputs
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
from typing import Optional

CONFIG_NAME = "model.json"


def _encode_value(v):
    from tpu_dist.models.layers import Layer
    from tpu_dist.parallel.sequence import RingAttention

    if isinstance(v, Layer):
        return {"__layer__": layer_config(v)}
    if isinstance(v, RingAttention):
        # Declarative attention spec (VERDICT r2 #8): plain data, mesh
        # resolved at call time from the restoring job's strategy scope.
        # An explicitly bound mesh is deliberately NOT saved — topology is
        # the restoring job's business, not the checkpoint's.
        return {"__attention__": {
            "class": "RingAttention",
            "config": {k: getattr(v, k)
                       for k in ("axis_name", "batch_axis", "scale",
                                 "kv_chunk")}}}
    if isinstance(v, (tuple, list)):
        return [_encode_value(e) for e in v]
    if callable(v):
        # e.g. MultiHeadAttention.attention_fn=partial(ring_attention, ...)
        raise TypeError(
            f"cannot serialize layer field holding a callable ({v!r}); "
            "use the declarative spec (RingAttention(axis_name=...)) for "
            "ring attention, or save_weights()/load_weights and rebuild "
            "the architecture in code for arbitrary attention_fn hooks")
    return v


def _decode_value(v):
    if isinstance(v, dict) and "__layer__" in v:
        return layer_from_config(v["__layer__"])
    if isinstance(v, dict) and "__attention__" in v:
        from tpu_dist.parallel.sequence import RingAttention

        spec = v["__attention__"]
        # Explicit allowlist, NOT getattr on the module: a crafted
        # model.json must not be able to instantiate arbitrary importable
        # classes with attacker-chosen kwargs (ADVICE r3).
        allowed = {"RingAttention": RingAttention}
        cls = allowed.get(spec["class"])
        if cls is None:
            raise ValueError(
                f"unknown attention spec class {spec['class']!r}")
        return cls(**spec["config"])
    if isinstance(v, list):
        return tuple(_decode_value(e) for e in v)
    return v


def layer_config(layer) -> dict:
    """{"class": ..., "config": {dataclass fields}} with nested layers
    encoded recursively."""
    fields = getattr(layer, "__dataclass_fields__", None)
    if fields is None:
        raise TypeError(
            f"cannot serialize non-dataclass layer {type(layer).__name__}; "
            "custom layers need dataclass fields to round-trip")
    cfg = {name: _encode_value(getattr(layer, name)) for name in fields}
    return {"class": type(layer).__name__, "config": cfg}


def layer_from_config(spec: dict):
    from tpu_dist.models import layers as layers_mod
    from tpu_dist.models import transformer as transformer_mod

    cls = getattr(layers_mod, spec["class"],
                  getattr(transformer_mod, spec["class"], None))
    # Layer subclasses only — the modules also import unrelated classes
    # (PartitionSpec, ...) that a crafted model.json must not reach.
    if (cls is None or not isinstance(cls, type)
            or not issubclass(cls, layers_mod.Layer)):
        raise ValueError(f"unknown layer class {spec['class']!r}")
    kwargs = {k: _decode_value(v) for k, v in spec["config"].items()}
    # JSON turns tuples (kernel_size, strides, pool_size...) into lists;
    # _decode_value already restored lists to tuples.
    return cls(**kwargs)


def _obj_config(obj) -> Optional[dict]:
    """{"class", "config"} from an op object's public attrs; None when an
    attr can't round-trip through JSON (e.g. a wrapped optax transform)."""
    from tpu_dist.ops.schedules import LearningRateSchedule

    cfg = {}
    for k, v in vars(obj).items():
        if k.startswith("_"):
            continue
        if isinstance(v, LearningRateSchedule):
            inner = _obj_config(v)
            if inner is None:
                return None
            v = {"__schedule__": inner}
        elif callable(v):
            return None
        elif isinstance(v, (list, tuple)):
            # NamedTuples (e.g. optax transforms) pass an isinstance-tuple
            # check while holding functions — require JSON scalars inside.
            if not all(isinstance(e, (int, float, str, bool, type(None)))
                       for e in v):
                return None
            v = list(v)
        elif not isinstance(v, (int, float, str, bool, type(None))):
            return None
        cfg[k] = v
    return {"class": type(obj).__name__, "config": cfg}


def _obj_from_config(spec: dict, module):
    import inspect

    from tpu_dist.ops import schedules as schedules_mod

    cls = getattr(module, spec["class"], None)
    if cls is None or not isinstance(cls, type):
        raise ValueError(
            f"unknown {module.__name__.rsplit('.', 1)[-1]} class "
            f"{spec['class']!r}")
    # Saved configs carry every public attr; constructors may accept only a
    # subset (e.g. a Loss sets self.name itself) — filter to the signature.
    accepted = set(inspect.signature(cls.__init__).parameters) - {"self"}
    kwargs = {}
    for k, v in spec["config"].items():
        if k not in accepted:
            continue
        if isinstance(v, dict) and "__schedule__" in v:
            v = _obj_from_config(v["__schedule__"], schedules_mod)
        elif isinstance(v, list):
            v = tuple(v)
        kwargs[k] = v
    return cls(**kwargs)


def _compile_config(model) -> Optional[dict]:
    """Loss/optimizer/metric identifiers, or None when any of them can't be
    serialized (load_model then returns an uncompiled model)."""
    if model.loss is None or model.optimizer is None:
        return None
    loss = _obj_config(model.loss)
    opt = _obj_config(model.optimizer)
    mets = [_obj_config(m) for m in model.metrics]
    if loss is None or opt is None or any(m is None for m in mets):
        return None
    return {"loss": loss, "optimizer": opt, "metrics": mets,
            "steps_per_execution": model.steps_per_execution}


def model_config(model) -> dict:
    from tpu_dist.models.model import Sequential

    if not isinstance(model, Sequential):
        raise TypeError(
            f"save/load supports Sequential models, got {type(model).__name__}")
    cfg = {
        "format": "tpu_dist.sequential.v1",
        "name": model.name,
        "input_shape": list(model.input_shape) if model.input_shape else None,
        "layers": [layer_config(l) for l in model.layers],
    }
    compiled = _compile_config(model)
    if compiled:
        cfg["compile"] = compiled
    return cfg


def save_model(model, directory) -> None:
    """Architecture (model.json, chief-only write) + weights (checkpoint
    step 0). Safe in multi-process jobs: non-chief processes write nothing,
    but every process MUST call this — checkpoint.save ends in a barrier,
    and when variables carry model-sharded (tensor-parallel) leaves it also
    allgathers them across processes, both collectives all peers join."""
    from tpu_dist.cluster import bootstrap
    from tpu_dist.models.model import Sequential
    from tpu_dist.training import checkpoint
    from tpu_dist.training.trainer import Trainer

    # Type check on EVERY process before any side effects: a chief-only
    # failure here would leave non-chief processes blocked at the
    # checkpoint barrier below.
    if not isinstance(model, Sequential):
        raise TypeError(
            f"save/load supports Sequential models, got {type(model).__name__}")
    directory = pathlib.Path(directory)
    if model._trainer is None:
        model._trainer = Trainer(model)
    model._trainer.ensure_variables()
    # Encode on EVERY process (not just the chief): an unserializable layer
    # field (e.g. a ring attention_fn) must raise everywhere, or non-chief
    # processes would block at the checkpoint barrier below.
    encoded = json.dumps(model_config(model), indent=2)
    if bootstrap.is_chief():
        directory.mkdir(parents=True, exist_ok=True)
        tmp = directory / f".{CONFIG_NAME}.tmp.{os.getpid()}"
        tmp.write_text(encoded)
        os.replace(tmp, directory / CONFIG_NAME)
    checkpoint.save(directory, model, step=0)


def load_model(directory, *, compile: bool = True):
    """Rebuild the Sequential from model.json, restore weights, and (by
    default) re-compile from the saved loss/optimizer/metric identifiers."""
    from tpu_dist.models.model import Sequential
    from tpu_dist.training import checkpoint

    directory = pathlib.Path(directory)
    spec = json.loads((directory / CONFIG_NAME).read_text())
    if spec.get("format") != "tpu_dist.sequential.v1":
        raise ValueError(f"unrecognized saved-model format in {directory}")
    model = Sequential(
        [layer_from_config(l) for l in spec["layers"]],
        input_shape=tuple(spec["input_shape"]) if spec["input_shape"]
        else None,
        name=spec.get("name", "sequential"))
    if compile and spec.get("compile"):
        from tpu_dist.ops import losses as losses_mod
        from tpu_dist.ops import metrics as metrics_mod
        from tpu_dist.ops import optimizers as optimizers_mod

        c = spec["compile"]
        model.compile(
            loss=_obj_from_config(c["loss"], losses_mod),
            optimizer=_obj_from_config(c["optimizer"], optimizers_mod),
            metrics=[_obj_from_config(m, metrics_mod)
                     for m in c.get("metrics", [])],
            steps_per_execution=c.get("steps_per_execution", 1))
    model.load_weights(directory, step=0)
    return model
