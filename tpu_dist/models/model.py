"""Model containers: functional core + Keras-style compile/fit surface.

Reproduces the high-level training API the reference script uses (SURVEY.md
R5/R6, L5): ``Sequential([...])`` -> ``compile(loss, optimizer, metrics)`` ->
``fit(dataset, epochs, steps_per_epoch)`` (tf_dist_example.py:39-59), so the
reference example ports line-for-line. Underneath, a Model is two pure
functions over pytrees —

    variables = model.init(seed, input_shape)        # {'params':…, 'state':…}
    logits, new_state = model.apply(variables['params'], variables['state'],
                                    x, training=True, rng=key)

— which is exactly what the jitted SPMD train step consumes. ``compile``
captures the active strategy from the surrounding ``strategy.scope()``
(tf_dist_example.py:56-57 semantics): under TF the scope intercepts variable
creation; here it pins which mesh the variables will be replicated onto when
``fit`` first touches them.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

from tpu_dist.models.layers import Layer
from tpu_dist.ops import losses as losses_lib
from tpu_dist.ops import metrics as metrics_lib
from tpu_dist.ops import optimizers as optimizers_lib

Variables = dict  # {'params': pytree, 'state': pytree}


class Model:
    """A named pair of (init_fn, apply_fn) plus compile/fit surface.

    init_fn(key, input_shape) -> (params, state)
    apply_fn(params, state, x, training, rng) -> (outputs, new_state)
    """

    def __init__(self, init_fn: Callable, apply_fn: Callable,
                 input_shape: Optional[tuple] = None, name: str = "model"):
        self._init_fn = init_fn
        self._apply_fn = apply_fn
        self.input_shape = input_shape
        self.name = name
        # Set by compile():
        self.optimizer = None
        self.loss = None
        self.metrics: list = []
        self.steps_per_execution = 1
        self.gradient_bucket_bytes = 0
        self.prefetch_to_device = 0
        self.strategy = None
        self._trainer = None
        self._carryover: Optional[dict] = None  # weights across recompiles

    # -- functional core -----------------------------------------------------

    def init(self, seed: int | jax.Array = 0,
             input_shape: Optional[tuple] = None) -> Variables:
        shape = input_shape or self.input_shape
        if shape is None:
            raise ValueError(
                f"{self.name}: input_shape unknown; pass it to init() or set "
                "it on the model")
        key = jax.random.PRNGKey(seed) if isinstance(seed, int) else seed
        params, state = self._init_fn(key, tuple(shape))
        return {"params": params, "state": state}

    def apply(self, params, state, x, *, training: bool = False, rng=None):
        return self._apply_fn(params, state, x, training, rng)

    def __call__(self, variables: Variables, x, *, training: bool = False,
                 rng=None):
        out, _ = self.apply(variables["params"], variables["state"], x,
                            training=training, rng=rng)
        return out

    # -- Keras-style training surface (SURVEY.md D15/D16) ---------------------

    def compile(self, optimizer="sgd", loss=None, metrics=(),
                steps_per_execution: int = 1,
                gradient_bucket_bytes: int = 0,
                prefetch_to_device: int = 0) -> None:
        """Record loss/optimizer/metrics and capture the scoped strategy
        (tf_dist_example.py:50-53 surface).

        ``steps_per_execution``: run K train steps inside one compiled
        dispatch (``lax.scan``) — the Keras knob of the same name; a large
        win when per-step device time is smaller than host dispatch overhead
        (tiny-model training; SURVEY.md hard-part #5). Batch-level callbacks
        and the progress bar then advance once per execution.

        ``gradient_bucket_bytes``: 0 (default) keeps the fused schedule —
        one implicit end-of-step gradient all-reduce, scheduled by the XLA
        partitioner. > 0 switches the train step to the explicit bucketed
        schedule: gradients are reduced in reverse-topological buckets of
        roughly this many bytes so early buckets overlap the remaining
        backward compute (README.md "Step-time performance"; the schedules
        agree to float tolerance, not bitwise — the bucketed step averages
        per-shard means).

        ``prefetch_to_device``: 0 (default) fetches each batch on the hot
        loop; > 0 double-buffers input — a background thread device_puts up
        to this many batches ahead while the current step runs, driving the
        trainer's measured ``data_wait_s`` toward zero.
        """
        from tpu_dist.parallel.strategy import get_strategy

        if steps_per_execution < 1:
            raise ValueError(
                f"steps_per_execution must be >= 1, got {steps_per_execution}")
        if gradient_bucket_bytes < 0:
            raise ValueError(
                f"gradient_bucket_bytes must be >= 0, got "
                f"{gradient_bucket_bytes}")
        if prefetch_to_device < 0:
            raise ValueError(
                f"prefetch_to_device must be >= 0, got {prefetch_to_device}")
        self.optimizer = optimizers_lib.get(optimizer)
        self.loss = losses_lib.get(loss) if loss is not None else None
        self.metrics = [metrics_lib.get(m) for m in metrics]
        self.steps_per_execution = int(steps_per_execution)
        self.gradient_bucket_bytes = int(gradient_bucket_bytes)
        self.prefetch_to_device = int(prefetch_to_device)
        self.strategy = get_strategy()
        # Invalidate the jitted step but carry trained weights forward —
        # recompiling must not reset a trained model (Keras fine-tuning
        # workflow). Optimizer slots are re-created (shapes/algorithm may
        # have changed).
        if self._trainer is not None and self._trainer.variables is not None:
            self._carryover = {
                k: self._trainer.variables[k] for k in ("params", "state")}
        self._trainer = None

    def fit(self, x, epochs: int = 1, steps_per_epoch: Optional[int] = None,
            verbose: int = 1, callbacks: Sequence = (), initial_epoch: int = 0,
            seed: int = 0, profile_dir: Optional[str] = None,
            validation_data=None, validation_steps: Optional[int] = None,
            checkpoint_dir: Optional[str] = None,
            class_weight: Optional[dict] = None):
        """Run the epoch/step loop (tf_dist_example.py:59 surface).

        ``profile_dir`` captures a chief-only jax.profiler trace (SURVEY.md
        §5.1). ``validation_data`` runs a validation pass each epoch,
        reported as ``val_``-prefixed logs. ``checkpoint_dir`` enables
        chief-only per-epoch checkpointing AND resume-from-latest (SURVEY.md
        §5.4): if the directory already holds checkpoints, training continues
        from the epoch after the newest one. ``class_weight`` scales each
        sample's loss contribution by its class's weight (Keras semantics
        for imbalanced data; the weight table compiles into the step)."""
        from tpu_dist.training.trainer import Trainer

        if self.loss is None or self.optimizer is None:
            raise RuntimeError(
                f"{self.name} must be compile()d with a loss and optimizer "
                "before fit()")
        if self._trainer is None:
            self._trainer = Trainer(self)
        return self._trainer.fit(
            x, epochs=epochs, steps_per_epoch=steps_per_epoch,
            verbose=verbose, callbacks=callbacks, initial_epoch=initial_epoch,
            seed=seed, profile_dir=profile_dir,
            validation_data=validation_data,
            validation_steps=validation_steps,
            checkpoint_dir=checkpoint_dir,
            class_weight=class_weight)

    def evaluate(self, x, steps: Optional[int] = None, verbose: int = 1):
        from tpu_dist.training.trainer import Trainer

        if self.loss is None:
            raise RuntimeError(
                f"{self.name} must be compile()d with a loss before "
                "evaluate()")
        if self._trainer is None:
            self._trainer = Trainer(self)
        return self._trainer.evaluate(x, steps=steps, verbose=verbose)

    def predict(self, x):
        from tpu_dist.training.trainer import Trainer

        if self._trainer is None:
            self._trainer = Trainer(self)
        return self._trainer.predict(x)

    def make_train_function(self, steps_per_execution: Optional[int] = None):
        """The jitted SPMD train step (Keras-2 name; SURVEY.md D15) — see
        ``Trainer.make_train_function`` for the callable's contract."""
        from tpu_dist.training.trainer import Trainer

        if self.loss is None or self.optimizer is None:
            raise RuntimeError(
                f"{self.name} must be compile()d with a loss and optimizer "
                "before make_train_function()")
        if self._trainer is None:
            self._trainer = Trainer(self)
        return self._trainer.make_train_function(steps_per_execution)

    def train_state(self) -> tuple:
        """Fresh ``(params, state, opt, metrics, loss_acc)`` for the
        ``make_train_function`` callable."""
        from tpu_dist.training.trainer import Trainer

        if self._trainer is None:
            self._trainer = Trainer(self)
        return self._trainer.train_state()

    @property
    def variables(self) -> Optional[Variables]:
        """Live training variables, once fit/evaluate has materialized them."""
        return self._trainer.variables if self._trainer is not None else None

    def save(self, directory):
        """Full-model save: architecture + weights (+ compile config when
        serializable) in one directory; reload with
        ``tpu_dist.models.load_model``. Chief-only writes (§5.4)."""
        from tpu_dist.models import serialize

        return serialize.save_model(self, directory)

    def save_weights(self, directory, step: int = 0):
        """Chief-only checkpoint write (README.md:51 chief duty; §5.4)."""
        from tpu_dist.training import checkpoint

        return checkpoint.save(directory, self, step=step)

    def load_weights(self, directory, step: Optional[int] = None) -> int:
        """Restore training variables from the latest (or given) checkpoint."""
        from tpu_dist.training import checkpoint

        return checkpoint.restore_model(directory, self, step=step)


class Sequential(Model):
    """Linear layer stack — the reference model container
    (tf_dist_example.py:40)."""

    def __init__(self, layers: Sequence[Layer], *,
                 input_shape: Optional[tuple] = None, name: str = "sequential"):
        self.layers = list(layers)
        if not self.layers:
            raise ValueError("Sequential needs at least one layer")
        self.layer_names = self._unique_names(self.layers)
        super().__init__(self._init_layers, self._apply_layers,
                         input_shape=input_shape, name=name)

    @staticmethod
    def _unique_names(layers: Sequence[Layer]) -> list[str]:
        from tpu_dist.models.layers import unique_layer_names

        return unique_layer_names(layers)

    def _init_layers(self, key, input_shape):
        from tpu_dist.models.layers import init_chain

        params, state, shape = init_chain(self.layers, self.layer_names, key,
                                          tuple(input_shape))
        self.output_shape = shape
        return params, state

    def _apply_layers(self, params, state, x, training, rng):
        from tpu_dist.models.layers import apply_chain
        from tpu_dist.models.policy import compute_dtype

        # Mixed-precision entry/exit casts (policy.py): activations run in the
        # compute dtype, the returned logits in float32 for a stable loss.
        dtype = compute_dtype()
        if x.dtype != dtype and jax.numpy.issubdtype(x.dtype, jax.numpy.floating):
            x = x.astype(dtype)
        y, new_state = apply_chain(self.layers, self.layer_names, params,
                                   state, x, training=training, rng=rng)
        if jax.numpy.issubdtype(y.dtype, jax.numpy.floating):
            y = y.astype(jax.numpy.float32)
        return y, new_state

    def summary(self) -> str:
        """Keras-style layer table: name, type, output shape, param count
        (shapes/counts need a known ``input_shape``; the dry per-layer init
        used to derive them is host-side and tiny)."""
        header = f"{'Layer (name)':<26}{'Type':<22}{'Output shape':<18}{'Params':>10}"
        lines = [f'Model: "{self.name}"', "=" * len(header), header,
                 "-" * len(header)]
        if self.input_shape is None:
            for name, layer in zip(self.layer_names, self.layers):
                lines.append(f"{name:<26}{type(layer).__name__:<22}"
                             f"{'?':<18}{'?':>10}")
            lines.append("-" * len(header))
            lines.append("(input_shape unknown — shapes/params unavailable)")
        else:
            import math

            def count(tree):
                return sum(math.prod(a.shape) for a in
                           jax.tree_util.tree_leaves(tree))

            key = jax.random.PRNGKey(0)
            shape = tuple(self.input_shape)
            total = total_state = 0
            for name, layer in zip(self.layer_names, self.layers):
                # eval_shape: shapes/counts WITHOUT materializing params
                # (a real init would run every initializer and allocate the
                # full model — tens of MB for the ResNets — per summary()).
                # The out-shape is plain Python computed during tracing, so
                # capture it; the abstracted pytrees carry the shapes.
                captured = {}

                def abstract_init(k, layer=layer, shape=shape):
                    p, s, out = layer.init(k, shape)
                    captured["out"] = out
                    return p, s

                p_spec, s_spec = jax.eval_shape(abstract_init, key)
                shape = captured["out"]
                n = count(p_spec)
                total += n
                total_state += count(s_spec)
                lines.append(f"{name:<26}{type(layer).__name__:<22}"
                             f"{str(tuple(shape)):<18}{n:>10,}")
            lines.append("-" * len(header))
            lines.append(f"Trainable params: {total:,}")
            if total_state:
                lines.append(f"Non-trainable state: {total_state:,}")
        out = "\n".join(lines)
        print(out)
        return out
