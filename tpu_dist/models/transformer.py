"""Transformer layers: the long-context model family.

Beyond the reference's parity scope (its model zoo is a 2-conv CNN +
benchmark ResNets, SURVEY.md R5/§2.3) — this family exists so the
sequence-parallel axis (tpu_dist.parallel.sequence) has a first-class model
to drive: :class:`MultiHeadAttention` takes a pluggable ``attention_fn``, so
the same block runs dense softmax attention on one device or EXACT ring
attention over a ``seq`` mesh axis for contexts that don't fit one device:

    from functools import partial
    from tpu_dist.parallel import make_mesh, ring_attention

    mesh = make_mesh({"data": 2, "seq": 4})
    attn = partial(ring_attention, mesh=mesh, axis_name="seq",
                   causal=True, batch_axis="data")
    block = TransformerBlock(d_model=512, num_heads=8, ff_dim=2048,
                             attention_fn=attn)

All layers follow the pure-functional Layer protocol (layers.py): immutable
dataclass descriptions, params/state pytrees owned by the caller, everything
jit-traceable. TPU notes: attention and MLP matmuls are MXU-shaped; under
``set_policy("mixed_bfloat16")`` activations run bf16 with fp32 params and
LayerNorm statistics.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_dist.models.layers import Block, Dense, Layer, Residual
from tpu_dist.ops import initializers
# Re-exported here so model.json deserialization (models/serialize.py
# resolves layer classes from this module) can round-trip pipelined and
# mixture-of-experts LMs.
from tpu_dist.parallel.expert import MixtureOfExperts  # noqa: F401
from tpu_dist.parallel.pipeline_parallel import PipelinedBlocks  # noqa: F401


@dataclasses.dataclass(frozen=True, repr=False)
class Embedding(Layer):
    """Token embedding: int [L] -> float [L, dim] lookup table."""

    vocab_size: int
    dim: int
    #: GPT-style init scale (normal); Keras' uniform(-0.05, 0.05) converges
    #: slower at transformer depth.
    init_scale: float = 0.02

    def init(self, key, in_shape):
        table = self.init_scale * jax.random.normal(
            key, (self.vocab_size, self.dim), jnp.float32)
        return {"table": table}, {}, (*in_shape, self.dim)

    def apply(self, params, state, x, *, training=False, rng=None):
        from tpu_dist.models.policy import compute_dtype

        return params["table"].astype(compute_dtype())[x], state


@dataclasses.dataclass(frozen=True, repr=False)
class PositionalEmbedding(Layer):
    """Learned absolute positions, added to a [.., L, D] stream."""

    max_len: int
    init_scale: float = 0.02

    def init(self, key, in_shape):
        ln, d = in_shape[-2], in_shape[-1]
        if ln > self.max_len:
            raise ValueError(
                f"sequence length {ln} exceeds max_len {self.max_len}")
        table = self.init_scale * jax.random.normal(
            key, (self.max_len, d), jnp.float32)
        return {"table": table}, {}, in_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        ln = x.shape[-2]
        return x + params["table"][:ln].astype(x.dtype), state


@dataclasses.dataclass(frozen=True, repr=False)
class LayerNormalization(Layer):
    """LayerNorm over the last axis; statistics in float32 always."""

    epsilon: float = 1e-5

    def init(self, key, in_shape):
        d = in_shape[-1]
        return ({"gamma": jnp.ones((d,), jnp.float32),
                 "beta": jnp.zeros((d,), jnp.float32)}, {}, in_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = xf.var(axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        y = y * params["gamma"] + params["beta"]
        return y.astype(x.dtype), state


def _dense_attention(q, k, v, *, causal: bool, scale: float):
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        ln = q.shape[-2]
        mask = jnp.tril(jnp.ones((ln, ln), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _mesh_mapped_flash(q, *, causal: bool, scale: float,
                       interpret: bool | None = None):
    """shard_map'd flash attention over the active strategy's mesh, or
    None when inapplicable.

    The fused kernel's custom call is opaque to XLA's SPMD partitioner:
    left unwrapped on a >1-device mesh, GSPMD all-gathers the sharded
    q/k/v around it and every device recomputes the GLOBAL batch's
    attention — silently, in the most common distributed configurations.
    Batch entries and heads are independent attention instances, so
    mapping the kernel per data-shard (batch dim) and per model-shard
    (head dim) is exact — the same composition the ring path uses for its
    seq axis. Declines (returns None) when: no strategy scope / 1-device
    mesh; a mesh axis is already bound (e.g. applied inside
    ``strategy.run`` — binding it twice would raise); no divisible
    data/model axis; or the per-shard shape is outside the kernel's
    envelope."""
    from tpu_dist.ops import flash_attention as fa
    from tpu_dist.parallel import mesh as mesh_lib
    from tpu_dist.parallel.strategy import get_strategy, has_strategy

    if q.ndim != 4 or not has_strategy():
        return None
    strategy = get_strategy()
    mesh = strategy.mesh
    if mesh.devices.size <= 1 or mesh_lib.inside_manual_axes(mesh):
        return None
    b, h, _, _ = q.shape

    def usable(axis, dim):
        size = mesh.shape.get(axis, 1)
        return axis if size > 1 and dim % size == 0 else None

    d_axis = usable(strategy.data_axis, b)
    m_axis = usable(mesh_lib.MODEL_AXIS, h)
    if d_axis is None and m_axis is None:
        return None
    d_size = mesh.shape.get(d_axis, 1)
    m_size = mesh.shape.get(m_axis, 1)
    # The kernel must support the PER-SHARD shape.
    shard = jax.ShapeDtypeStruct((b // d_size, h // m_size, *q.shape[2:]),
                                 q.dtype)
    if not fa.supported(shard):
        return None

    shard_map = mesh_lib.get_shard_map()
    spec = P(d_axis, m_axis, None, None)
    body = functools.partial(fa.flash_attention, causal=causal, scale=scale,
                             interpret=interpret)
    try:
        # pallas_call's out_shape carries no varying-mesh-axes type, so the
        # vma checker can't see through the custom call; the body is
        # per-shard pure, which is exactly what disabling the check asserts.
        return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
    except TypeError:  # pragma: no cover - older jax spells it check_rep
        return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_rep=False)


def _unwrapped_flash_safe() -> bool:
    """Whether the RAW (un-shard_map'd) Pallas kernel can run without GSPMD
    silently all-gathering its operands: true when nothing is sharded (no
    strategy scope / 1-device mesh) or when the caller is already inside the
    mesh's manual axes (``strategy.run`` / shard_map — operands are per-shard
    values there). On a >1-device mesh OUTSIDE manual axes the custom call is
    opaque to the partitioner, so the only safe fallbacks are a mapped kernel
    or dense attention. NOTE the polarity on an unreadable axis env:
    ``manual_axes_state() is True`` — "can't confirm" must gate the raw
    kernel OFF here, the opposite of inside_manual_axes's decline default."""
    from tpu_dist.parallel import mesh as mesh_lib
    from tpu_dist.parallel.strategy import get_strategy, has_strategy

    if not has_strategy():
        return True
    mesh = get_strategy().mesh
    return (mesh.devices.size <= 1
            or mesh_lib.manual_axes_state(mesh) is True)


def _default_attention(q, k, v, *, causal: bool, scale: float):
    """Attention dispatch: the fused flash kernel (ops/flash_attention.py)
    on TPU for supported shapes — O(L) memory, tiled online softmax; on a
    >1-device mesh the kernel maps per data/model shard via shard_map
    (batch entries and heads are independent). When no shard mapping
    applies (indivisible batch/heads, per-shard shape outside the kernel
    envelope) the UNWRAPPED kernel runs only where it cannot be silently
    all-gathered (single device, or already inside manual axes); otherwise
    dense attention runs — GSPMD partitions it natively (ADVICE r3).
    TPU_DIST_FLASH=0 forces dense for A/B measurement."""
    from tpu_dist.ops import flash_attention as fa

    if fa.use_flash(q):
        mapped = _mesh_mapped_flash(q, causal=causal, scale=scale)
        if mapped is not None:
            return mapped(q, k, v)
        if _unwrapped_flash_safe():
            return fa.flash_attention(q, k, v, causal=causal, scale=scale)
    return _dense_attention(q, k, v, causal=causal, scale=scale)


@dataclasses.dataclass(frozen=True, repr=False)
class MultiHeadAttention(Layer):
    """Multi-head self-attention on a [.., L, D] stream.

    ``attention_fn(q, k, v, causal=...) -> out`` (shapes [B, H, L, key_dim])
    swaps the attention inner loop: default is dense softmax (``causal``
    applies the autoregressive mask); pass ``functools.partial(ring_attention,
    mesh=..., axis_name='seq')`` for sequence-parallel exact attention — the
    layer forwards its own ``causal`` flag (a partial that already binds
    ``causal=`` must agree or apply() raises), so the flag can never be
    silently dropped. The projections stay identical, so the two paths are
    numerically interchangeable (tests assert it). For full-model save use
    the declarative spec (``tpu_dist.parallel.RingAttention``) — arbitrary
    callables can't serialize; save weights and rebuild in code instead.
    """

    num_heads: int
    key_dim: int
    causal: bool = False
    use_bias: bool = True
    kernel_initializer: str = "glorot_uniform"
    attention_fn: Optional[Callable] = None

    def init(self, key, in_shape):
        d = in_shape[-1]
        h, dk = self.num_heads, self.key_dim
        ks = jax.random.split(key, 4)
        mk = initializers.get(self.kernel_initializer)
        params = {
            "wq": mk(ks[0], (d, h * dk)),
            "wk": mk(ks[1], (d, h * dk)),
            "wv": mk(ks[2], (d, h * dk)),
            "wo": mk(ks[3], (h * dk, d)),
        }
        if self.use_bias:
            z = lambda n: jnp.zeros((n,), jnp.float32)
            params.update(bq=z(h * dk), bk=z(h * dk), bv=z(h * dk), bo=z(d))
        return params, {}, in_shape

    def _heads(self, x, w, b):
        y = x @ w.astype(x.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        *lead, ln, _ = y.shape
        y = y.reshape(*lead, ln, self.num_heads, self.key_dim)
        return jnp.moveaxis(y, -2, -3)  # [.., H, L, dk]

    def apply(self, params, state, x, *, training=False, rng=None):
        b = (lambda n: params[n]) if self.use_bias else (lambda n: None)
        q = self._heads(x, params["wq"], b("bq"))
        k = self._heads(x, params["wk"], b("bk"))
        v = self._heads(x, params["wv"], b("bv"))
        if self.attention_fn is not None:
            # Forward the layer's causal flag so attention_fn models can't
            # silently be non-causal (ADVICE r2). A functools.partial chain
            # that already binds causal= must agree with the layer. Walk the
            # whole chain: at call time an OUTER partial's kwargs override an
            # inner one's, so the effective binding is innermost-first with
            # outer layers winning.
            chain, fn = [], self.attention_fn
            while isinstance(fn, functools.partial):
                chain.append(fn.keywords or {})
                fn = fn.func
            bound: dict = {}
            for kw in reversed(chain):
                bound.update(kw)
            if "causal" in bound:
                if bool(bound["causal"]) != bool(self.causal):
                    raise ValueError(
                        f"MultiHeadAttention(causal={self.causal}) conflicts "
                        f"with attention_fn binding causal={bound['causal']}")
                out = self.attention_fn(q, k, v)
            else:
                out = self.attention_fn(q, k, v, causal=self.causal)
        else:
            out = _default_attention(q, k, v, causal=self.causal,
                                     scale=1.0 / math.sqrt(self.key_dim))
        out = jnp.moveaxis(out, -3, -2)  # [.., L, H, dk]
        *lead, ln, h, dk = out.shape
        out = out.reshape(*lead, ln, h * dk)
        y = out @ params["wo"].astype(out.dtype)
        if self.use_bias:
            y = y + params["bo"].astype(y.dtype)
        return y, state


def TransformerBlock(d_model: int, num_heads: int, ff_dim: int,
                     key_dim: Optional[int] = None, causal: bool = False,
                     activation: str = "gelu",
                     attention_fn: Optional[Callable] = None,
                     epsilon: float = 1e-5,
                     moe=None) -> Block:
    """Pre-LN transformer block: x + MHA(LN(x)), then x + MLP(LN(x)) —
    built from the existing Residual container (identity shortcut), so
    params nest exactly like the ResNet blocks. ``d_model`` is the residual
    stream width (the MLP projects ff_dim back to it); ``key_dim`` defaults
    to d_model / num_heads. ``moe`` (a
    :class:`tpu_dist.parallel.MixtureOfExperts`) replaces the dense MLP
    with the expert-parallel FFN — the Switch-transformer block shape."""
    if key_dim is None:
        if d_model % num_heads:
            raise ValueError(
                f"d_model {d_model} not divisible by num_heads {num_heads}; "
                "pass key_dim explicitly")
        key_dim = d_model // num_heads
    attn = Residual(
        main=(LayerNormalization(epsilon=epsilon),
              MultiHeadAttention(num_heads=num_heads, key_dim=key_dim,
                                 causal=causal, attention_fn=attention_fn)),
        shortcut=(), activation=None)
    ffn = ((moe,) if moe is not None
           else (Dense(ff_dim, activation=activation), Dense(d_model)))
    mlp = Residual(
        main=(LayerNormalization(epsilon=epsilon), *ffn),
        shortcut=(), activation=None)
    return Block(layers=(attn, mlp))


def build_transformer_lm(vocab_size: int, seq_len: int, *, d_model: int = 128,
                         depth: int = 2, num_heads: int = 4,
                         ff_dim: Optional[int] = None,
                         attention_fn: Optional[Callable] = None,
                         pipeline_stages: Optional[int] = None,
                         pipeline_microbatches: int = 4,
                         moe_experts: Optional[int] = None,
                         moe_top_k: int = 2,
                         moe_capacity_factor: float = 1.25,
                         moe_groups: Optional[int] = None,
                         moe_every: int = 1):
    """A small causal (GPT-style) language model: token + position
    embeddings, ``depth`` pre-LN blocks, final LN, vocab head. Inputs are
    int token ids [B, L]; outputs are logits [B, L, vocab].

    ``pipeline_stages=S`` wraps the block stack in
    :class:`tpu_dist.parallel.PipelinedBlocks` (``depth`` must divide by
    S): under a mesh with a ``pipe`` axis of size S the stages GPipe-
    pipeline with ``pipeline_microbatches`` microbatches; elsewhere the
    same stacked weights run sequentially.

    ``moe_experts=E`` makes every ``moe_every``-th block a
    Switch-transformer block (:class:`tpu_dist.parallel.MixtureOfExperts`
    replaces the dense MLP; ``ff_dim`` sizes each expert): under a mesh
    with an ``expert`` axis the experts shard and tokens all_to_all;
    elsewhere the same stacked experts run locally. MoE and
    ``pipeline_stages`` are mutually exclusive (the aux loss is state the
    pipeline cannot thread)."""
    from tpu_dist.models.model import Sequential

    ff_dim = ff_dim or 4 * d_model
    if moe_experts and pipeline_stages:
        raise ValueError("moe_experts and pipeline_stages are mutually "
                         "exclusive (see docstring)")
    layers = [Embedding(vocab_size, d_model),
              PositionalEmbedding(max_len=seq_len)]

    def mk_moe():
        return MixtureOfExperts(
            num_experts=moe_experts, ff_dim=ff_dim, top_k=moe_top_k,
            capacity_factor=moe_capacity_factor, groups=moe_groups)

    def mk_block(i: int = 0):
        moe = (mk_moe() if moe_experts and i % max(moe_every, 1) == 0
               else None)
        return TransformerBlock(
            d_model, num_heads, ff_dim, causal=True,
            attention_fn=attention_fn, moe=moe)
    if pipeline_stages:
        if depth % pipeline_stages:
            raise ValueError(
                f"depth {depth} not divisible by pipeline_stages "
                f"{pipeline_stages}")
        per_stage = depth // pipeline_stages
        stage = (mk_block() if per_stage == 1
                 else Block(layers=tuple(mk_block()
                                         for _ in range(per_stage))))
        layers.append(PipelinedBlocks(block=stage,
                                      num_stages=pipeline_stages,
                                      microbatches=pipeline_microbatches))
    else:
        for i in range(depth):
            layers.append(mk_block(i))
    layers += [LayerNormalization(), Dense(vocab_size)]
    return Sequential(layers, input_shape=(seq_len,),
                      name="transformer_lm")
