"""ResNet-18 / ResNet-50 — the benchmark models (BASELINE.md configs 4-5).

The reference itself ships only the MNIST CNN (tf_dist_example.py:39-53); the
driver's baseline adds Fashion-MNIST ResNet-18 and CIFAR-10 ResNet-50 to
stress the gradient all-reduce payload (SURVEY.md §6). Standard He-style
residual networks:

* ResNet-18: BasicBlock (3x3 + 3x3), stages [2, 2, 2, 2], widths 64-512.
* ResNet-50: Bottleneck (1x1 → 3x3 → 1x1·4), stages [3, 4, 6, 3].

Small-image inputs (CIFAR/MNIST scale, <= 64 px) get the CIFAR stem — one 3x3
stride-1 conv, no max-pool — instead of the ImageNet 7x7/2 + pool stem, which
would collapse 28-32 px inputs to nothing. TPU notes: NHWC layout throughout
(layers.py maps convs onto the MXU via XLA); BatchNorm statistics are computed
over the *global* sharded batch, so multi-worker training gets synchronized BN
with no extra machinery; under ``set_policy("mixed_bfloat16")`` activations run
in bfloat16 with float32 params/statistics.
"""

from __future__ import annotations

from tpu_dist.models.layers import (
    Activation,
    BatchNormalization,
    Block,
    Conv2D,
    Dense,
    GlobalAveragePooling2D,
    MaxPooling2D,
    Residual,
)
from tpu_dist.models.model import Sequential


def _conv_bn(filters: int, kernel: int, strides: int = 1,
             activation: str | None = "relu") -> list:
    layers = [
        Conv2D(filters, kernel, strides=strides, padding="same",
               use_bias=False, kernel_initializer="he_normal"),
        BatchNormalization(momentum=0.9, epsilon=1e-5),
    ]
    if activation:
        layers.append(Activation(activation))
    return layers


def _basic_block(filters: int, strides: int, project: bool) -> Residual:
    main = (*_conv_bn(filters, 3, strides),
            *_conv_bn(filters, 3, activation=None))
    shortcut = tuple(_conv_bn(filters, 1, strides, activation=None)
                     ) if project else ()
    return Residual(main=main, shortcut=shortcut)


def _bottleneck_block(filters: int, strides: int, project: bool) -> Residual:
    out = filters * 4
    main = (*_conv_bn(filters, 1),
            *_conv_bn(filters, 3, strides),
            *_conv_bn(out, 1, activation=None))
    shortcut = tuple(_conv_bn(out, 1, strides, activation=None)
                     ) if project else ()
    return Residual(main=main, shortcut=shortcut)


def _stage(block_fn, filters: int, blocks: int, first_strides: int,
           first_projects: bool) -> Block:
    layers = [block_fn(filters, first_strides, first_projects)]
    layers += [block_fn(filters, 1, False) for _ in range(blocks - 1)]
    return Block(layers=tuple(layers))


def _resnet(block_fn, stage_blocks: list[int], num_classes: int,
            input_shape: tuple, name: str) -> Sequential:
    small = input_shape[0] <= 64
    if small:
        stem = _conv_bn(64, 3)
    else:
        stem = [*_conv_bn(64, 7, strides=2),
                MaxPooling2D(pool_size=3, strides=2, padding="same")]
    # Stage 1 keeps stride 1; bottleneck widening means even stage 1 projects.
    projects_first = block_fn is _bottleneck_block
    stages = [
        _stage(block_fn, 64, stage_blocks[0], 1, projects_first),
        _stage(block_fn, 128, stage_blocks[1], 2, True),
        _stage(block_fn, 256, stage_blocks[2], 2, True),
        _stage(block_fn, 512, stage_blocks[3], 2, True),
    ]
    return Sequential(
        [*stem, *stages, GlobalAveragePooling2D(),
         Dense(num_classes, kernel_initializer="glorot_uniform")],
        input_shape=input_shape, name=name)


def ResNet18(num_classes: int = 10,
             input_shape: tuple = (32, 32, 3)) -> Sequential:
    return _resnet(_basic_block, [2, 2, 2, 2], num_classes, input_shape,
                   "resnet18")


def ResNet50(num_classes: int = 10,
             input_shape: tuple = (32, 32, 3)) -> Sequential:
    return _resnet(_bottleneck_block, [3, 4, 6, 3], num_classes, input_shape,
                   "resnet50")
