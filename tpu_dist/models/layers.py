"""Neural-net layers: pure-functional, shape-inferring, Keras-surface-compatible.

Covers the layer vocabulary the reference model needs (SURVEY.md R5:
Conv2D / MaxPooling2D / Flatten / Dense with relu activations,
tf_dist_example.py:40-49) plus BatchNormalization / pooling / Dropout for the
ResNet benchmark models (BASELINE.md configs 4-5).

Design (the idiom shift from Keras, SURVEY.md D4/D17): a layer is an immutable
*description*; parameters and mutable state (BatchNorm running stats) live in
plain pytrees owned by the caller:

    params, state, out_shape = layer.init(key, in_shape)   # shapes sans batch
    y, new_state = layer.apply(params, state, x, training=True)

Everything is jit-traceable; there are no Python-side variables to mirror —
replication is a sharding annotation on the pytrees (tpu_dist.parallel.mesh).
TPU notes: convs/matmuls use NHWC / HWIO layouts which XLA maps onto the MXU;
``compute_dtype=bfloat16`` (via models.Policy) casts activations while keeping
params and BN statistics in float32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from tpu_dist.ops import initializers

Params = Any
State = Any
Shape = tuple[int, ...]

_ACTIVATIONS = {
    None: lambda x: x,
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
}


def _activation(name) -> Callable:
    if callable(name):
        return name
    if name not in _ACTIVATIONS:
        raise ValueError(
            f"unknown activation {name!r}; available: "
            f"{sorted(k for k in _ACTIVATIONS if k)}")
    return _ACTIVATIONS[name]


def _pair(v) -> tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    a, b = v
    return (int(a), int(b))


class Layer:
    """Stateless layer description."""

    def init(self, key, in_shape: Shape) -> tuple[Params, State, Shape]:
        """Returns (params, state, out_shape); shapes exclude the batch dim."""
        raise NotImplementedError

    def apply(self, params: Params, state: State, x, *,
              training: bool = False, rng=None) -> tuple[Any, State]:
        raise NotImplementedError

    @property
    def kind(self) -> str:
        return type(self).__name__.lower()

    def __repr__(self):
        fields = getattr(self, "__dataclass_fields__", {})
        attrs = ", ".join(f"{k}={getattr(self, k)!r}" for k in fields)
        return f"{type(self).__name__}({attrs})"


@dataclasses.dataclass(frozen=True, repr=False)
class Conv2D(Layer):
    """2-D convolution, NHWC. Reference uses Conv2D(32|64, 3, relu)
    (tf_dist_example.py:42, 44)."""

    filters: int
    kernel_size: int | tuple[int, int]
    strides: int | tuple[int, int] = 1
    padding: str = "valid"  # Keras Conv2D default
    activation: Optional[str] = None
    use_bias: bool = True
    kernel_initializer: str = "glorot_uniform"

    def init(self, key, in_shape):
        h, w, cin = in_shape
        kh, kw = _pair(self.kernel_size)
        kernel = initializers.get(self.kernel_initializer)(
            key, (kh, kw, cin, self.filters))
        params = {"kernel": kernel}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,), jnp.float32)
        sh, sw = _pair(self.strides)
        if self.padding.upper() == "SAME":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return params, {}, (oh, ow, self.filters)

    def apply(self, params, state, x, *, training=False, rng=None):
        kernel = params["kernel"].astype(x.dtype)
        if self._use_im2col(x):
            y = _conv_im2col(x, kernel)
        else:
            y = jax.lax.conv_general_dilated(
                x, kernel,
                window_strides=_pair(self.strides),
                padding=self.padding.upper(),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return _activation(self.activation)(y), state

    def _use_im2col(self, x):
        """CPU stem fast path: XLA:CPU's conv GRADIENTS are naive loops
        (r3 audit: the 1-channel 28x28 stem's fwd+bwd took 61 ms at batch
        128 vs 17 ms as slice-concat patches + one matmul, whose backward
        is matmuls + static pads). Only worth it while the patch blowup
        (kh*kw*cin columns) stays small — wide-channel convs lose to the
        native path. TPU always takes lax conv (MXU-native)."""
        if jax.default_backend() != "cpu":
            return False
        kh, kw = _pair(self.kernel_size)
        return (_pair(self.strides) == (1, 1)
                and self.padding.upper() == "VALID"
                and kh * kw * x.shape[-1] <= 64)


def _conv_im2col(x, w):
    """VALID stride-1 conv as slice-concat patches + one matmul — same
    contraction, CPU-friendly gradients (see Conv2D._use_im2col)."""
    kh, kw, cin, cout = w.shape
    b, h, ww_, _ = x.shape
    oh, ow = h - kh + 1, ww_ - kw + 1
    cols = [x[:, i:i + oh, j:j + ow, :]
            for i in range(kh) for j in range(kw)]
    patches = jnp.concatenate(cols, axis=-1)       # [B, oh, ow, kh*kw*cin]
    out = patches.reshape(b * oh * ow, kh * kw * cin) @ w.reshape(
        kh * kw * cin, cout)
    return out.reshape(b, oh, ow, cout)


def _nonoverlap_maxpool(xw):
    """Max over the window axes of a [B, OH, WH, OW, WW, C] view.

    DOCUMENTED gradient divergence on tied window maxima (common
    post-ReLU): plain ``jnp.max``'s VJP SPLITS the cotangent across ties,
    while TPU's reduce_window routes it to one element. Both are valid
    subgradients; r4 implemented the exact one-hot routing three ways
    (argmax-forward, cumsum-mask backward, static slice-loop backward)
    and every custom_vjp formulation cost 30-45 % of the WHOLE CPU train
    step — custom_vjp is a fusion barrier right between the conv stacks,
    and this fast path exists purely for CPU speed (the reference's own
    silicon). The split-tie gradient is kept and pinned in
    tests/test_models.py::test_pool_tie_gradient_splits; expected loss is
    unaffected (both subgradients are members of the subdifferential),
    only per-element credit assignment under exact ties differs."""
    return jnp.max(xw, axis=(2, 4))


def _pool(x, window, strides, padding, init_val, op):
    wh, ww = _pair(window)
    sh, sw = _pair(strides)
    if ((sh, sw) == (wh, ww) and padding.upper() == "VALID"
            and op in (jax.lax.max, jax.lax.add)
            and jax.default_backend() == "cpu"):
        # Non-overlapping windows (the reference's pool_size=2 default):
        # reshape + axis-reduce is exactly reduce_window VALID forward
        # (both crop trailing rows/cols). CPU-only: XLA:CPU lowers
        # select_and_scatter to a ~200 ms/step scatter loop at the
        # reference's batch (pools were 2/3 of the whole step); TPU keeps
        # reduce_window (MXU/VPU-native). Tie-gradient semantics: see
        # _nonoverlap_maxpool (documented split-tie divergence).
        b, h, w, c = x.shape
        oh, ow = h // wh, w // ww
        x = x[:, :oh * wh, :ow * ww, :]
        x = x.reshape(b, oh, wh, ow, ww, c)
        if op is jax.lax.max:
            return _nonoverlap_maxpool(x)
        return jnp.sum(x, axis=(2, 4))
    return jax.lax.reduce_window(
        x, init_val, op,
        window_dimensions=(1, wh, ww, 1),
        window_strides=(1, sh, sw, 1),
        padding=padding.upper(),
    )


@dataclasses.dataclass(frozen=True, repr=False)
class MaxPooling2D(Layer):
    """Max pool — reference default pool_size=2 (tf_dist_example.py:43, 45)."""

    pool_size: int | tuple[int, int] = 2
    strides: Optional[int | tuple[int, int]] = None
    padding: str = "valid"

    def _strides(self):
        return self.strides if self.strides is not None else self.pool_size

    def init(self, key, in_shape):
        h, w, c = in_shape
        ph, pw = _pair(self.pool_size)
        sh, sw = _pair(self._strides())
        if self.padding.upper() == "SAME":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - ph) // sh + 1, (w - pw) // sw + 1
        return {}, {}, (oh, ow, c)

    def apply(self, params, state, x, *, training=False, rng=None):
        return _pool(x, self.pool_size, self._strides(), self.padding,
                     -jnp.inf, jax.lax.max), state


@dataclasses.dataclass(frozen=True, repr=False)
class AveragePooling2D(MaxPooling2D):
    def apply(self, params, state, x, *, training=False, rng=None):
        summed = _pool(x, self.pool_size, self._strides(), self.padding,
                       jnp.array(0, x.dtype), jax.lax.add)
        if self.padding.upper() == "SAME":
            # Keras averages over VALID window elements only — divide by the
            # per-position count, not the full window size.
            counts = _pool(jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None],
                           self.pool_size, self._strides(), self.padding,
                           jnp.array(0, x.dtype), jax.lax.add)
            return summed / counts, state
        ph, pw = _pair(self.pool_size)
        return summed / jnp.array(ph * pw, x.dtype), state


@dataclasses.dataclass(frozen=True, repr=False)
class GlobalAveragePooling2D(Layer):
    def init(self, key, in_shape):
        h, w, c = in_shape
        return {}, {}, (c,)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state


@dataclasses.dataclass(frozen=True, repr=False)
class Flatten(Layer):
    """tf_dist_example.py:46."""

    def init(self, key, in_shape):
        return {}, {}, (math.prod(in_shape),)

    def apply(self, params, state, x, *, training=False, rng=None):
        return x.reshape(x.shape[0], -1), state


@dataclasses.dataclass(frozen=True, repr=False)
class Dense(Layer):
    """Fully connected — reference uses Dense(128, relu) and Dense(10)
    (tf_dist_example.py:47-48)."""

    units: int
    activation: Optional[str] = None
    use_bias: bool = True
    kernel_initializer: str = "glorot_uniform"

    def init(self, key, in_shape):
        # Applies to the LAST axis (Keras Dense semantics): a (L, D) input
        # (transformer token stream) maps to (L, units), a (D,) input to
        # (units,).
        din = in_shape[-1]
        params = {"kernel": initializers.get(self.kernel_initializer)(
            key, (din, self.units))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,), jnp.float32)
        return params, {}, (*in_shape[:-1], self.units)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = x @ params["kernel"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return _activation(self.activation)(y), state


@dataclasses.dataclass(frozen=True, repr=False)
class Activation(Layer):
    activation: str = "relu"

    def init(self, key, in_shape):
        return {}, {}, in_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return _activation(self.activation)(x), state


ReLU = lambda: Activation("relu")


@dataclasses.dataclass(frozen=True, repr=False)
class BatchNormalization(Layer):
    """Batch norm over the channel axis with running statistics.

    Running mean/var live in ``state`` (float32 always); in a distributed step
    the batch statistics are computed over the *global* batch automatically —
    the batch axis is sharded, so XLA all-reduces the moment sums (sync-BN for
    free; contrast TF where SyncBatchNormalization is a separate layer).
    """

    momentum: float = 0.99
    epsilon: float = 1e-3
    center: bool = True
    scale: bool = True

    def init(self, key, in_shape):
        c = in_shape[-1]
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((c,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((c,), jnp.float32)
        state = {"mean": jnp.zeros((c,), jnp.float32),
                 "var": jnp.ones((c,), jnp.float32)}
        return params, state, in_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if training:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            var = jnp.var(xf, axis=reduce_axes)
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = jax.lax.rsqrt(var + self.epsilon)
        y = (x.astype(jnp.float32) - mean) * inv
        if self.scale:
            y = y * params["gamma"]
        if self.center:
            y = y + params["beta"]
        return y.astype(x.dtype), new_state


def unique_layer_names(layers: Sequence[Layer]) -> list[str]:
    """kind, kind_1, kind_2, ... — stable param-tree keys for a layer list."""
    import collections

    counts: collections.Counter = collections.Counter()
    names = []
    for layer in layers:
        k = layer.kind
        names.append(k if counts[k] == 0 else f"{k}_{counts[k]}")
        counts[k] += 1
    return names


def init_chain(layers: Sequence[Layer], names: Sequence[str], key, in_shape):
    """Initialize a layer chain; returns (params, state, out_shape)."""
    params: dict = {}
    state: dict = {}
    shape = tuple(in_shape)
    keys = jax.random.split(key, max(len(layers), 1))
    for layer, name, k in zip(layers, names, keys):
        p, s, shape = layer.init(k, shape)
        if p:
            params[name] = p
        if s:
            state[name] = s
    return params, state, shape


def apply_chain(layers: Sequence[Layer], names: Sequence[str], params, state,
                x, *, training: bool, rng):
    """Apply a layer chain; returns (y, new_state). Dropout layers receive
    per-layer keys folded from ``rng``."""
    new_state = dict(state) if state else {}
    for i, (layer, name) in enumerate(zip(layers, names)):
        p = params.get(name, {}) if params else {}
        s = state.get(name, {}) if state else {}
        # Every layer gets a per-position key (containers thread it down to
        # nested Dropouts); layers that don't use randomness ignore it.
        layer_rng = jax.random.fold_in(rng, i) if rng is not None else None
        x, s_new = layer.apply(p, s, x, training=training, rng=layer_rng)
        if s_new:
            new_state[name] = s_new
    return x, new_state


@dataclasses.dataclass(frozen=True, repr=False)
class Block(Layer):
    """A named sub-stack of layers — composable container for deep models
    (ResNet stages, BASELINE.md configs 4-5). Params/state nest under the
    sublayer names."""

    layers: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "layers", tuple(self.layers))
        object.__setattr__(self, "_names",
                           tuple(unique_layer_names(self.layers)))

    def init(self, key, in_shape):
        return init_chain(self.layers, self._names, key, in_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        return apply_chain(self.layers, self._names, params, state, x,
                           training=training, rng=rng)


@dataclasses.dataclass(frozen=True, repr=False)
class Residual(Layer):
    """``activation(main(x) + shortcut(x))`` — the residual connection.

    ``shortcut=()`` is the identity skip; a projection (1x1 conv + BN) goes
    there when shapes change. The building block of the ResNet benchmark
    models; XLA fuses the add into the preceding conv/BN epilogue on TPU.
    """

    main: tuple = ()
    shortcut: tuple = ()
    activation: Optional[str] = "relu"

    def __post_init__(self):
        object.__setattr__(self, "main", tuple(self.main))
        object.__setattr__(self, "shortcut", tuple(self.shortcut))
        object.__setattr__(self, "_main_names",
                           tuple(unique_layer_names(self.main)))
        object.__setattr__(self, "_short_names",
                           tuple(unique_layer_names(self.shortcut)))

    def init(self, key, in_shape):
        k_main, k_short = jax.random.split(key)
        p_main, s_main, out_main = init_chain(self.main, self._main_names,
                                              k_main, in_shape)
        p_short, s_short, out_short = init_chain(
            self.shortcut, self._short_names, k_short, in_shape)
        if out_main != out_short:
            raise ValueError(
                f"residual branches disagree: main -> {out_main}, "
                f"shortcut -> {out_short}")
        params = {"main": p_main}
        state = {}
        if p_short:
            params["shortcut"] = p_short
        if s_main:
            state["main"] = s_main
        if s_short:
            state["shortcut"] = s_short
        return params, state, out_main

    def apply(self, params, state, x, *, training=False, rng=None):
        # Distinct rng per branch: a Dropout at position i of each branch must
        # not draw the same fold_in(rng, i) key (correlated masks otherwise).
        rng_main = rng_short = None
        if rng is not None:
            rng_main, rng_short = jax.random.split(rng)
        y, s_main = apply_chain(
            self.main, self._main_names, params.get("main", {}),
            state.get("main", {}) if state else {}, x,
            training=training, rng=rng_main)
        sc, s_short = apply_chain(
            self.shortcut, self._short_names, params.get("shortcut", {}),
            state.get("shortcut", {}) if state else {}, x,
            training=training, rng=rng_short)
        new_state = {}
        if s_main:
            new_state["main"] = s_main
        if s_short:
            new_state["shortcut"] = s_short
        return _activation(self.activation)(y + sc), new_state


@dataclasses.dataclass(frozen=True, repr=False)
class Dropout(Layer):
    rate: float = 0.5

    def init(self, key, in_shape):
        if not 0.0 <= self.rate < 1.0:
            raise ValueError(f"dropout rate must be in [0, 1), got {self.rate}")
        return {}, {}, in_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout needs an rng during training; "
                             "fit() threads one automatically")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state
