"""The reference MNIST CNN (SURVEY.md R5), as a tpu_dist model.

Architecture (tf_dist_example.py:39-53; README.md:131-148):
Conv2D(32, 3, relu) -> MaxPool -> Conv2D(64, 3, relu) -> MaxPool -> Flatten ->
Dense(128, relu) -> Dense(10); compiled with
SparseCategoricalCrossentropy(from_logits=True), SGD(lr=0.001),
SparseCategoricalAccuracy.
"""

from __future__ import annotations

from tpu_dist.models.layers import Conv2D, Dense, Flatten, MaxPooling2D
from tpu_dist.models.model import Sequential
from tpu_dist.ops.losses import SparseCategoricalCrossentropy
from tpu_dist.ops.metrics import SparseCategoricalAccuracy
from tpu_dist.ops.optimizers import SGD


def build_cnn_model(num_classes: int = 10,
                    input_shape: tuple = (28, 28, 1)) -> Sequential:
    return Sequential([
        Conv2D(32, 3, activation="relu"),
        MaxPooling2D(),
        Conv2D(64, 3, activation="relu"),
        MaxPooling2D(),
        Flatten(),
        Dense(128, activation="relu"),
        Dense(num_classes),
    ], input_shape=input_shape, name="mnist_cnn")


def build_and_compile_cnn_model(learning_rate: float = 0.001) -> Sequential:
    """Line-for-line analog of tf_dist_example.py:39-53."""
    model = build_cnn_model()
    model.compile(
        loss=SparseCategoricalCrossentropy(from_logits=True),
        optimizer=SGD(learning_rate=learning_rate),
        metrics=[SparseCategoricalAccuracy()],
    )
    return model
