"""Model layer: layer vocabulary, containers, reference model builders."""

from tpu_dist.models.layers import (
    Activation,
    AveragePooling2D,
    BatchNormalization,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    GlobalAveragePooling2D,
    Layer,
    MaxPooling2D,
    ReLU,
)
from tpu_dist.models.model import Model, Sequential
from tpu_dist.models.cnn import build_and_compile_cnn_model, build_cnn_model

__all__ = [
    "Activation",
    "AveragePooling2D",
    "BatchNormalization",
    "Conv2D",
    "Dense",
    "Dropout",
    "Flatten",
    "GlobalAveragePooling2D",
    "Layer",
    "MaxPooling2D",
    "ReLU",
    "Model",
    "Sequential",
    "build_and_compile_cnn_model",
    "build_cnn_model",
]
