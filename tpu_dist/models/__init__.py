"""Model layer: layer vocabulary, containers, reference model builders."""

from tpu_dist.models.layers import (
    Activation,
    AveragePooling2D,
    BatchNormalization,
    Block,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    GlobalAveragePooling2D,
    Layer,
    MaxPooling2D,
    ReLU,
    Residual,
)
from tpu_dist.models.model import Model, Sequential
from tpu_dist.models.serialize import load_model, save_model
from tpu_dist.models.transformer import (
    Embedding,
    LayerNormalization,
    MultiHeadAttention,
    PositionalEmbedding,
    TransformerBlock,
    build_transformer_lm,
)
from tpu_dist.models.cnn import build_and_compile_cnn_model, build_cnn_model
from tpu_dist.models.policy import compute_dtype, policy, set_policy
from tpu_dist.models.resnet import ResNet18, ResNet50

__all__ = [
    "Activation",
    "AveragePooling2D",
    "BatchNormalization",
    "Block",
    "Conv2D",
    "Dense",
    "Dropout",
    "Flatten",
    "GlobalAveragePooling2D",
    "Layer",
    "MaxPooling2D",
    "ReLU",
    "Residual",
    "Model",
    "Sequential",
    "load_model",
    "Embedding",
    "LayerNormalization",
    "MultiHeadAttention",
    "PositionalEmbedding",
    "TransformerBlock",
    "build_transformer_lm",
    "save_model",
    "ResNet18",
    "ResNet50",
    "build_and_compile_cnn_model",
    "build_cnn_model",
    "compute_dtype",
    "policy",
    "set_policy",
]
