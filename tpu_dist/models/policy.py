"""Mixed-precision policy: bfloat16 compute, float32 params and state.

TPU-native analog of Keras' ``mixed_precision.set_global_policy`` — on TPU the
MXU natively multiplies bfloat16 operands, so casting activations to bfloat16
roughly doubles matmul/conv throughput and halves activation HBM traffic while
float32 parameters, BatchNorm statistics, and the loss keep full precision
(the standard TPU recipe; no loss-scaling is needed because bfloat16 keeps
float32's exponent range, unlike float16/CUDA).

    tpu_dist.models.set_policy("mixed_bfloat16")   # or "float32"

The model containers cast inputs to ``compute_dtype()`` on entry and cast
outputs back to float32, and every layer casts its params to the activation
dtype at use (see layers.py), so a policy flip requires no model changes.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp

_POLICIES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "mixed_bfloat16": jnp.bfloat16,
}

_lock = threading.Lock()
_current = "float32"


def set_policy(name: str) -> None:
    global _current
    if name not in _POLICIES:
        raise ValueError(
            f"unknown policy {name!r}; available: {sorted(_POLICIES)}")
    with _lock:
        _current = name


def policy() -> str:
    return _current


def compute_dtype():
    return _POLICIES[_current]
