"""Training callbacks — the Keras-fit hook surface the reference relies on.

The reference's fit loop runs callbacks/progress per step and epoch (SURVEY.md
§3.3 hot path: "callbacks / progress (chief also checkpoints+TensorBoard per
README.md:51)"). Implemented here: the base hook protocol, History (always
installed, the object ``fit`` returns), ModelCheckpoint (chief-only writes per
README.md:51), and EarlyStopping. Scope is intentionally the
reference-exercised surface (SURVEY.md hard-part #2: avoid Keras scope creep).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

logger = logging.getLogger("tpu_dist.callbacks")


class LazyLogs(dict):
    """Epoch logs whose device-resident scalars are fetched on first read.

    The trainer queues the epoch's loss/metric reductions as device ops and
    issues ONE batched non-blocking device→host transfer right at last-step
    dispatch; materialization (a single ``jax.device_get``) happens only when
    a consumer actually reads a value — :class:`History` at ``.history``
    access, the progress bar when verbose, a monitoring callback via
    ``get``/``items``. A ``verbose=0`` fit with no log-reading callbacks
    never blocks on the epoch boundary at all.

    Value reads materialize; key/len/contains queries don't (the key set is
    known up front). Plain-dict writes (``update``/``[]=`` with host floats)
    are unaffected. The stored device scalars are NEVER donated by later
    steps (the trainer re-creates metric states each epoch), so deferred
    reads stay valid for the life of the History object.
    """

    def __init__(self, host_logs: Optional[dict] = None,
                 device_logs: Optional[dict] = None):
        super().__init__(host_logs or {})
        self._device = dict(device_logs or {})
        for v in self._device.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        # Pending values are visible (as device scalars) to dict-bypass
        # readers like dict(logs); float() on them still works, it just
        # blocks — the override surface below is the non-blocking contract.
        super().update(self._device)

    def materialize(self) -> "LazyLogs":
        """Fetch every pending device value in one batched transfer and
        replace it with a plain float; idempotent."""
        if self._device:
            import jax

            fetched = jax.device_get(self._device)
            self._device = {}
            super().update({k: float(v) for k, v in fetched.items()})
        return self

    def absorb(self, other: dict, prefix: str = "") -> None:
        """Merge ``other``'s entries under ``prefix`` WITHOUT forcing a
        fetch: another LazyLogs' pending device values stay pending (this is
        how validation logs fold into the epoch logs lazily)."""
        if isinstance(other, LazyLogs):
            for k, v in other._device.items():
                self._device[prefix + k] = v
        for k, v in dict.items(other):
            dict.__setitem__(self, prefix + k, v)

    def __getitem__(self, key):
        self.materialize()
        return super().__getitem__(key)

    def get(self, key, default=None):
        self.materialize()
        return super().get(key, default)

    def items(self):
        self.materialize()
        return super().items()

    def values(self):
        self.materialize()
        return super().values()

    def copy(self) -> dict:
        self.materialize()
        return dict(self)

    def __repr__(self):
        self.materialize()
        return super().__repr__()


class Callback:
    model = None  # wired by CallbackList

    def on_train_begin(self) -> None: ...
    def on_train_end(self) -> None: ...
    def on_epoch_begin(self, epoch: int) -> None: ...
    def on_epoch_end(self, epoch: int, logs: dict) -> None: ...
    def on_batch_end(self, step: int, logs: dict) -> None: ...

    #: Set True on subclasses that implement on_batch_end, so the trainer only
    #: pays the per-step device->host sync when someone is listening.
    wants_batches = False


class CallbackList:
    def __init__(self, callbacks: Sequence[Callback], model=None):
        self.callbacks = list(callbacks)
        for cb in self.callbacks:
            cb.model = model

    @property
    def has_batch_hooks(self) -> bool:
        return any(cb.wants_batches for cb in self.callbacks)

    def on_train_begin(self):
        for cb in self.callbacks:
            cb.on_train_begin()

    def on_train_end(self):
        # Teardown runs in REVERSE registration order (proper nesting):
        # later-registered callbacks may own in-flight work whose completion
        # earlier ones' teardown must still observe — e.g. ModelCheckpoint
        # (appended last by fit) drains its async checkpoint writer while the
        # FaultInjector's write-fault hook and the Telemetry registry are
        # still installed.
        for cb in reversed(self.callbacks):
            cb.on_train_end()

    def on_epoch_begin(self, epoch):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def on_batch_end(self, step, logs):
        for cb in self.callbacks:
            if cb.wants_batches:
                cb.on_batch_end(step, logs)


class History(Callback):
    """Per-epoch log record; ``fit`` returns this (Keras History analog).

    Epoch logs may be :class:`LazyLogs` still holding device scalars;
    History stores them unread and folds them into the dict only when
    ``.history`` is accessed — so a fit whose History is never inspected
    never forces the epoch-boundary device→host fetch."""

    def __init__(self):
        self.epoch: list[int] = []
        self._pending: list[dict] = []
        self._history: dict[str, list] = {}

    def on_epoch_end(self, epoch, logs):
        self.epoch.append(epoch)
        self._pending.append(logs)

    @property
    def history(self) -> dict[str, list]:
        while self._pending:
            logs = self._pending.pop(0)
            if isinstance(logs, LazyLogs):
                logs.materialize()
            for k, v in logs.items():
                self._history.setdefault(k, []).append(v)
        return self._history


class ModelCheckpoint(Callback):
    """Chief-only checkpoint writes each epoch (README.md:51 semantics:
    'the chief saves checkpoint models').

    ``async_save=True`` (the default) routes saves through the zero-stall
    :class:`~tpu_dist.training.checkpoint.AsyncCheckpointer`: the epoch
    boundary only pays the on-device snapshot; serialization/fsync/publish
    overlap the next epoch's steps, and any write error surfaces at the next
    epoch's save (or at train end), where it is absorbed exactly like a sync
    failure — one lost checkpoint interval, logged as
    ``checkpoint_write_failed``, never a dead run. ``on_train_end`` drains
    the writer, so fit never returns with a save still in flight."""

    def __init__(self, directory: str, *, save_best_only: bool = False,
                 monitor: str = "loss", mode: str = "min",
                 max_to_keep: Optional[int] = None, async_save: bool = True,
                 sharded: bool = False):
        self.directory = directory
        self.save_best_only = save_best_only
        self.monitor = monitor
        self.mode = mode
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.sharded = sharded
        self._best: Optional[float] = None
        self._ckpt = None

    def on_train_begin(self):
        if self.async_save and self._ckpt is None:
            from tpu_dist.training import checkpoint

            self._ckpt = checkpoint.AsyncCheckpointer(
                self.directory, max_to_keep=self.max_to_keep,
                sharded=self.sharded)

    def on_epoch_end(self, epoch, logs):
        from tpu_dist.training import checkpoint

        if self.save_best_only:
            current = logs.get(self.monitor)
            if current is None:
                logger.warning("ModelCheckpoint: monitor %r not in logs %s",
                               self.monitor, sorted(logs))
                return
            better = (self._best is None
                      or (current < self._best if self.mode == "min"
                          else current > self._best))
            if not better:
                return
            self._best = current
        try:
            if self._ckpt is not None:
                self._ckpt.save_async(self.model, step=epoch)
            else:
                checkpoint.save(self.directory, self.model, step=epoch,
                                max_to_keep=self.max_to_keep,
                                sharded=self.sharded)
        except OSError as exc:
            self._write_failed(getattr(exc, "checkpoint_step", epoch), exc)

    def publish_in_flight(self) -> None:
        """Drain the async writer NOW without closing it.

        The gang-reform drain point: before a survivor acks a reform it must
        make its latest epoch checkpoint durable, or the relaunched rank
        could restore one epoch behind the survivors and the rendezvous
        coordinates would disagree. A write failure is absorbed like any
        other (one lost interval), and the reform falls back to the previous
        complete checkpoint on every rank alike.
        """
        if self._ckpt is None:
            return
        try:
            self._ckpt.wait()
        except OSError as exc:
            self._write_failed(getattr(exc, "checkpoint_step", None), exc)

    def on_train_end(self):
        if self._ckpt is None:
            return
        ckpt, self._ckpt = self._ckpt, None
        try:
            ckpt.close()
        except OSError as exc:
            self._write_failed(getattr(exc, "checkpoint_step", None), exc)

    def _write_failed(self, step, exc) -> None:
        # A failed write costs one checkpoint interval, never the run:
        # training state is still live, and the next epoch retries.
        logger.warning("ModelCheckpoint: step %s write failed (%s); "
                       "continuing without it", step, exc)
        from tpu_dist.resilience import events

        events.maybe_log("checkpoint_write_failed", step=step,
                         error=str(exc))


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", patience: int = 3,
                 mode: str = "min", min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self._best: Optional[float] = None
        self._wait = 0
        self.stopped_epoch: Optional[int] = None

    def on_epoch_end(self, epoch, logs):
        current = logs.get(self.monitor)
        if current is None:
            return
        improved = (self._best is None or
                    (self._best - current > self.min_delta if self.mode == "min"
                     else current - self._best > self.min_delta))
        if improved:
            self._best = current
            self._wait = 0
        else:
            self._wait += 1
            if self._wait > self.patience:
                self.stopped_epoch = epoch
                raise StopTraining(f"EarlyStopping at epoch {epoch}")


class JSONLogger(Callback):
    """Structured per-epoch training log: one JSON line per epoch, chief-only.

    The §5.5 observability surface (SURVEY.md): loss, metrics, epoch time and
    steps/sec in a machine-readable stream — the analog of the reference era's
    CSVLogger + the INFO logging this framework's collectives module provides
    for all-reduce shapes. Append mode supports resumed runs.
    """

    def __init__(self, path: str, *, log_batches: bool = False):
        self.path = path
        self._log_batches = log_batches
        self.wants_batches = False  # resolved per-process at train begin
        self._file = None

    def _chief(self) -> bool:
        from tpu_dist.cluster import bootstrap

        return bootstrap.is_chief()

    def on_train_begin(self):
        chief = self._chief()
        # Only the chief writes, so only the chief should make the trainer pay
        # the per-step device->host loss sync batch logging requires.
        self.wants_batches = self._log_batches and chief
        if chief:
            import os

            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            self._file = open(self.path, "a", buffering=1)

    def on_train_end(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _write(self, record: dict):
        if self._file is not None:
            import json

            self._file.write(json.dumps(record) + "\n")

    def on_epoch_end(self, epoch, logs):
        self._write({"event": "epoch", "epoch": epoch,
                     **{k: round(float(v), 6) for k, v in logs.items()}})

    def on_batch_end(self, step, logs):
        self._write({"event": "batch", "step": step,
                     **{k: round(float(v), 6) for k, v in logs.items()}})


class TensorBoard(Callback):
    """Chief-only TensorBoard scalar logging — the README.md:51 chief duty
    ('generates TensorBoard'). Writes per-epoch scalars (loss, metrics,
    val_*) as TF event files via ``tf.summary`` when TensorFlow is importable;
    otherwise logs a warning once and no-ops (TF is an optional dependency of
    this framework, used only here)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._writer = None

    def on_train_begin(self):
        from tpu_dist.cluster import bootstrap

        if not bootstrap.is_chief():
            return
        try:
            import tensorflow as tf  # optional, event-file writer only

            self._writer = tf.summary.create_file_writer(self.log_dir)
        except ImportError:
            logger.warning(
                "TensorBoard callback: tensorflow is not importable; scalar "
                "event files will not be written (use JSONLogger instead)")

    def on_epoch_end(self, epoch, logs):
        if self._writer is None:
            return
        import tensorflow as tf

        with self._writer.as_default(step=epoch):
            for k, v in logs.items():
                tf.summary.scalar(f"epoch_{k}", float(v))
        self._writer.flush()

    def on_train_end(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class StopTraining(Exception):
    """Raised by callbacks to end fit cleanly."""


class LambdaCallback(Callback):
    def __init__(self, *, on_epoch_end=None, on_batch_end=None):
        self._epoch_end = on_epoch_end
        self._batch_end = on_batch_end
        self.wants_batches = on_batch_end is not None

    def on_epoch_end(self, epoch, logs):
        if self._epoch_end:
            self._epoch_end(epoch, logs)

    def on_batch_end(self, step, logs):
        if self._batch_end:
            self._batch_end(step, logs)


def __getattr__(name):
    # Telemetry lives in tpu_dist.observe (which imports Callback from this
    # module) but belongs on the callback surface alongside JSONLogger and
    # TensorBoard; a PEP 562 lazy re-export gives it the natural spelling
    # without the import cycle.
    if name == "Telemetry":
        from tpu_dist.observe.telemetry import Telemetry

        return Telemetry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
