"""Training callbacks — the Keras-fit hook surface the reference relies on.

The reference's fit loop runs callbacks/progress per step and epoch (SURVEY.md
§3.3 hot path: "callbacks / progress (chief also checkpoints+TensorBoard per
README.md:51)"). Implemented here: the base hook protocol, History (always
installed, the object ``fit`` returns), ModelCheckpoint (chief-only writes per
README.md:51), and EarlyStopping. Scope is intentionally the
reference-exercised surface (SURVEY.md hard-part #2: avoid Keras scope creep).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

logger = logging.getLogger("tpu_dist.callbacks")


class Callback:
    model = None  # wired by CallbackList

    def on_train_begin(self) -> None: ...
    def on_train_end(self) -> None: ...
    def on_epoch_begin(self, epoch: int) -> None: ...
    def on_epoch_end(self, epoch: int, logs: dict) -> None: ...
    def on_batch_end(self, step: int, logs: dict) -> None: ...

    #: Set True on subclasses that implement on_batch_end, so the trainer only
    #: pays the per-step device->host sync when someone is listening.
    wants_batches = False


class CallbackList:
    def __init__(self, callbacks: Sequence[Callback], model=None):
        self.callbacks = list(callbacks)
        for cb in self.callbacks:
            cb.model = model

    @property
    def has_batch_hooks(self) -> bool:
        return any(cb.wants_batches for cb in self.callbacks)

    def on_train_begin(self):
        for cb in self.callbacks:
            cb.on_train_begin()

    def on_train_end(self):
        for cb in self.callbacks:
            cb.on_train_end()

    def on_epoch_begin(self, epoch):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def on_batch_end(self, step, logs):
        for cb in self.callbacks:
            if cb.wants_batches:
                cb.on_batch_end(step, logs)


class History(Callback):
    """Per-epoch log record; ``fit`` returns this (Keras History analog)."""

    def __init__(self):
        self.history: dict[str, list] = {}
        self.epoch: list[int] = []

    def on_epoch_end(self, epoch, logs):
        self.epoch.append(epoch)
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


class ModelCheckpoint(Callback):
    """Chief-only checkpoint writes each epoch (README.md:51 semantics:
    'the chief saves checkpoint models')."""

    def __init__(self, directory: str, *, save_best_only: bool = False,
                 monitor: str = "loss", mode: str = "min",
                 max_to_keep: Optional[int] = None):
        self.directory = directory
        self.save_best_only = save_best_only
        self.monitor = monitor
        self.mode = mode
        self.max_to_keep = max_to_keep
        self._best: Optional[float] = None

    def on_epoch_end(self, epoch, logs):
        from tpu_dist.training import checkpoint

        if self.save_best_only:
            current = logs.get(self.monitor)
            if current is None:
                logger.warning("ModelCheckpoint: monitor %r not in logs %s",
                               self.monitor, sorted(logs))
                return
            better = (self._best is None
                      or (current < self._best if self.mode == "min"
                          else current > self._best))
            if not better:
                return
            self._best = current
        try:
            checkpoint.save(self.directory, self.model, step=epoch,
                            max_to_keep=self.max_to_keep)
        except OSError as exc:
            # A failed write costs one checkpoint interval, never the run:
            # training state is still live, and the next epoch retries.
            logger.warning("ModelCheckpoint: step %d write failed (%s); "
                           "continuing without it", epoch, exc)
            from tpu_dist.resilience import events

            events.maybe_log("checkpoint_write_failed", step=epoch,
                             error=str(exc))


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", patience: int = 3,
                 mode: str = "min", min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self._best: Optional[float] = None
        self._wait = 0
        self.stopped_epoch: Optional[int] = None

    def on_epoch_end(self, epoch, logs):
        current = logs.get(self.monitor)
        if current is None:
            return
        improved = (self._best is None or
                    (self._best - current > self.min_delta if self.mode == "min"
                     else current - self._best > self.min_delta))
        if improved:
            self._best = current
            self._wait = 0
        else:
            self._wait += 1
            if self._wait > self.patience:
                self.stopped_epoch = epoch
                raise StopTraining(f"EarlyStopping at epoch {epoch}")


class JSONLogger(Callback):
    """Structured per-epoch training log: one JSON line per epoch, chief-only.

    The §5.5 observability surface (SURVEY.md): loss, metrics, epoch time and
    steps/sec in a machine-readable stream — the analog of the reference era's
    CSVLogger + the INFO logging this framework's collectives module provides
    for all-reduce shapes. Append mode supports resumed runs.
    """

    def __init__(self, path: str, *, log_batches: bool = False):
        self.path = path
        self._log_batches = log_batches
        self.wants_batches = False  # resolved per-process at train begin
        self._file = None

    def _chief(self) -> bool:
        from tpu_dist.cluster import bootstrap

        return bootstrap.is_chief()

    def on_train_begin(self):
        chief = self._chief()
        # Only the chief writes, so only the chief should make the trainer pay
        # the per-step device->host loss sync batch logging requires.
        self.wants_batches = self._log_batches and chief
        if chief:
            import os

            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            self._file = open(self.path, "a", buffering=1)

    def on_train_end(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def _write(self, record: dict):
        if self._file is not None:
            import json

            self._file.write(json.dumps(record) + "\n")

    def on_epoch_end(self, epoch, logs):
        self._write({"event": "epoch", "epoch": epoch,
                     **{k: round(float(v), 6) for k, v in logs.items()}})

    def on_batch_end(self, step, logs):
        self._write({"event": "batch", "step": step,
                     **{k: round(float(v), 6) for k, v in logs.items()}})


class TensorBoard(Callback):
    """Chief-only TensorBoard scalar logging — the README.md:51 chief duty
    ('generates TensorBoard'). Writes per-epoch scalars (loss, metrics,
    val_*) as TF event files via ``tf.summary`` when TensorFlow is importable;
    otherwise logs a warning once and no-ops (TF is an optional dependency of
    this framework, used only here)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._writer = None

    def on_train_begin(self):
        from tpu_dist.cluster import bootstrap

        if not bootstrap.is_chief():
            return
        try:
            import tensorflow as tf  # optional, event-file writer only

            self._writer = tf.summary.create_file_writer(self.log_dir)
        except ImportError:
            logger.warning(
                "TensorBoard callback: tensorflow is not importable; scalar "
                "event files will not be written (use JSONLogger instead)")

    def on_epoch_end(self, epoch, logs):
        if self._writer is None:
            return
        import tensorflow as tf

        with self._writer.as_default(step=epoch):
            for k, v in logs.items():
                tf.summary.scalar(f"epoch_{k}", float(v))
        self._writer.flush()

    def on_train_end(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class StopTraining(Exception):
    """Raised by callbacks to end fit cleanly."""


class LambdaCallback(Callback):
    def __init__(self, *, on_epoch_end=None, on_batch_end=None):
        self._epoch_end = on_epoch_end
        self._batch_end = on_batch_end
        self.wants_batches = on_batch_end is not None

    def on_epoch_end(self, epoch, logs):
        if self._epoch_end:
            self._epoch_end(epoch, logs)

    def on_batch_end(self, step, logs):
        if self._batch_end:
            self._batch_end(step, logs)


def __getattr__(name):
    # Telemetry lives in tpu_dist.observe (which imports Callback from this
    # module) but belongs on the callback surface alongside JSONLogger and
    # TensorBoard; a PEP 562 lazy re-export gives it the natural spelling
    # without the import cycle.
    if name == "Telemetry":
        from tpu_dist.observe.telemetry import Telemetry

        return Telemetry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
