"""Training layer: fit engine, callbacks, checkpointing."""

from tpu_dist.training import checkpoint
from tpu_dist.training.callbacks import (
    Callback,
    EarlyStopping,
    History,
    JSONLogger,
    LambdaCallback,
    ModelCheckpoint,
    StopTraining,
    TensorBoard,
)
from tpu_dist.training.trainer import Trainer

__all__ = [
    "checkpoint",
    "Callback",
    "EarlyStopping",
    "History",
    "JSONLogger",
    "LambdaCallback",
    "ModelCheckpoint",
    "StopTraining",
    "TensorBoard",
    "Trainer",
]
