"""Training layer: fit engine, callbacks, checkpointing."""

from tpu_dist.training import checkpoint
from tpu_dist.training.callbacks import (
    Callback,
    EarlyStopping,
    History,
    JSONLogger,
    LambdaCallback,
    LazyLogs,
    ModelCheckpoint,
    StopTraining,
    TensorBoard,
)
from tpu_dist.training.checkpoint import AsyncCheckpointer
from tpu_dist.training.trainer import Trainer

__all__ = [
    "checkpoint",
    "AsyncCheckpointer",
    "Callback",
    "EarlyStopping",
    "History",
    "JSONLogger",
    "LambdaCallback",
    "LazyLogs",
    "ModelCheckpoint",
    "StopTraining",
    "Telemetry",
    "TensorBoard",
    "Trainer",
]


def __getattr__(name):
    # Telemetry lives in tpu_dist.observe (which imports Callback from
    # this package's callbacks module) — lazy re-export avoids the cycle
    # while keeping it discoverable next to the other fit callbacks.
    if name == "Telemetry":
        from tpu_dist.observe.telemetry import Telemetry

        return Telemetry
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
