"""Checkpoint / resume: chief-writes, everyone-restores (SURVEY.md §5.4).

The reference specifies the capability in prose only — the chief's duties
include "saving checkpoint models" (README.md:51); the example itself never
saves. Parity target: chief-only checkpoint + resume-from-latest, not a format
zoo. Format: one ``.npz`` of flattened arrays + a JSON manifest per step,
written atomically (temp + rename) and durably (fsync before the rename, the
parent directory after), with a ``checkpoint`` pointer file naming the latest
step — restore on every process, then a broadcast from process 0 guarantees
bit-identical restored state cluster-wide (the D4 init-broadcast rule applied
to resume; divergence-free restore is SURVEY.md hard-part #3).

Two write pipelines share the formats:

* :func:`save` — synchronous; the whole gather/serialize/fsync/publish
  sequence runs on the caller's critical path (``Model.save_weights``).
* :class:`AsyncCheckpointer` — the zero-stall pipeline (CheckFreq, Mohan et
  al. FAST '21; Orbax's async checkpointer): a *snapshot* phase copies the
  variable tree on-device (one async jit dispatch) and starts non-blocking
  device→host transfers, then a background thread serializes, fsyncs and
  atomically publishes while training continues. Barriers and error delivery
  move to a bounded *commit point* — the next ``save_async``, ``wait()`` or
  ``close()`` — so at most one snapshot is in flight and a failed write
  still fails the run, one checkpoint interval late at most.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import shutil
import tempfile
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from tpu_dist.cluster import bootstrap
from tpu_dist.observe import metrics as metrics_lib

logger = logging.getLogger("tpu_dist.checkpoint")

_POINTER = "checkpoint"
_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_FORMAT_V1 = "tpu_dist.checkpoint.v1"
_FORMAT_V2 = "tpu_dist.checkpoint.v2-sharded"


def _shard_arrays(process: int) -> str:
    return f"arrays-shard-{process}.npz"


def _shard_index(process: int) -> str:
    return f"shards-{process}.json"


def _to_host(leaf) -> np.ndarray:
    """Fetch a leaf's GLOBAL value to host memory.

    Replicated or single-process leaves read locally; a model-sharded leaf in
    a multi-process job spans non-addressable devices, so ``np.asarray`` would
    raise — those are allgathered across processes first. The gather is a
    COLLECTIVE: every process must reach it (callers hoist flattening out of
    chief-only branches; the addressability predicate is uniform across
    processes because it is a property of the one global array)."""
    if _needs_allgather(leaf):
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(leaf)


def _needs_allgather(leaf) -> bool:
    """The ONE definition of "this leaf's host copy requires a collective".

    Chief and peers count collectives off this predicate; two drifting
    copies would mean mismatched process_allgather calls — a cluster-wide
    hang, not a wrong answer. Keep every caller on this helper."""
    return isinstance(leaf, jax.Array) and not (
        leaf.is_fully_addressable or leaf.is_fully_replicated)


def _placeholder(leaf) -> np.ndarray:
    """Host array with a leaf's global shape/dtype and arbitrary contents —
    for templates whose values are about to be overwritten. ``jax.Array.shape``
    is the global shape, so no collective and no device transfer happens."""
    if isinstance(leaf, jax.Array):
        return np.zeros(leaf.shape, leaf.dtype)
    return np.asarray(leaf)


def _needs_gather(tree) -> bool:
    return any(_needs_allgather(l) for l in jax.tree_util.tree_leaves(tree))


def _join_gathers(tree) -> None:
    """Non-chief side of a v1 save: join each cross-process allgather the
    chief's flatten will issue, discarding the results."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if _needs_allgather(leaf):
            _to_host(leaf)


def _flatten(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _to_host(leaf)
    return flat


def _flatten_local(tree) -> dict[str, np.ndarray]:
    """:func:`_flatten` for snapshot trees: every leaf is a host array or a
    fully readable device copy, so no collective can fire — the invariant
    that lets the background writer call this off the main thread (the main
    thread owns all collectives; a gather here would interleave with the
    step stream's and deadlock the cluster)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if _needs_allgather(leaf):
            raise ValueError(
                f"snapshot leaf {jax.tree_util.keystr(path)!r} still spans "
                "non-addressable devices; snapshot phase must gather it")
        flat[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return flat


def _unflatten_into(template, arrays: dict[str, np.ndarray]):
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(
                f"checkpoint missing array {key!r}; checkpoint/model mismatch")
        arr = arrays[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint array {key!r} has shape {arr.shape}, model "
                f"expects {np.shape(leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _step_dir(directory: pathlib.Path, step: int) -> pathlib.Path:
    return directory / f"ckpt-{step}"


def _saveable(model_or_variables) -> dict:
    variables = getattr(model_or_variables, "variables", model_or_variables)
    if variables is None:
        raise ValueError("model has no materialized variables to save; "
                         "run fit() or ensure_variables() first")
    return {k: variables[k] for k in ("params", "state", "opt")
            if k in variables}


# -- durability helpers -------------------------------------------------------
# os.replace makes the publish ATOMIC, but atomicity is not DURABILITY: after
# a crash right behind the rename, the npz/manifest data pages — or the rename
# record itself — may still sit in the page cache, leaving the pointer naming
# a torn step on a journal replay. The classic create→fsync(files)→rename→
# fsync(parent dir) sequence closes that window on both layouts.

def _fsync(path: pathlib.Path) -> None:
    """fsync a file or directory by path (directories need an fd too)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _publish_stage(stage: pathlib.Path, target: pathlib.Path,
                   directory: pathlib.Path, step: int) -> None:
    """Durably publish a fully staged checkpoint directory (chief only):
    fsync every staged file + the stage dir, rename into place, fsync the
    parent, then atomically update the ``checkpoint`` pointer."""
    for child in sorted(stage.iterdir()):
        if child.is_file():
            _fsync(child)
    _fsync(stage)
    if target.exists():
        shutil.rmtree(target)
    os.replace(stage, target)
    _fsync(directory)
    pointer_tmp = directory / (_POINTER + ".tmp")
    pointer_tmp.write_text(str(step))
    _fsync(pointer_tmp)
    os.replace(pointer_tmp, directory / _POINTER)
    _fsync(directory)


#: Fault-injection seam (tpu_dist.resilience): called on the chief with the
#: fully staged checkpoint directory right before the atomic publish (for
#: async saves this happens on the background writer thread — which is what
#: lets a ``kill_during_save`` fault land deterministically mid-flight). A
#: hook may raise OSError (a transient write failure — the stage is discarded
#: and nothing is published) or corrupt the staged files in place (simulating
#: a mid-write crash on a filesystem whose rename is not atomic);
#: restore-side manifest validation must then reject the published step.
#: None in production — one pointer check per save.
_WRITE_FAULT_HOOK = None


def install_write_fault_hook(hook):
    """Install (or, with None, remove) the checkpoint write fault hook;
    returns the previously installed hook. ``hook(stage_dir, step)``."""
    global _WRITE_FAULT_HOOK
    prev = _WRITE_FAULT_HOOK
    _WRITE_FAULT_HOOK = hook
    return prev


def _fire_write_fault(stage: pathlib.Path, step: int) -> None:
    if _WRITE_FAULT_HOOK is not None:
        _WRITE_FAULT_HOOK(stage, step)


def _write_v1_checkpoint(directory: pathlib.Path, flat: dict,
                         *, step: int, max_to_keep: Optional[int]) -> str:
    """Serialize + durably publish one v1 checkpoint from host arrays (chief
    only). Shared by the sync path and the async writer thread; contains no
    collectives and no barriers."""
    directory.mkdir(parents=True, exist_ok=True)
    target = _step_dir(directory, step)
    with tempfile.TemporaryDirectory(dir=directory) as tmp:
        tmp_path = pathlib.Path(tmp) / "stage"
        tmp_path.mkdir()
        np.savez(tmp_path / _ARRAYS, **flat)
        (tmp_path / _MANIFEST).write_text(json.dumps({
            "step": step,
            "keys": sorted(flat),
            "format": _FORMAT_V1,
            # Topology stamp: lets restore_model detect (and count) a
            # reshape — resuming on a different gang/device shape.
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }))
        _fire_write_fault(tmp_path, step)
        _publish_stage(tmp_path, target, directory, step)
    logger.info("checkpoint step %d written to %s", step, target)
    if max_to_keep is not None:
        _gc(directory, max_to_keep)
    return str(target)


def save(directory: str | os.PathLike, model_or_variables, *, step: int,
         max_to_keep: Optional[int] = None,
         sharded: bool = False) -> Optional[str]:
    """Write checkpoint ``step`` synchronously; returns its path (None on
    non-chief unless ``sharded``).

    Accepts a compiled Model (saves its live training variables) or a raw
    variables pytree. Only the chief writes (README.md:51); all processes
    rendezvous afterwards so no peer races ahead of a half-written checkpoint.

    ``sharded=True`` writes the v2 layout instead: EVERY process writes its
    own ``arrays-shard-p.npz`` holding only its addressable shards of
    non-replicated leaves (O(model/P) host memory and P-way parallel write
    bandwidth — the matching story for TP/PP/EP-sharded models, where the
    chief-writes path would allgather O(model) through one host), the chief
    writes replicated leaves + the manifest, and two barriers bracket a
    chief-created staging directory so the rename publish stays atomic.
    Requires a FILESYSTEM SHARED by all processes (the standard sharded-
    checkpoint contract); restore re-places onto whatever mesh is current,
    so cross-topology moves work exactly like v1.
    """
    t0 = time.perf_counter()
    saveable = _saveable(model_or_variables)
    directory = pathlib.Path(directory)
    try:
        if sharded:
            return _save_sharded(directory, saveable, step=step,
                                 max_to_keep=max_to_keep)
        path = None
        # Tensor-parallel leaves require a cross-process allgather (a
        # collective), so non-chief processes must JOIN each gather — but only
        # the gathers: they walk the same leaf order the chief's _flatten does
        # and discard the results, paying nothing for replicated leaves.
        # Pure-DP saves keep their old shape (chief-only host copy, peers
        # untouched).
        if not bootstrap.is_chief():
            _join_gathers(saveable)
        write_error: Optional[OSError] = None
        if bootstrap.is_chief():
            # A write failure (real, or injected through the fault seam) must
            # not skip the closing barrier — peers are already waiting there,
            # so raising early would trade a lost checkpoint for a
            # cluster-wide hang. Record, rendezvous, then propagate.
            try:
                path = _write_v1_checkpoint(directory, _flatten(saveable),
                                            step=step, max_to_keep=max_to_keep)
            except OSError as exc:
                write_error = exc
        bootstrap.barrier(f"checkpoint_save_{step}")
        if write_error is not None:
            raise write_error
        return path
    finally:
        # Sync saves stall the step stream for their full duration — record
        # it on the same series the async pipeline uses, so one bench/gate
        # compares both (free when the observe registry is disabled).
        metrics_lib.inc("checkpoint.sync_saves")
        metrics_lib.observe_value("checkpoint.stall_s",
                                  time.perf_counter() - t0)


def _is_replicated(leaf) -> bool:
    """Leaves the chief owns in the v2 layout: everything that is not a
    multi-device-sharded jax.Array (host numpy, scalars, replicated)."""
    if not isinstance(leaf, jax.Array):
        return True
    return leaf.is_fully_replicated


def _write_sharded_stage(stage: pathlib.Path, saveable, *, step: int) -> None:
    """This process's v2 stage writes: its replica-0 shards + index, plus —
    on the chief — the replicated-leaf npz and the manifest. No collectives,
    no barriers, no fsync (the publish fsyncs the whole stage): callable
    from the sync path between its barriers or from an async writer thread
    on a snapshot tree."""
    proc = bootstrap.process_index()
    # Every process: its addressable replica-0 shards of sharded leaves.
    # replica_id==0 picks exactly one owner per distinct shard index, so
    # leaves replicated over SOME axes (e.g. P('pipe') on a data x pipe
    # mesh) are written once, and the union over processes tiles the
    # global array exactly (asserted at assembly).
    local_arrays: dict[str, np.ndarray] = {}
    index: dict[str, list] = {}
    chief_arrays: dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(saveable)[0]:
        key = jax.tree_util.keystr(path)
        if _is_replicated(leaf):
            if bootstrap.is_chief():
                chief_arrays[key] = np.asarray(leaf)
            continue
        entries = []
        for j, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue
            name = f"{key}//{j}"
            local_arrays[name] = np.asarray(shard.data)
            entries.append({
                "name": name,
                "slices": [[s.start or 0,
                            s.stop if s.stop is not None else dim]
                           for s, dim in zip(shard.index, leaf.shape)],
            })
        if entries:
            index[key] = entries
    np.savez(stage / _shard_arrays(proc), **local_arrays)
    (stage / _shard_index(proc)).write_text(json.dumps(index))
    if bootstrap.is_chief():
        np.savez(stage / _ARRAYS, **chief_arrays)
        meta = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(saveable)[0]:
            key = jax.tree_util.keystr(path)
            dtype = (leaf.dtype if hasattr(leaf, "dtype")
                     else np.asarray(leaf).dtype)
            meta[key] = {
                "shape": list(np.shape(leaf)),
                "dtype": str(dtype),
                "sharded": not _is_replicated(leaf),
            }
        (stage / _MANIFEST).write_text(json.dumps({
            "step": step,
            "format": _FORMAT_V2,
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "leaves": meta,
        }))


def _save_sharded(directory: pathlib.Path, saveable, *, step: int,
                  max_to_keep: Optional[int]) -> str:
    stage = directory / f".stage-{step}"
    target = _step_dir(directory, step)
    if bootstrap.is_chief():
        directory.mkdir(parents=True, exist_ok=True)
        if stage.exists():
            shutil.rmtree(stage)
        stage.mkdir()
    bootstrap.barrier(f"checkpoint_stage_{step}")
    _write_sharded_stage(stage, saveable, step=step)
    bootstrap.barrier(f"checkpoint_written_{step}")
    write_error: Optional[OSError] = None
    if bootstrap.is_chief():
        # Same barrier-before-raise contract as the v1 path: a publish
        # failure must not strand peers at the closing rendezvous.
        try:
            _fire_write_fault(stage, step)
            _publish_stage(stage, target, directory, step)
            logger.info(
                "sharded checkpoint step %d written to %s (%d writers)",
                step, target, jax.process_count())
            if max_to_keep is not None:
                _gc(directory, max_to_keep)
        except OSError as exc:
            write_error = exc
            shutil.rmtree(stage, ignore_errors=True)
    bootstrap.barrier(f"checkpoint_save_{step}")
    if write_error is not None:
        raise write_error
    return str(target)


# -- async snapshot/write pipeline (zero-stall checkpointing) -----------------

def snapshot_copy_program(tree):
    """The snapshot phase's device program: a pure tree copy, NO collectives.

    Traced by shardcheck as the ``training.checkpoint.snapshot_copy`` entry
    point to pin that invariant — a collective smuggled into the snapshot
    would re-serialize the step stream this pipeline exists to overlap, and
    (worse) would eventually run concurrently with the main thread's own
    collectives."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.copy, tree)


_SNAPSHOT_COPY = None


def _snapshot_copy(tree):
    global _SNAPSHOT_COPY
    if _SNAPSHOT_COPY is None:
        _SNAPSHOT_COPY = jax.jit(snapshot_copy_program)
    return _SNAPSHOT_COPY(tree)


def _snapshot(saveable, *, gather: bool):
    """Capture ``saveable``'s values NOW without blocking the step stream.

    Returns a same-structure pytree whose leaves are host numpy arrays or
    freshly copied device arrays with a non-blocking device→host transfer
    already in flight. The device copy is required for CORRECTNESS, not just
    speed: the trainer's compiled steps donate their variable arguments, so
    a snapshot holding references to the live arrays would be invalidated by
    the very next step's dispatch (donation deletes the buffers even with a
    D2H copy pending). The jit dispatch itself is async — it queues behind
    the in-flight step and returns immediately.

    ``gather=True`` (v1 layout) fetches collective-needing leaves
    synchronously here, on the calling thread — the same allgather the sync
    path pays, and the ONLY blocking part of a snapshot. ``gather=False``
    (v2 layout) copies every jax leaf on-device instead; shards are read
    locally by the writer. Either way the returned tree satisfies
    :func:`_flatten_local`'s no-collective invariant."""
    leaves, treedef = jax.tree_util.tree_flatten(saveable)
    host: dict[int, np.ndarray] = {}
    for i, leaf in enumerate(leaves):
        if gather and _needs_allgather(leaf):
            host[i] = _to_host(leaf)
        elif not isinstance(leaf, jax.Array):
            host[i] = np.asarray(leaf)
    to_copy = [None if i in host else leaf for i, leaf in enumerate(leaves)]
    copied = _snapshot_copy(to_copy)
    out = []
    for i, leaf in enumerate(leaves):
        if i in host:
            out.append(host[i])
            continue
        c = copied[i]
        if c.is_fully_addressable or c.is_fully_replicated:
            c.copy_to_host_async()
        else:
            for shard in c.addressable_shards:
                shard.data.copy_to_host_async()
        out.append(c)
    return jax.tree_util.tree_unflatten(treedef, out)


class AsyncCheckpointer:
    """Zero-stall checkpoint pipeline: snapshot on-device now, write later.

    ``save_async(model, step=N)`` returns after (1) *committing* the previous
    in-flight save — the bounded commit point where the cross-process
    barriers fire and any stored write error is raised — and (2) dispatching
    the device-side snapshot copy plus non-blocking D2H transfers for step N.
    Serialization, fsync and the atomic publish then run on a background
    thread, entirely off the step stream. At most ONE snapshot is in flight;
    ``wait()``/``close()`` are the other commit points (``ModelCheckpoint``
    closes at ``on_train_end``, so a fit never exits with an unpublished
    save).

    Error contract (same cost model as the sync path): a failed write costs
    the checkpoint it was writing, never the run — the error surfaces at the
    NEXT commit point, tagged with ``exc.checkpoint_step``, after all
    processes have rendezvoused (the barrier-before-raise rule: raising
    before the barrier would strand peers). The error is raised AFTER the
    next snapshot is dispatched, so one transient fault loses exactly one
    checkpoint interval.

    Threading rules: the background writer never joins a collective or a
    barrier — the main thread concurrently issues its own, and interleaved
    collectives from two threads deadlock the cluster. v1: only the chief
    has a writer; peers just rendezvous at commit. v2 (sharded): every
    process writes its own shard in background, and the chief's publish
    happens on the MAIN thread at commit, after the written-barrier proves
    every shard landed.
    """

    def __init__(self, directory: str | os.PathLike, *,
                 max_to_keep: Optional[int] = None, sharded: bool = False):
        self.directory = pathlib.Path(directory)
        self.max_to_keep = max_to_keep
        self.sharded = sharded
        self._thread: Optional[threading.Thread] = None
        self._pending_step: Optional[int] = None
        self._error: Optional[BaseException] = None  # writer → commit point
        self._last_path: Optional[str] = None

    @property
    def in_flight_step(self) -> Optional[int]:
        """Step currently snapshot-ed/writing, or None when drained."""
        return self._pending_step

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self._drain()  # already unwinding: don't mask the original error

    def save_async(self, model_or_variables, *, step: int) -> None:
        """Commit the previous save, snapshot the current state, and hand it
        to the background writer. Raises the PREVIOUS save's stored error
        (if any) after the new snapshot is safely in flight."""
        t0 = time.perf_counter()
        prev_error = self._drain()
        saveable = _saveable(model_or_variables)
        t_snap = time.perf_counter()
        if self.sharded:
            self._begin_sharded(saveable, step)
        else:
            self._begin_v1(saveable, step)
        now = time.perf_counter()
        metrics_lib.inc("checkpoint.async_saves")
        metrics_lib.set_gauge("checkpoint.inflight", 1.0)
        metrics_lib.observe_value("checkpoint.snapshot_s", now - t_snap)
        metrics_lib.observe_value("checkpoint.stall_s", now - t0)
        if prev_error is not None:
            raise prev_error

    def wait(self) -> Optional[str]:
        """Commit point: join the in-flight write, rendezvous, raise any
        stored error. Returns the last successfully published path (chief;
        None on non-chief v1 processes or before the first publish)."""
        error = self._drain()
        if error is not None:
            raise error
        return self._last_path

    def close(self) -> Optional[str]:
        """Drain and commit; alias of :meth:`wait` (the checkpointer stays
        usable afterwards — "closed" means "nothing left in flight")."""
        return self.wait()

    # -- snapshot/dispatch phase (main thread) --------------------------------

    def _begin_v1(self, saveable, step: int) -> None:
        if not bootstrap.is_chief():
            # Peers only join the chief's gathers; their commit-point barrier
            # is the sole remaining rendezvous.
            _join_gathers(saveable)
            self._pending_step = step
            return
        snap = _snapshot(saveable, gather=True)
        self._pending_step = step
        self._spawn(self._write_v1, snap, step)

    def _begin_sharded(self, saveable, step: int) -> None:
        stage = self.directory / f".stage-{step}"
        # The chief clears any torn stage left by a crashed earlier attempt
        # (a resume can re-save the same step) before anyone writes into it —
        # the one rendezvous the sharded snapshot phase pays.
        if bootstrap.is_chief():
            self.directory.mkdir(parents=True, exist_ok=True)
            if stage.exists():
                shutil.rmtree(stage)
            stage.mkdir()
        bootstrap.barrier(f"checkpoint_stage_{step}")
        snap = _snapshot(saveable, gather=False)
        self._pending_step = step
        self._spawn(self._write_sharded, snap, stage, step)

    def _spawn(self, fn, *args) -> None:
        self._thread = threading.Thread(
            target=fn, args=args, daemon=True,
            name=f"tpu-dist-ckpt-writer-{args[-1]}")
        self._thread.start()

    # -- writer phase (background thread; no collectives, no barriers) -------

    def _write_v1(self, snap, step: int) -> None:
        t0 = time.perf_counter()
        # _last_path/_error are single-writer handoffs, not shared state:
        # the writer owns them until _drain's join(), and the join is the
        # happens-before edge for the main thread's read-and-reset.
        try:
            self._last_path = _write_v1_checkpoint(  # shardcheck: disable=SC401 -- handoff attr; _drain joins before touching it
                self.directory, _flatten_local(snap), step=step,
                max_to_keep=self.max_to_keep)
        except Exception as exc:  # delivered at the next commit point
            self._error = exc  # shardcheck: disable=SC401 -- handoff attr; _drain joins before touching it
        finally:
            metrics_lib.observe_value("checkpoint.write_s",
                                      time.perf_counter() - t0)

    def _write_sharded(self, snap, stage: pathlib.Path, step: int) -> None:
        t0 = time.perf_counter()
        try:
            _write_sharded_stage(stage, snap, step=step)
        except Exception as exc:  # delivered at the next commit point
            self._error = exc
        finally:
            metrics_lib.observe_value("checkpoint.write_s",
                                      time.perf_counter() - t0)

    # -- commit phase (main thread) -------------------------------------------

    def _drain(self) -> Optional[BaseException]:
        """Join the writer, run the commit-point barrier protocol, and
        RETURN (not raise) any error so callers choose when to surface it."""
        if self._pending_step is None:
            return None
        t0 = time.perf_counter()
        step, self._pending_step = self._pending_step, None
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        error, self._error = self._error, None
        if self.sharded:
            error = self._commit_sharded(step, error)
        bootstrap.barrier(f"checkpoint_commit_{step}")
        metrics_lib.set_gauge("checkpoint.inflight", 0.0)
        metrics_lib.observe_value("checkpoint.commit_s",
                                  time.perf_counter() - t0)
        if error is not None:
            metrics_lib.inc("checkpoint.write_errors")
            error.checkpoint_step = step
            logger.warning("async checkpoint step %d failed: %s", step, error)
            return error
        return None

    def _commit_sharded(self, step: int,
                        error: Optional[BaseException]) -> Optional[BaseException]:
        stage = self.directory / f".stage-{step}"
        target = _step_dir(self.directory, step)
        from tpu_dist.parallel.collectives import host_all_reduce_sum

        # Publish only when EVERY process staged cleanly: a torn v2 step
        # would pass the chief's local view and only fail at restore-time
        # assembly. A failing peer raises its own local error; the chief
        # just withholds the publish.
        bad = int(host_all_reduce_sum(np.int64(0 if error is None else 1)))
        if bootstrap.is_chief():
            if bad == 0:
                try:
                    _fire_write_fault(stage, step)
                    _publish_stage(stage, target, self.directory, step)
                    logger.info(
                        "async sharded checkpoint step %d written to %s "
                        "(%d writers)", step, target, jax.process_count())
                    if self.max_to_keep is not None:
                        _gc(self.directory, self.max_to_keep)
                    self._last_path = str(target)
                except OSError as exc:
                    error = exc
                    shutil.rmtree(stage, ignore_errors=True)
            else:
                shutil.rmtree(stage, ignore_errors=True)
        return error


def _manifest(target: pathlib.Path) -> dict:
    mf = target / _MANIFEST
    if mf.is_file():
        try:
            return json.loads(mf.read_text())
        except ValueError:
            pass
    return {}


def _iter_sharded_leaves(target: pathlib.Path):
    """Yield ``(key, assemble)`` for every leaf of a v2 checkpoint —
    ``assemble()`` materializes that ONE leaf's global host array.
    ``restore`` currently materializes all leaves (its contract returns a
    host pytree); the per-leaf shape exists so a streaming restore —
    assemble one leaf, ``device_put`` it, drop the host copy — can be
    built on it without touching the file format."""
    manifest = _manifest(target)
    indices: dict[str, list] = {}
    by_file: dict[str, dict] = {}
    for idx_file in sorted(target.glob("shards-*.json")):
        arr_file = target / idx_file.name.replace(
            "shards-", "arrays-shard-").replace(".json", ".npz")
        listing = json.loads(idx_file.read_text())
        for key, entries in listing.items():
            for e in entries:
                e["file"] = str(arr_file)
            indices.setdefault(key, []).extend(entries)
    chief = target / _ARRAYS

    def load_from(fname, name):
        z = by_file.get(fname)
        if z is None:
            z = by_file[fname] = np.load(fname)
        return z[name]

    for key, meta in manifest["leaves"].items():
        if not meta["sharded"]:
            yield key, (lambda k=key: load_from(str(chief), k))
            continue

        def assemble(k=key, m=meta):
            entries = indices.get(k)
            if not entries:
                raise FileNotFoundError(
                    f"sharded checkpoint {target} has no shards for {k!r} "
                    "— were all processes' shard files on this "
                    "filesystem? (v2 checkpoints require a shared FS)")
            out = np.zeros(tuple(m["shape"]), np.dtype(m["dtype"]))
            filled = 0
            for e in entries:
                data = load_from(e["file"], e["name"])
                want = tuple(b - a for a, b in e["slices"])
                if tuple(data.shape) != want:
                    raise ValueError(
                        f"sharded checkpoint {target}: shard "
                        f"{e['name']!r} of {k!r} has shape "
                        f"{tuple(data.shape)} but its index claims slices "
                        f"{e['slices']} ({want}) — shard index and data "
                        "disagree (mixed checkpoint generations, or a "
                        "corrupted shard file); refusing to assemble a "
                        "torn state")
                sl = tuple(slice(a, b) for a, b in e["slices"])
                out[sl] = data
                filled += data.size
            if filled != out.size:
                raise ValueError(
                    f"sharded checkpoint {target}: shards for {k!r} "
                    f"cover {filled} of {out.size} elements — missing "
                    "shard files (v2 checkpoints require a shared FS)")
            return out

        yield key, assemble


def _gc(directory: pathlib.Path, max_to_keep: int) -> None:
    steps = sorted(all_steps(directory))
    for old in steps[:-max_to_keep]:
        shutil.rmtree(_step_dir(directory, old), ignore_errors=True)


def all_steps(directory: str | os.PathLike) -> list[int]:
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    out = []
    for child in directory.iterdir():
        if child.is_dir() and child.name.startswith("ckpt-"):
            try:
                out.append(int(child.name.split("-", 1)[1]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    directory = pathlib.Path(directory)
    pointer = directory / _POINTER
    if pointer.is_file():
        try:
            return int(pointer.read_text().strip())
        except ValueError:
            pass
    steps = all_steps(directory)
    return steps[-1] if steps else None


# -- integrity validation (resume must never trust a half-written step) ------

def _npz_names(path: pathlib.Path) -> Optional[set]:
    """Member names of an npz, or None when the file is unreadable — a
    truncated write leaves a zip without its central directory, which
    np.load rejects at open."""
    import zipfile

    try:
        with np.load(path) as z:
            return set(z.files)
    except (OSError, ValueError, zipfile.BadZipFile):
        return None


def validate_step_dir(target: str | os.PathLike) -> Optional[str]:
    """Why checkpoint directory ``target`` is NOT safe to restore from, or
    None when it is.

    Validation is structural (manifest parses, array containers open, v1
    key sets agree), not content hashing: the threat model is a write cut
    short — by a crash, a preemption, or an injected fault — on a path
    where the atomic temp+rename publish was subverted (non-atomic network
    filesystems, partial rsync copies). Cheap enough to run on every
    resume."""
    target = pathlib.Path(target)
    if not target.is_dir():
        return "missing checkpoint directory"
    mf = target / _MANIFEST
    if not mf.is_file():
        return "missing manifest.json"
    try:
        manifest = json.loads(mf.read_text())
    except ValueError:
        return "manifest.json does not parse"
    fmt = manifest.get("format")
    if fmt == _FORMAT_V1:
        names = _npz_names(target / _ARRAYS)
        if names is None:
            return f"{_ARRAYS} is unreadable (truncated write?)"
        keys = manifest.get("keys")
        if keys is not None and set(keys) != names:
            missing = sorted(set(keys) - names)[:3]
            return (f"{_ARRAYS} does not match manifest keys "
                    f"(e.g. missing {missing})")
        return None
    if fmt == _FORMAT_V2:
        if _npz_names(target / _ARRAYS) is None:
            return f"chief {_ARRAYS} is unreadable (truncated write?)"
        for idx_file in sorted(target.glob("shards-*.json")):
            try:
                json.loads(idx_file.read_text())
            except ValueError:
                return f"{idx_file.name} does not parse"
            arr = target / idx_file.name.replace(
                "shards-", "arrays-shard-").replace(".json", ".npz")
            if _npz_names(arr) is None:
                return f"{arr.name} is unreadable (truncated write?)"
        return None
    return f"unknown checkpoint format {fmt!r}"


def is_complete(directory: str | os.PathLike, step: int) -> bool:
    return validate_step_dir(_step_dir(pathlib.Path(directory), step)) is None


def latest_complete_step(directory: str | os.PathLike, *,
                         before: Optional[int] = None) -> Optional[int]:
    """The newest step that passes :func:`validate_step_dir` — the resume
    anchor. The pointer file is a hint, not an authority: a fault between
    publish and pointer update (or a corrupt published step) must cost at
    most one checkpoint interval, never the whole run. Unpublished async
    stages (``.stage-N`` dirs, temp dirs) never match the ``ckpt-`` step
    pattern, so a save that died in flight is invisible here by
    construction.

    ``before`` restricts the search to steps strictly earlier than the given
    step — the integrity guard's rollback escalation: when a restore of step
    N did not clear an anomaly (the corruption predates it), the next
    candidate is the newest complete step ``before=N``."""
    directory = pathlib.Path(directory)
    pointed = latest_step(directory)
    if before is not None and pointed is not None and pointed >= before:
        pointed = None
    if pointed is not None and is_complete(directory, pointed):
        return pointed
    for step in reversed(all_steps(directory)):
        if before is not None and step >= before:
            continue
        if step == pointed:
            continue  # already rejected above
        reason = validate_step_dir(_step_dir(directory, step))
        if reason is None:
            if pointed is not None:
                logger.warning(
                    "checkpoint step %s is incomplete (%s); resuming from "
                    "step %d instead", pointed,
                    validate_step_dir(_step_dir(directory, pointed)), step)
            return step
        logger.warning("skipping incomplete checkpoint step %d: %s",
                       step, reason)
    return None


def restore(directory: str | os.PathLike, template: Any, *,
            step: Optional[int] = None) -> tuple[Any, int]:
    """Load checkpoint arrays into the structure of ``template``.

    Returns (host variables pytree, step). Process 0's bytes are broadcast to
    every process so the restored state is identical cluster-wide even if the
    filesystem is not shared/consistent.
    """
    directory = pathlib.Path(directory)
    if step is None:
        # Resolve on process 0 and broadcast the choice: checkpoints are
        # chief-written, so peers may have no local copy (or, on an
        # eventually-consistent shared FS, see a different latest step).
        # "Latest" means latest COMPLETE: a step that fails manifest
        # validation (half-written, truncated, corrupted) is skipped in
        # favor of the newest one that verifies — a fault injected
        # mid-write costs one checkpoint interval, never a corrupt restore.
        if jax.process_count() > 1:
            from tpu_dist.parallel.collectives import broadcast_from_chief

            local = latest_complete_step(directory) \
                if bootstrap.process_index() == 0 else None
            chosen = int(broadcast_from_chief(
                np.int64(-1 if local is None else local)))
            step = None if chosen < 0 else chosen
        else:
            step = latest_complete_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no complete checkpoints under {directory}")
    target = _step_dir(directory, step)
    # Integrity gate + FORMAT branch, decided by the chief and broadcast so
    # they are uniform cluster-wide (checkpoints are chief-written — peers
    # may hold no local copy, and the v2 path returns without broadcasting,
    # so a peer taking the v1 branch alone would hang in
    # broadcast_from_chief waiting for a collective the chief never joins).
    # Verdict encoding: -1 invalid, 0 restore as v1, 1 restore as v2.
    if bootstrap.process_index() == 0:
        reason = validate_step_dir(target)
        verdict = -1 if reason is not None else int(
            _manifest(target).get("format") == _FORMAT_V2)
    else:
        reason, verdict = None, 0  # placeholder; chief's value wins below
    if jax.process_count() > 1:
        from tpu_dist.parallel.collectives import broadcast_from_chief

        verdict = int(broadcast_from_chief(np.int64(verdict)))
    if verdict < 0:
        raise ValueError(
            f"checkpoint step {step} at {target} failed validation"
            + (f": {reason}" if reason else "")
            + "; refusing to restore from an incomplete checkpoint")
    is_v2 = bool(verdict)
    if is_v2:
        # v2 (sharded) lives on a shared FS by contract: every process
        # assembles directly from the shard files — no broadcast needed.
        arrays = {k: assemble()
                  for k, assemble in _iter_sharded_leaves(target)}
        host_template = jax.tree_util.tree_map(_placeholder, template)
        restored = _unflatten_into(host_template, arrays)
        logger.info("restored sharded checkpoint step %d from %s",
                    step, target)
        return restored, step
    # The template's VALUES are never read — the chief overwrites every leaf
    # from the npz and peers receive the broadcast — so sharded leaves (a TP
    # job's live variables) become zero placeholders of their GLOBAL shape
    # rather than paying a cross-process allgather per leaf.
    host_template = jax.tree_util.tree_map(_placeholder, template)
    if bootstrap.process_index() == 0:
        with np.load(target / _ARRAYS) as z:
            arrays = {k: z[k] for k in z.files}
        restored = _unflatten_into(host_template, arrays)
    else:
        # Non-chief processes skip the (possibly shared-FS) read entirely;
        # they receive process 0's bytes in the broadcast below.
        restored = host_template
    from tpu_dist.parallel.collectives import broadcast_from_chief

    restored = broadcast_from_chief(restored)
    logger.info("restored checkpoint step %d from %s", step, target)
    return restored, step


def _check_divisible_placement(strategy, host,
                               sharded_keys: frozenset | set = frozenset()
                               ) -> None:
    """Reject a reshape-restore that would SILENTLY degrade placement.

    ``prune_indivisible`` replaces any spec whose sharded dim doesn't tile
    evenly with replicated — the right degradation for live construction,
    but on the RESTORE path it would quietly absorb a bad elastic reshape
    (e.g. a 48-row TP leaf relaunched on a 5-wide model axis) as a
    replicated tree with a different memory/step profile than the job that
    saved. Only leaves the checkpoint actually stored SHARDED
    (``sharded_keys``, from the v2 manifest) are held to this bar: a leaf
    the saving job already replicated (its dim never tiled — e.g. a
    vocab-sized bias) keeps degrading gracefully, as does a spec naming an
    axis the new mesh simply doesn't have (restoring a TP checkpoint onto
    a data-only mesh is supported). v1 checkpoints carry no per-leaf
    sharding, so they pass ``frozenset()`` and skip the check — they
    always stored a gathered global copy."""
    from jax.sharding import PartitionSpec as P

    from tpu_dist.parallel import tensor as tensor_lib

    mesh = getattr(strategy, "_mesh", None)
    if mesh is None or not sharded_keys:
        return
    specs = tensor_lib.specs_like_params(
        host, strategy.param_spec_tree(host["params"]))

    def check(path, spec, leaf):
        if jax.tree_util.keystr(path) not in sharded_keys:
            return  # stored replicated: no placement to lose
        shape = getattr(leaf, "shape", ())
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            if any(ax not in mesh.shape for ax in axes):
                continue  # axis gone entirely: graceful replication
            div = 1
            for ax in axes:
                div *= mesh.shape[ax]
            if dim < len(shape) and shape[dim] % div:
                key = jax.tree_util.keystr(path)
                raise ValueError(
                    f"cannot reshape-restore {key}: dimension {dim} of "
                    f"shape {tuple(shape)} does not divide mesh axis "
                    f"{axis!r} (size {div}) — relaunch with a worker/"
                    "device count whose axis sizes divide every sharded "
                    "dimension, or restore on the original topology")

    jax.tree_util.tree_map_with_path(
        check, specs, host, is_leaf=lambda x: isinstance(x, P))


def restore_model(directory: str | os.PathLike, model, *,
                  step: Optional[int] = None, trainer=None) -> int:
    """Restore a compiled model's training variables in place (resume).

    ``trainer`` pins which Trainer's variables receive the restored state —
    required when a Trainer other than ``model._trainer`` is driving (e.g.
    the running trainer inside ``fit(checkpoint_dir=...)``); defaults to the
    model's own trainer.

    Elastic reshape: the restored host tree is GLOBAL (v1 by construction;
    v2 stitched from the per-process shard files), so placement works on
    any target mesh — restoring a checkpoint written by P processes /
    D devices onto Q≠P / E≠D re-shards the same global state. Optimizer
    moments ride along (they inherit the params' specs by path suffix) and
    RNG needs no state at all: the trainer derives its per-epoch keys from
    ``seed`` and the epoch index, so a reshaped resume replays the exact
    key sequence of the original job. A reshape that would force a SILENT
    placement degradation (sharded dim not divisible by the new axis size)
    raises instead — see :func:`_check_divisible_placement`."""
    from tpu_dist.training.trainer import Trainer

    if trainer is None:
        if model._trainer is None:
            model._trainer = Trainer(model)
        trainer = model._trainer
    trainer.ensure_variables()
    v = trainer.variables
    template = {k: v[k] for k in ("params", "state", "opt") if k in v}
    host, step = restore(directory, template, step=step)
    manifest = _manifest(_step_dir(pathlib.Path(directory), step))
    sharded_keys = {k for k, m in manifest.get("leaves", {}).items()
                    if m.get("sharded")}
    _check_divisible_placement(trainer.strategy, host, sharded_keys)
    _note_reshape(pathlib.Path(directory), step)
    # Strategy-owned placement: mirrored on a data mesh, Megatron shards
    # under a 'model' axis — a TP job must NOT come back replicated (it
    # would multiply per-device param+moment memory by the model-axis size
    # and force a reshard on the first step).
    placed = trainer.strategy.place_variables(host["params"], host,
                                              broadcast=False)
    for k in template:
        v[k] = placed[k]
    return step


def _note_reshape(directory: pathlib.Path, step: int) -> None:
    """Count/record a topology-changing restore, from the manifest's
    topology stamp (older checkpoints without one are simply not counted).
    Observability only — never fails the restore."""
    try:
        manifest = _manifest(_step_dir(directory, step))
        saved_procs = manifest.get("process_count")
        saved_devs = manifest.get("device_count")
        now_procs, now_devs = jax.process_count(), jax.device_count()
        reshaped = ((saved_procs is not None and saved_procs != now_procs)
                    or (saved_devs is not None and saved_devs != now_devs))
        if not reshaped:
            return
        metrics_lib.inc("elastic.reshape_restores")
        logger.info(
            "reshape-restore of step %d: saved on %s process(es) / %s "
            "device(s), restoring on %d / %d", step, saved_procs,
            saved_devs, now_procs, now_devs)
        from tpu_dist.resilience import events

        events.maybe_log("reshape_restore", step=step,
                         saved_process_count=saved_procs,
                         saved_device_count=saved_devs,
                         process_count=now_procs, device_count=now_devs)
    except OSError:
        pass
