"""Checkpoint / resume: chief-writes, everyone-restores (SURVEY.md §5.4).

The reference specifies the capability in prose only — the chief's duties
include "saving checkpoint models" (README.md:51); the example itself never
saves. Parity target: chief-only checkpoint + resume-from-latest, not a format
zoo. Format: one ``.npz`` of flattened arrays + a JSON manifest per step,
written atomically (temp + rename), with a ``checkpoint`` pointer file naming
the latest step — restore on every process, then a broadcast from process 0
guarantees bit-identical restored state cluster-wide (the D4 init-broadcast
rule applied to resume; divergence-free restore is SURVEY.md hard-part #3).
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import tempfile
from typing import Any, Optional

import jax
import numpy as np

from tpu_dist.cluster import bootstrap

logger = logging.getLogger("tpu_dist.checkpoint")

_POINTER = "checkpoint"
_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"


def _to_host(leaf) -> np.ndarray:
    """Fetch a leaf's GLOBAL value to host memory.

    Replicated or single-process leaves read locally; a model-sharded leaf in
    a multi-process job spans non-addressable devices, so ``np.asarray`` would
    raise — those are allgathered across processes first. The gather is a
    COLLECTIVE: every process must reach it (callers hoist flattening out of
    chief-only branches; the addressability predicate is uniform across
    processes because it is a property of the one global array)."""
    if _needs_allgather(leaf):
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(leaf)


def _needs_allgather(leaf) -> bool:
    """The ONE definition of "this leaf's host copy requires a collective".

    Chief and peers count collectives off this predicate; two drifting
    copies would mean mismatched process_allgather calls — a cluster-wide
    hang, not a wrong answer. Keep every caller on this helper."""
    return isinstance(leaf, jax.Array) and not (
        leaf.is_fully_addressable or leaf.is_fully_replicated)


def _placeholder(leaf) -> np.ndarray:
    """Host array with a leaf's global shape/dtype and arbitrary contents —
    for templates whose values are about to be overwritten. ``jax.Array.shape``
    is the global shape, so no collective and no device transfer happens."""
    if isinstance(leaf, jax.Array):
        return np.zeros(leaf.shape, leaf.dtype)
    return np.asarray(leaf)


def _needs_gather(tree) -> bool:
    return any(_needs_allgather(l) for l in jax.tree_util.tree_leaves(tree))


def _flatten(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _to_host(leaf)
    return flat


def _unflatten_into(template, arrays: dict[str, np.ndarray]):
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(
                f"checkpoint missing array {key!r}; checkpoint/model mismatch")
        arr = arrays[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint array {key!r} has shape {arr.shape}, model "
                f"expects {np.shape(leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _step_dir(directory: pathlib.Path, step: int) -> pathlib.Path:
    return directory / f"ckpt-{step}"


def save(directory: str | os.PathLike, model_or_variables, *, step: int,
         max_to_keep: Optional[int] = None) -> Optional[str]:
    """Write checkpoint ``step``; returns its path (None on non-chief).

    Accepts a compiled Model (saves its live training variables) or a raw
    variables pytree. Only the chief writes (README.md:51); all processes
    rendezvous afterwards so no peer races ahead of a half-written checkpoint.
    """
    variables = getattr(model_or_variables, "variables", model_or_variables)
    if variables is None:
        raise ValueError("model has no materialized variables to save; "
                         "run fit() or ensure_variables() first")
    saveable = {k: variables[k] for k in ("params", "state", "opt")
                if k in variables}
    directory = pathlib.Path(directory)
    path = None
    # Tensor-parallel leaves require a cross-process allgather (a collective),
    # so non-chief processes must JOIN each gather — but only the gathers:
    # they walk the same leaf order the chief's _flatten does and discard the
    # results, paying nothing for replicated leaves. Pure-DP saves keep their
    # old shape (chief-only host copy, peers untouched).
    if _needs_gather(saveable) and not bootstrap.is_chief():
        for leaf in jax.tree_util.tree_leaves(saveable):
            if _needs_allgather(leaf):
                _to_host(leaf)
    if bootstrap.is_chief():
        directory.mkdir(parents=True, exist_ok=True)
        target = _step_dir(directory, step)
        flat = _flatten(saveable)
        # Atomic publish: stage into a temp dir, then rename into place.
        with tempfile.TemporaryDirectory(dir=directory) as tmp:
            tmp_path = pathlib.Path(tmp) / "stage"
            tmp_path.mkdir()
            np.savez(tmp_path / _ARRAYS, **flat)
            (tmp_path / _MANIFEST).write_text(json.dumps({
                "step": step,
                "keys": sorted(flat),
                "format": "tpu_dist.checkpoint.v1",
            }))
            if target.exists():
                import shutil

                shutil.rmtree(target)
            os.replace(tmp_path, target)
        (directory / _POINTER).write_text(str(step))
        path = str(target)
        logger.info("checkpoint step %d written to %s", step, target)
        if max_to_keep is not None:
            _gc(directory, max_to_keep)
    bootstrap.barrier(f"checkpoint_save_{step}")
    return path


def _gc(directory: pathlib.Path, max_to_keep: int) -> None:
    steps = sorted(all_steps(directory))
    for old in steps[:-max_to_keep]:
        import shutil

        shutil.rmtree(_step_dir(directory, old), ignore_errors=True)


def all_steps(directory: str | os.PathLike) -> list[int]:
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    out = []
    for child in directory.iterdir():
        if child.is_dir() and child.name.startswith("ckpt-"):
            try:
                out.append(int(child.name.split("-", 1)[1]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    directory = pathlib.Path(directory)
    pointer = directory / _POINTER
    if pointer.is_file():
        try:
            return int(pointer.read_text().strip())
        except ValueError:
            pass
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore(directory: str | os.PathLike, template: Any, *,
            step: Optional[int] = None) -> tuple[Any, int]:
    """Load checkpoint arrays into the structure of ``template``.

    Returns (host variables pytree, step). Process 0's bytes are broadcast to
    every process so the restored state is identical cluster-wide even if the
    filesystem is not shared/consistent.
    """
    directory = pathlib.Path(directory)
    if step is None:
        # Resolve on process 0 and broadcast the choice: checkpoints are
        # chief-written, so peers may have no local copy (or, on an
        # eventually-consistent shared FS, see a different latest step).
        if jax.process_count() > 1:
            from tpu_dist.parallel.collectives import broadcast_from_chief

            local = latest_step(directory) if bootstrap.process_index() == 0 \
                else None
            chosen = int(broadcast_from_chief(
                np.int64(-1 if local is None else local)))
            step = None if chosen < 0 else chosen
        else:
            step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    target = _step_dir(directory, step)
    # The template's VALUES are never read — the chief overwrites every leaf
    # from the npz and peers receive the broadcast — so sharded leaves (a TP
    # job's live variables) become zero placeholders of their GLOBAL shape
    # rather than paying a cross-process allgather per leaf.
    host_template = jax.tree_util.tree_map(_placeholder, template)
    if bootstrap.process_index() == 0:
        with np.load(target / _ARRAYS) as z:
            arrays = {k: z[k] for k in z.files}
        restored = _unflatten_into(host_template, arrays)
    else:
        # Non-chief processes skip the (possibly shared-FS) read entirely;
        # they receive process 0's bytes in the broadcast below.
        restored = host_template
    from tpu_dist.parallel.collectives import broadcast_from_chief

    restored = broadcast_from_chief(restored)
    logger.info("restored checkpoint step %d from %s", step, target)
    return restored, step


def restore_model(directory: str | os.PathLike, model, *,
                  step: Optional[int] = None, trainer=None) -> int:
    """Restore a compiled model's training variables in place (resume).

    ``trainer`` pins which Trainer's variables receive the restored state —
    required when a Trainer other than ``model._trainer`` is driving (e.g.
    the running trainer inside ``fit(checkpoint_dir=...)``); defaults to the
    model's own trainer."""
    from tpu_dist.training.trainer import Trainer

    if trainer is None:
        if model._trainer is None:
            model._trainer = Trainer(model)
        trainer = model._trainer
    trainer.ensure_variables()
    v = trainer.variables
    template = {k: v[k] for k in ("params", "state", "opt") if k in v}
    host, step = restore(directory, template, step=step)
    # Strategy-owned placement: mirrored on a data mesh, Megatron shards
    # under a 'model' axis — a TP job must NOT come back replicated (it
    # would multiply per-device param+moment memory by the model-axis size
    # and force a reshard on the first step).
    placed = trainer.strategy.place_variables(host["params"], host,
                                              broadcast=False)
    for k in template:
        v[k] = placed[k]
    return step
