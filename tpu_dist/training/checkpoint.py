"""Checkpoint / resume: chief-writes, everyone-restores (SURVEY.md §5.4).

The reference specifies the capability in prose only — the chief's duties
include "saving checkpoint models" (README.md:51); the example itself never
saves. Parity target: chief-only checkpoint + resume-from-latest, not a format
zoo. Format: one ``.npz`` of flattened arrays + a JSON manifest per step,
written atomically (temp + rename), with a ``checkpoint`` pointer file naming
the latest step — restore on every process, then a broadcast from process 0
guarantees bit-identical restored state cluster-wide (the D4 init-broadcast
rule applied to resume; divergence-free restore is SURVEY.md hard-part #3).
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import tempfile
from typing import Any, Optional

import jax
import numpy as np

from tpu_dist.cluster import bootstrap

logger = logging.getLogger("tpu_dist.checkpoint")

_POINTER = "checkpoint"
_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_FORMAT_V1 = "tpu_dist.checkpoint.v1"
_FORMAT_V2 = "tpu_dist.checkpoint.v2-sharded"


def _shard_arrays(process: int) -> str:
    return f"arrays-shard-{process}.npz"


def _shard_index(process: int) -> str:
    return f"shards-{process}.json"


def _to_host(leaf) -> np.ndarray:
    """Fetch a leaf's GLOBAL value to host memory.

    Replicated or single-process leaves read locally; a model-sharded leaf in
    a multi-process job spans non-addressable devices, so ``np.asarray`` would
    raise — those are allgathered across processes first. The gather is a
    COLLECTIVE: every process must reach it (callers hoist flattening out of
    chief-only branches; the addressability predicate is uniform across
    processes because it is a property of the one global array)."""
    if _needs_allgather(leaf):
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(leaf)


def _needs_allgather(leaf) -> bool:
    """The ONE definition of "this leaf's host copy requires a collective".

    Chief and peers count collectives off this predicate; two drifting
    copies would mean mismatched process_allgather calls — a cluster-wide
    hang, not a wrong answer. Keep every caller on this helper."""
    return isinstance(leaf, jax.Array) and not (
        leaf.is_fully_addressable or leaf.is_fully_replicated)


def _placeholder(leaf) -> np.ndarray:
    """Host array with a leaf's global shape/dtype and arbitrary contents —
    for templates whose values are about to be overwritten. ``jax.Array.shape``
    is the global shape, so no collective and no device transfer happens."""
    if isinstance(leaf, jax.Array):
        return np.zeros(leaf.shape, leaf.dtype)
    return np.asarray(leaf)


def _needs_gather(tree) -> bool:
    return any(_needs_allgather(l) for l in jax.tree_util.tree_leaves(tree))


def _flatten(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = _to_host(leaf)
    return flat


def _unflatten_into(template, arrays: dict[str, np.ndarray]):
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(
                f"checkpoint missing array {key!r}; checkpoint/model mismatch")
        arr = arrays[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint array {key!r} has shape {arr.shape}, model "
                f"expects {np.shape(leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _step_dir(directory: pathlib.Path, step: int) -> pathlib.Path:
    return directory / f"ckpt-{step}"


#: Fault-injection seam (tpu_dist.resilience): called on the chief with the
#: fully staged checkpoint directory right before the atomic publish. A hook
#: may raise OSError (a transient write failure — the stage is discarded and
#: nothing is published) or corrupt the staged files in place (simulating a
#: mid-write crash on a filesystem whose rename is not atomic); restore-side
#: manifest validation must then reject the published step. None in
#: production — one pointer check per save.
_WRITE_FAULT_HOOK = None


def install_write_fault_hook(hook):
    """Install (or, with None, remove) the checkpoint write fault hook;
    returns the previously installed hook. ``hook(stage_dir, step)``."""
    global _WRITE_FAULT_HOOK
    prev = _WRITE_FAULT_HOOK
    _WRITE_FAULT_HOOK = hook
    return prev


def _fire_write_fault(stage: pathlib.Path, step: int) -> None:
    if _WRITE_FAULT_HOOK is not None:
        _WRITE_FAULT_HOOK(stage, step)


def save(directory: str | os.PathLike, model_or_variables, *, step: int,
         max_to_keep: Optional[int] = None,
         sharded: bool = False) -> Optional[str]:
    """Write checkpoint ``step``; returns its path (None on non-chief
    unless ``sharded``).

    Accepts a compiled Model (saves its live training variables) or a raw
    variables pytree. Only the chief writes (README.md:51); all processes
    rendezvous afterwards so no peer races ahead of a half-written checkpoint.

    ``sharded=True`` writes the v2 layout instead: EVERY process writes its
    own ``arrays-shard-p.npz`` holding only its addressable shards of
    non-replicated leaves (O(model/P) host memory and P-way parallel write
    bandwidth — the matching story for TP/PP/EP-sharded models, where the
    chief-writes path would allgather O(model) through one host), the chief
    writes replicated leaves + the manifest, and two barriers bracket a
    chief-created staging directory so the rename publish stays atomic.
    Requires a FILESYSTEM SHARED by all processes (the standard sharded-
    checkpoint contract); restore re-places onto whatever mesh is current,
    so cross-topology moves work exactly like v1.
    """
    variables = getattr(model_or_variables, "variables", model_or_variables)
    if variables is None:
        raise ValueError("model has no materialized variables to save; "
                         "run fit() or ensure_variables() first")
    saveable = {k: variables[k] for k in ("params", "state", "opt")
                if k in variables}
    directory = pathlib.Path(directory)
    if sharded:
        return _save_sharded(directory, saveable, step=step,
                             max_to_keep=max_to_keep)
    path = None
    # Tensor-parallel leaves require a cross-process allgather (a collective),
    # so non-chief processes must JOIN each gather — but only the gathers:
    # they walk the same leaf order the chief's _flatten does and discard the
    # results, paying nothing for replicated leaves. Pure-DP saves keep their
    # old shape (chief-only host copy, peers untouched).
    if _needs_gather(saveable) and not bootstrap.is_chief():
        for leaf in jax.tree_util.tree_leaves(saveable):
            if _needs_allgather(leaf):
                _to_host(leaf)
    write_error: Optional[OSError] = None
    if bootstrap.is_chief():
        directory.mkdir(parents=True, exist_ok=True)
        target = _step_dir(directory, step)
        flat = _flatten(saveable)
        # Atomic publish: stage into a temp dir, then rename into place.
        # A write failure (real, or injected through the fault seam) must
        # not skip the closing barrier — peers are already waiting there,
        # so raising early would trade a lost checkpoint for a cluster-wide
        # hang. Record, rendezvous, then propagate.
        try:
            with tempfile.TemporaryDirectory(dir=directory) as tmp:
                tmp_path = pathlib.Path(tmp) / "stage"
                tmp_path.mkdir()
                np.savez(tmp_path / _ARRAYS, **flat)
                (tmp_path / _MANIFEST).write_text(json.dumps({
                    "step": step,
                    "keys": sorted(flat),
                    "format": _FORMAT_V1,
                }))
                _fire_write_fault(tmp_path, step)
                if target.exists():
                    import shutil

                    shutil.rmtree(target)
                os.replace(tmp_path, target)
            (directory / _POINTER).write_text(str(step))
            path = str(target)
            logger.info("checkpoint step %d written to %s", step, target)
            if max_to_keep is not None:
                _gc(directory, max_to_keep)
        except OSError as exc:
            write_error = exc
    bootstrap.barrier(f"checkpoint_save_{step}")
    if write_error is not None:
        raise write_error
    return path


def _is_replicated(leaf) -> bool:
    """Leaves the chief owns in the v2 layout: everything that is not a
    multi-device-sharded jax.Array (host numpy, scalars, replicated)."""
    if not isinstance(leaf, jax.Array):
        return True
    return leaf.is_fully_replicated


def _save_sharded(directory: pathlib.Path, saveable, *, step: int,
                  max_to_keep: Optional[int]) -> str:
    proc = bootstrap.process_index()
    stage = directory / f".stage-{step}"
    target = _step_dir(directory, step)
    if bootstrap.is_chief():
        directory.mkdir(parents=True, exist_ok=True)
        if stage.exists():
            import shutil

            shutil.rmtree(stage)
        stage.mkdir()
    bootstrap.barrier(f"checkpoint_stage_{step}")

    # Every process: its addressable replica-0 shards of sharded leaves.
    # replica_id==0 picks exactly one owner per distinct shard index, so
    # leaves replicated over SOME axes (e.g. P('pipe') on a data x pipe
    # mesh) are written once, and the union over processes tiles the
    # global array exactly (asserted at assembly).
    local_arrays: dict[str, np.ndarray] = {}
    index: dict[str, list] = {}
    chief_arrays: dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(saveable)[0]:
        key = jax.tree_util.keystr(path)
        if _is_replicated(leaf):
            if bootstrap.is_chief():
                chief_arrays[key] = np.asarray(leaf)
            continue
        entries = []
        for j, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue
            name = f"{key}//{j}"
            local_arrays[name] = np.asarray(shard.data)
            entries.append({
                "name": name,
                "slices": [[s.start or 0,
                            s.stop if s.stop is not None else dim]
                           for s, dim in zip(shard.index, leaf.shape)],
            })
        if entries:
            index[key] = entries
    np.savez(stage / _shard_arrays(proc), **local_arrays)
    (stage / _shard_index(proc)).write_text(json.dumps(index))
    if bootstrap.is_chief():
        np.savez(stage / _ARRAYS, **chief_arrays)
        meta = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(saveable)[0]:
            key = jax.tree_util.keystr(path)
            dtype = (leaf.dtype if hasattr(leaf, "dtype")
                     else np.asarray(leaf).dtype)
            meta[key] = {
                "shape": list(np.shape(leaf)),
                "dtype": str(dtype),
                "sharded": not _is_replicated(leaf),
            }
        (stage / _MANIFEST).write_text(json.dumps({
            "step": step,
            "format": _FORMAT_V2,
            "process_count": jax.process_count(),
            "leaves": meta,
        }))
    bootstrap.barrier(f"checkpoint_written_{step}")
    write_error: Optional[OSError] = None
    if bootstrap.is_chief():
        # Same barrier-before-raise contract as the v1 path: a publish
        # failure must not strand peers at the closing rendezvous.
        try:
            _fire_write_fault(stage, step)
            if target.exists():
                import shutil

                shutil.rmtree(target)
            os.replace(stage, target)
            (directory / _POINTER).write_text(str(step))
            logger.info(
                "sharded checkpoint step %d written to %s (%d writers)",
                step, target, jax.process_count())
            if max_to_keep is not None:
                _gc(directory, max_to_keep)
        except OSError as exc:
            write_error = exc
            import shutil

            shutil.rmtree(stage, ignore_errors=True)
    bootstrap.barrier(f"checkpoint_save_{step}")
    if write_error is not None:
        raise write_error
    return str(target)


def _manifest(target: pathlib.Path) -> dict:
    mf = target / _MANIFEST
    if mf.is_file():
        try:
            return json.loads(mf.read_text())
        except ValueError:
            pass
    return {}


def _iter_sharded_leaves(target: pathlib.Path):
    """Yield ``(key, assemble)`` for every leaf of a v2 checkpoint —
    ``assemble()`` materializes that ONE leaf's global host array.
    ``restore`` currently materializes all leaves (its contract returns a
    host pytree); the per-leaf shape exists so a streaming restore —
    assemble one leaf, ``device_put`` it, drop the host copy — can be
    built on it without touching the file format."""
    manifest = _manifest(target)
    indices: dict[str, list] = {}
    by_file: dict[str, dict] = {}
    for idx_file in sorted(target.glob("shards-*.json")):
        arr_file = target / idx_file.name.replace(
            "shards-", "arrays-shard-").replace(".json", ".npz")
        listing = json.loads(idx_file.read_text())
        for key, entries in listing.items():
            for e in entries:
                e["file"] = str(arr_file)
            indices.setdefault(key, []).extend(entries)
    chief = target / _ARRAYS

    def load_from(fname, name):
        z = by_file.get(fname)
        if z is None:
            z = by_file[fname] = np.load(fname)
        return z[name]

    for key, meta in manifest["leaves"].items():
        if not meta["sharded"]:
            yield key, (lambda k=key: load_from(str(chief), k))
            continue

        def assemble(k=key, m=meta):
            entries = indices.get(k)
            if not entries:
                raise FileNotFoundError(
                    f"sharded checkpoint {target} has no shards for {k!r} "
                    "— were all processes' shard files on this "
                    "filesystem? (v2 checkpoints require a shared FS)")
            out = np.zeros(tuple(m["shape"]), np.dtype(m["dtype"]))
            filled = 0
            for e in entries:
                data = load_from(e["file"], e["name"])
                sl = tuple(slice(a, b) for a, b in e["slices"])
                out[sl] = data
                filled += data.size
            if filled != out.size:
                raise ValueError(
                    f"sharded checkpoint {target}: shards for {k!r} "
                    f"cover {filled} of {out.size} elements — missing "
                    "shard files (v2 checkpoints require a shared FS)")
            return out

        yield key, assemble


def _gc(directory: pathlib.Path, max_to_keep: int) -> None:
    steps = sorted(all_steps(directory))
    for old in steps[:-max_to_keep]:
        import shutil

        shutil.rmtree(_step_dir(directory, old), ignore_errors=True)


def all_steps(directory: str | os.PathLike) -> list[int]:
    directory = pathlib.Path(directory)
    if not directory.is_dir():
        return []
    out = []
    for child in directory.iterdir():
        if child.is_dir() and child.name.startswith("ckpt-"):
            try:
                out.append(int(child.name.split("-", 1)[1]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    directory = pathlib.Path(directory)
    pointer = directory / _POINTER
    if pointer.is_file():
        try:
            return int(pointer.read_text().strip())
        except ValueError:
            pass
    steps = all_steps(directory)
    return steps[-1] if steps else None


# -- integrity validation (resume must never trust a half-written step) ------

def _npz_names(path: pathlib.Path) -> Optional[set]:
    """Member names of an npz, or None when the file is unreadable — a
    truncated write leaves a zip without its central directory, which
    np.load rejects at open."""
    import zipfile

    try:
        with np.load(path) as z:
            return set(z.files)
    except (OSError, ValueError, zipfile.BadZipFile):
        return None


def validate_step_dir(target: str | os.PathLike) -> Optional[str]:
    """Why checkpoint directory ``target`` is NOT safe to restore from, or
    None when it is.

    Validation is structural (manifest parses, array containers open, v1
    key sets agree), not content hashing: the threat model is a write cut
    short — by a crash, a preemption, or an injected fault — on a path
    where the atomic temp+rename publish was subverted (non-atomic network
    filesystems, partial rsync copies). Cheap enough to run on every
    resume."""
    target = pathlib.Path(target)
    if not target.is_dir():
        return "missing checkpoint directory"
    mf = target / _MANIFEST
    if not mf.is_file():
        return "missing manifest.json"
    try:
        manifest = json.loads(mf.read_text())
    except ValueError:
        return "manifest.json does not parse"
    fmt = manifest.get("format")
    if fmt == _FORMAT_V1:
        names = _npz_names(target / _ARRAYS)
        if names is None:
            return f"{_ARRAYS} is unreadable (truncated write?)"
        keys = manifest.get("keys")
        if keys is not None and set(keys) != names:
            missing = sorted(set(keys) - names)[:3]
            return (f"{_ARRAYS} does not match manifest keys "
                    f"(e.g. missing {missing})")
        return None
    if fmt == _FORMAT_V2:
        if _npz_names(target / _ARRAYS) is None:
            return f"chief {_ARRAYS} is unreadable (truncated write?)"
        for idx_file in sorted(target.glob("shards-*.json")):
            try:
                json.loads(idx_file.read_text())
            except ValueError:
                return f"{idx_file.name} does not parse"
            arr = target / idx_file.name.replace(
                "shards-", "arrays-shard-").replace(".json", ".npz")
            if _npz_names(arr) is None:
                return f"{arr.name} is unreadable (truncated write?)"
        return None
    return f"unknown checkpoint format {fmt!r}"


def is_complete(directory: str | os.PathLike, step: int) -> bool:
    return validate_step_dir(_step_dir(pathlib.Path(directory), step)) is None


def latest_complete_step(directory: str | os.PathLike) -> Optional[int]:
    """The newest step that passes :func:`validate_step_dir` — the resume
    anchor. The pointer file is a hint, not an authority: a fault between
    publish and pointer update (or a corrupt published step) must cost at
    most one checkpoint interval, never the whole run."""
    directory = pathlib.Path(directory)
    pointed = latest_step(directory)
    if pointed is not None and is_complete(directory, pointed):
        return pointed
    for step in reversed(all_steps(directory)):
        if step == pointed:
            continue  # already rejected above
        reason = validate_step_dir(_step_dir(directory, step))
        if reason is None:
            if pointed is not None:
                logger.warning(
                    "checkpoint step %s is incomplete (%s); resuming from "
                    "step %d instead", pointed,
                    validate_step_dir(_step_dir(directory, pointed)), step)
            return step
        logger.warning("skipping incomplete checkpoint step %d: %s",
                       step, reason)
    return None


def restore(directory: str | os.PathLike, template: Any, *,
            step: Optional[int] = None) -> tuple[Any, int]:
    """Load checkpoint arrays into the structure of ``template``.

    Returns (host variables pytree, step). Process 0's bytes are broadcast to
    every process so the restored state is identical cluster-wide even if the
    filesystem is not shared/consistent.
    """
    directory = pathlib.Path(directory)
    if step is None:
        # Resolve on process 0 and broadcast the choice: checkpoints are
        # chief-written, so peers may have no local copy (or, on an
        # eventually-consistent shared FS, see a different latest step).
        # "Latest" means latest COMPLETE: a step that fails manifest
        # validation (half-written, truncated, corrupted) is skipped in
        # favor of the newest one that verifies — a fault injected
        # mid-write costs one checkpoint interval, never a corrupt restore.
        if jax.process_count() > 1:
            from tpu_dist.parallel.collectives import broadcast_from_chief

            local = latest_complete_step(directory) \
                if bootstrap.process_index() == 0 else None
            chosen = int(broadcast_from_chief(
                np.int64(-1 if local is None else local)))
            step = None if chosen < 0 else chosen
        else:
            step = latest_complete_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no complete checkpoints under {directory}")
    target = _step_dir(directory, step)
    # Integrity gate + FORMAT branch, decided by the chief and broadcast so
    # they are uniform cluster-wide (checkpoints are chief-written — peers
    # may hold no local copy, and the v2 path returns without broadcasting,
    # so a peer taking the v1 branch alone would hang in
    # broadcast_from_chief waiting for a collective the chief never joins).
    # Verdict encoding: -1 invalid, 0 restore as v1, 1 restore as v2.
    if bootstrap.process_index() == 0:
        reason = validate_step_dir(target)
        verdict = -1 if reason is not None else int(
            _manifest(target).get("format") == _FORMAT_V2)
    else:
        reason, verdict = None, 0  # placeholder; chief's value wins below
    if jax.process_count() > 1:
        from tpu_dist.parallel.collectives import broadcast_from_chief

        verdict = int(broadcast_from_chief(np.int64(verdict)))
    if verdict < 0:
        raise ValueError(
            f"checkpoint step {step} at {target} failed validation"
            + (f": {reason}" if reason else "")
            + "; refusing to restore from an incomplete checkpoint")
    is_v2 = bool(verdict)
    if is_v2:
        # v2 (sharded) lives on a shared FS by contract: every process
        # assembles directly from the shard files — no broadcast needed.
        arrays = {k: assemble()
                  for k, assemble in _iter_sharded_leaves(target)}
        host_template = jax.tree_util.tree_map(_placeholder, template)
        restored = _unflatten_into(host_template, arrays)
        logger.info("restored sharded checkpoint step %d from %s",
                    step, target)
        return restored, step
    # The template's VALUES are never read — the chief overwrites every leaf
    # from the npz and peers receive the broadcast — so sharded leaves (a TP
    # job's live variables) become zero placeholders of their GLOBAL shape
    # rather than paying a cross-process allgather per leaf.
    host_template = jax.tree_util.tree_map(_placeholder, template)
    if bootstrap.process_index() == 0:
        with np.load(target / _ARRAYS) as z:
            arrays = {k: z[k] for k in z.files}
        restored = _unflatten_into(host_template, arrays)
    else:
        # Non-chief processes skip the (possibly shared-FS) read entirely;
        # they receive process 0's bytes in the broadcast below.
        restored = host_template
    from tpu_dist.parallel.collectives import broadcast_from_chief

    restored = broadcast_from_chief(restored)
    logger.info("restored checkpoint step %d from %s", step, target)
    return restored, step


def restore_model(directory: str | os.PathLike, model, *,
                  step: Optional[int] = None, trainer=None) -> int:
    """Restore a compiled model's training variables in place (resume).

    ``trainer`` pins which Trainer's variables receive the restored state —
    required when a Trainer other than ``model._trainer`` is driving (e.g.
    the running trainer inside ``fit(checkpoint_dir=...)``); defaults to the
    model's own trainer."""
    from tpu_dist.training.trainer import Trainer

    if trainer is None:
        if model._trainer is None:
            model._trainer = Trainer(model)
        trainer = model._trainer
    trainer.ensure_variables()
    v = trainer.variables
    template = {k: v[k] for k in ("params", "state", "opt") if k in v}
    host, step = restore(directory, template, step=step)
    # Strategy-owned placement: mirrored on a data mesh, Megatron shards
    # under a 'model' axis — a TP job must NOT come back replicated (it
    # would multiply per-device param+moment memory by the model-axis size
    # and force a reshard on the first step).
    placed = trainer.strategy.place_variables(host["params"], host,
                                              broadcast=False)
    for k in template:
        v[k] = placed[k]
    return step
