"""Training-integrity guard: anomaly detection, SDC audits, rollback-replay.

Crash-shaped failures (worker death, torn writes, preemption) are covered by
:mod:`tpu_dist.resilience`; the failures that burn the most accelerator-hours
at pod scale are SEMANTIC — a NaN loss, an exploding gradient, or a silent
bit-flip on one replica that crashes nothing and quietly poisons every
subsequent checkpoint. This module is the detection-and-recovery layer that
makes the previously landed recovery paths *trigger themselves*:

**In-step health vector.** :func:`health_summary` folds three scalars —
non-finite count, global grad-norm², update-norm² — into the compiled train
step itself (:meth:`Trainer._pure_step` calls it on values the step already
computes), so detection adds zero extra dispatches. The trainer hands each
execution's ``f32[3]`` health output to :meth:`IntegrityGuard.on_execution`,
which starts a NON-blocking device→host copy and inspects the *previous*
execution's vector — the same one-behind lazy-fetch discipline as
``LazyLogs``, so the dispatch pipeline never stalls on a health read.
Thresholds: any non-finite is absolute; grad-norm is judged relative to an
EMA of its own history (``spike_factor`` × EMA after ``warmup`` clean steps).

**Cross-replica SDC audit (shard-aware).** Every ``audit_every_n`` steps the
guard runs a collective-FREE compiled program (``shard_map`` over the whole
mesh, one output row per device) that checksums the parameter tree per
device: leaf bytes are bitcast to ``uint32`` and wrap-summed, giving a
``[n_devices, n_leaves]`` table. Sharded leaves are consumed SHARD-LOCALLY
(``in_specs`` taken from each leaf's live ``NamedSharding``), so TP/
pipeline/MoE params audit just like replicated ones and the program still
contains no collective. Rows are compared ON HOST through the existing
collectives seam (:func:`~tpu_dist.parallel.collectives.host_all_gather`)
*within shard groups* derived from the same shardings
(:func:`~tpu_dist.parallel.mesh.shard_groups`): devices holding the same
shard of a leaf are replicas of that shard and must agree — a TP-sharded
kernel has one group per shard (column block), a replicated bias one global
group. On mismatch the per-leaf columns name the corrupted leaf,
shard-group, device and rank. Replicated training makes this divergence
otherwise invisible — every replica keeps producing plausible losses. A
leaf shard held by only ONE device has no replica to compare against; its
singleton group is vacuously consistent (on real meshes the data axis
replicates every shard).

**Rollback-and-replay.** A confirmed anomaly raises
:class:`RollbackAndReplay`; ``Trainer.fit`` catches it, restores the last
*published* checkpoint (``latest_complete_step``/``restore_model`` — the
same path a gang restart resumes through, minus the restart), resets the
data iterator to the epoch boundary and replays. Epoch-index-derived RNG
keys and cardinality==steps_per_epoch demo datasets make the replay exact,
so a recovered run reproduces the no-fault baseline bit-for-bit. If replay
hits the same (or an earlier) anomaly again, the next rollback goes one
published checkpoint further back (``latest_complete_step(before=...)``).
A ``rollback_budget`` bounds the loop: exhausting it raises
:class:`IntegrityAbort`, which ``run_entry`` maps to
:data:`~tpu_dist.resilience.faults.EXIT_INTEGRITY` so the Supervisor
classifies the exit ``integrity_abort`` — restarts won't help, operators
should triage.

Environment knobs (read by :func:`maybe_guard_from_env`, set by the chaos
CLI for integrity plans):

==================================  =========================================
``TPU_DIST_INTEGRITY``              ``1`` arms the guard inside ``fit``
``TPU_DIST_INTEGRITY_SPIKE``        grad-norm spike factor vs EMA (default 50)
``TPU_DIST_INTEGRITY_AUDIT_N``      SDC-audit period in steps (0 = off)
``TPU_DIST_INTEGRITY_BUDGET``       rollbacks before abort (default 3)
``TPU_DIST_INTEGRITY_QUARANTINE``   ``1`` = skip-and-log a batch window that
                                    already triggered a rollback instead of
                                    re-running it (breaks exact replay
                                    parity; for data-dependent poison)
``TPU_DIST_INTEGRITY_LOSS_SCALE``   static loss scale S: grad norms are
                                    divided by S before the spike EMA, so
                                    scaled-loss training is judged in true
                                    gradient units (default 1)
``TPU_DIST_INTEGRITY_BF16_SLACK``   spike-factor multiplier applied when the
                                    param tree is low-precision (bf16/f16)
                                    — quantization makes grad norms
                                    noisier, so the threshold widens
                                    instead of false-positives (default 4)
==================================  =========================================

The module also owns the BATCH-fault seam (:func:`install_batch_fault_hook`)
through which the fault injector corrupts a target step's batch
(``nan_loss``/``grad_spike``/``corrupt_batch`` fault kinds) without touching
training code — the same hook pattern as the collectives and checkpoint
seams.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import time
from typing import Any, Optional

import jax
import numpy as np

logger = logging.getLogger("tpu_dist.integrity")

#: Fault kinds delivered through the batch seam (the injector corrupts the
#: target step's batch; detection is the health vector's job).
BATCH_FAULT_KINDS = ("nan_loss", "grad_spike", "corrupt_batch")

INTEGRITY_ENV = "TPU_DIST_INTEGRITY"
SPIKE_ENV = "TPU_DIST_INTEGRITY_SPIKE"
AUDIT_N_ENV = "TPU_DIST_INTEGRITY_AUDIT_N"
BUDGET_ENV = "TPU_DIST_INTEGRITY_BUDGET"
QUARANTINE_ENV = "TPU_DIST_INTEGRITY_QUARANTINE"
LOSS_SCALE_ENV = "TPU_DIST_INTEGRITY_LOSS_SCALE"
BF16_SLACK_ENV = "TPU_DIST_INTEGRITY_BF16_SLACK"

#: Param dtypes whose quantization noise warrants the wider
#: ``bf16_spike_slack`` threshold.
_LOW_PRECISION_DTYPES = ("bfloat16", "float16")


class RollbackAndReplay(Exception):
    """A confirmed anomaly: unwind to ``fit``'s rollback handler, restore
    the last published checkpoint, replay. Never escapes ``fit``."""

    def __init__(self, kind: str, gstep: int, **detail: Any):
        self.kind = kind
        self.gstep = int(gstep)
        self.detail = detail
        super().__init__(
            f"training-integrity anomaly {kind!r} at global step {gstep}"
            + (f" ({detail})" if detail else ""))


class IntegrityAbort(Exception):
    """Rollback budget exhausted — recovery by replay is not converging.
    Escapes ``fit``; ``run_entry`` maps it to ``EXIT_INTEGRITY``."""


# -- batch-fault seam ---------------------------------------------------------
# Module-global hook + install/fire pair, same shape as
# collectives.install_fault_hook and checkpoint.install_write_fault_hook.

_BATCH_FAULT_HOOK = None


def install_batch_fault_hook(hook):
    """Install (or, with None, remove) the batch fault hook.

    ``hook(first_gstep, k, x, y) -> (x, y)`` is called once per compiled
    execution with the window's first global step, its step count ``k`` and
    the (already device-placed) batch; it returns the batch to actually
    train on. Returns the previously installed hook.
    """
    global _BATCH_FAULT_HOOK
    prev = _BATCH_FAULT_HOOK
    _BATCH_FAULT_HOOK = hook
    return prev


def fire_batch_hook(first_gstep: int, k: int, x, y):
    """Run the installed batch hook (identity when none is installed).
    Called by the trainer hot loop right before each dispatch; the no-hook
    fast path is one global read and a compare."""
    hook = _BATCH_FAULT_HOOK
    if hook is None:
        return x, y
    return hook(first_gstep, k, x, y)


# -- in-step health vector ----------------------------------------------------

def health_summary(loss, grads, params, new_params):
    """The device-side health vector, computed INSIDE the train step.

    ``f32[3] = [nonfinite_count, grad_norm², update_norm²]`` from values the
    step already produced — no extra forward/backward work, and XLA fuses
    the reductions into the step program, so the vector costs a few scalar
    ops and one tiny output buffer. All three entries are replicated
    scalars (grads are all-reduced, params mirrored), so the trainer's
    lazy fetch moves 12 bytes.
    """
    import jax.numpy as jnp

    def _sumsq(tree):
        total = jnp.float32(0.0)
        for leaf in jax.tree_util.tree_leaves(tree):
            total = total + jnp.sum(jnp.square(
                jnp.asarray(leaf, jnp.float32)))
        return total

    gsq = _sumsq(grads)
    usq = _sumsq(jax.tree_util.tree_map(
        lambda a, b: jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32),
        new_params, params))
    bad = ((~jnp.isfinite(jnp.asarray(loss, jnp.float32))).astype(jnp.float32)
           + (~jnp.isfinite(gsq)).astype(jnp.float32)
           + (~jnp.isfinite(usq)).astype(jnp.float32))
    return jnp.stack([bad, gsq, usq])


def reduce_window_health(healths):
    """Fold a scanned execution's ``[k, 3]`` per-step health stack into one
    ``f32[3]``: non-finite counts sum; norms take the window max (a single
    spiked step must survive the fold)."""
    import jax.numpy as jnp

    return jnp.stack([healths[:, 0].sum(),
                      healths[:, 1].max(),
                      healths[:, 2].max()])


# -- cross-replica SDC audit --------------------------------------------------

def build_audit_checksum(mesh, leaf_shapes_dtypes, leaf_specs=None):
    """The compiled per-device checksum program for one param-tree layout.

    A ``shard_map`` over the WHOLE mesh: every device checksums its own
    local copy — or, for sharded leaves, its own SHARD — of each leaf
    (bytes bitcast to ``uint32``, wrap-summed) and contributes one
    ``[1, n_leaves]`` row; rows concatenate across devices to the global
    ``[n_devices, n_leaves]`` table. ``leaf_specs`` carries one
    ``PartitionSpec`` per leaf taken from the live arrays' shardings
    (``None`` = all replicated, the pre-shard-aware behavior); devices
    holding the same shard produce equal checksums, which is exactly the
    shard-group comparison :meth:`IntegrityGuard.audit` runs on host. No
    collective appears in the program — so its baselined comm payload is
    exactly 0 bytes, replicated and sharded alike.
    """
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    names = tuple(mesh.axis_names)
    n_leaves = len(leaf_shapes_dtypes)
    if leaf_specs is None:
        leaf_specs = tuple(P() for _ in range(n_leaves))

    def per_device(*leaves):
        sums = []
        for leaf in leaves:
            flat = jnp.ravel(jnp.asarray(leaf, jnp.float32))
            sums.append(jnp.sum(
                jax.lax.bitcast_convert_type(flat, jnp.uint32),
                dtype=jnp.uint32))
        return jnp.stack(sums).reshape(1, n_leaves)

    shmapped = shard_map(per_device, mesh=mesh,
                         in_specs=tuple(leaf_specs),
                         out_specs=P(names), check_rep=False)
    return jax.jit(shmapped)


def _leaf_audit_spec(leaf, mesh):
    """The audit ``in_spec`` for one live leaf: its own PartitionSpec when
    it is a NamedSharding over the audited mesh, else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    sh = getattr(leaf, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh == mesh:
        return PartitionSpec(*sh.spec)
    return PartitionSpec()


def _leaf_shard_groups(leaf, mesh):
    """Shard groups (lists of checksum-table row indices) for one leaf —
    one global group when the leaf is not sharded over this mesh."""
    from jax.sharding import NamedSharding

    from tpu_dist.parallel import mesh as mesh_lib

    sh = getattr(leaf, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh == mesh:
        return mesh_lib.shard_groups(sh, leaf.shape)
    return [list(range(mesh.devices.size))]


def host_leaf_checksums(arrays: dict) -> dict:
    """Host-side mirror of :func:`build_audit_checksum`'s per-leaf math:
    ``{key: uint32 wrap-sum of the f32 bytes}`` for a ``{key: ndarray}``
    mapping.

    Same bit pattern as the compiled audit (f32 ravel → uint32 bitcast →
    wrap-sum), but in numpy so the PS server can checksum its authoritative
    params per apply-epoch and workers can verify pulled snapshots WITHOUT
    a device program — the PS audit runs where the data already is, on
    host, between transport and training.
    """
    out = {}
    for key in sorted(arrays):
        flat = np.ravel(np.asarray(arrays[key], np.float32))
        out[key] = int(flat.view(np.uint32).sum(dtype=np.uint32))
    return out


def verify_pull_checksums(arrays: dict, manifest: dict) -> None:
    """Worker-side transport audit: raise :class:`IntegrityAbort` when a
    pulled parameter snapshot does not match the checksums its manifest
    published. The server checksummed these exact bytes at publish time, so
    a mismatch is transport/storage SDC — the one corruption class the
    server-side apply-epoch audit cannot see."""
    expected = manifest.get("checksums") or {}
    if not expected:
        return
    missing = sorted(k for k in expected if k not in arrays)
    if missing:
        raise IntegrityAbort(
            f"PS pull: snapshot v{manifest.get('version')} is missing "
            f"published leaves {missing[:4]}")
    live = host_leaf_checksums({k: arrays[k] for k in expected})
    bad = sorted(k for k in expected if live[k] != int(expected[k]))
    if bad:
        raise IntegrityAbort(
            f"PS pull: checksum mismatch on leaves {bad[:4]} of snapshot "
            f"v{manifest.get('version')} — corruption between server "
            "publish and worker read")


#: Unsigned view dtype per element width for the dtype-aware bit flip.
_FLIP_VIEWS = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def flip_param_bit(variables: dict, *, replica: int, bit: int = 22,
                   leaf: int = 0) -> dict:
    """Inject silent data corruption: XOR one bit of element 0 of parameter
    leaf ``leaf`` (flatten order), on ONE device's copy/shard only.

    Used by the ``bitflip`` fault kind (``bitflip@stepN:leafK:replicaR``).
    Rebuilds the array from per-device local buffers via
    ``jax.make_array_from_single_device_arrays`` so exactly one device's
    data diverges — the SDC model: nothing crashes, the loss stays
    plausible, only a cross-replica checksum can see it. For a SHARDED
    leaf the flip lands in that one device's shard, so the audit must
    localize it to the right shard group. In multi-process runs the caller
    has already matched the fault's rank to this process, so the flip
    lands on local replica 0; single-process multi-device runs use
    ``replica`` as the device position (sorted by device id, which matches
    the mesh row order the audit reports).

    The flip is dtype-aware: ``bit`` is taken modulo the element width, on
    an unsigned view of matching width — so the default ``bit=22`` hits
    f32 mantissa bit 22 and bf16 bit ``22 % 16 == 6``, the TOP mantissa
    bit (a ~2^-1 relative change). A byte-wise flip here would land on a
    numerically invisible low bf16 mantissa bit. Returns a description of
    what was flipped — including the ``effective_bit`` — for the event
    log.
    """
    params = variables["params"]
    flat, treedef = jax.tree_util.tree_flatten(params)
    paths = jax.tree_util.tree_flatten_with_path(params)[0]
    leaf_idx = int(leaf) % len(flat)
    arr = flat[leaf_idx]
    leaf_name = jax.tree_util.keystr(paths[leaf_idx][0])
    shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    datas = [np.array(s.data) for s in shards]
    idx = 0 if jax.process_count() > 1 else replica % len(datas)
    buf = datas[idx].reshape(-1)
    width = buf.dtype.itemsize * 8
    view = buf.view(_FLIP_VIEWS[buf.dtype.itemsize])
    eff_bit = int(bit) % width
    view[0] ^= view.dtype.type(1 << eff_bit)
    rebuilt = jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding,
        [jax.device_put(d, s.device) for d, s in zip(datas, shards)])
    flat[leaf_idx] = rebuilt
    variables["params"] = jax.tree_util.tree_unflatten(treedef, flat)
    return {"leaf": leaf_name, "leaf_index": leaf_idx, "replica": idx,
            "device": int(shards[idx].device.id), "bit": int(bit),
            "effective_bit": eff_bit, "dtype": str(buf.dtype)}


# -- the guard ----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    spike_factor: float = 50.0     # grad-norm anomaly = factor x EMA
    ema_decay: float = 0.9
    warmup_steps: int = 3          # clean executions before spike checks arm
    audit_every_n: int = 0         # SDC-audit period in global steps; 0 = off
    rollback_budget: int = 3       # rollbacks before IntegrityAbort
    quarantine: bool = False       # skip-and-log windows that caused rollback
    loss_scale: float = 1.0        # grad norms divided by this before the EMA
    bf16_spike_slack: float = 4.0  # spike-factor multiplier on bf16/f16 params

    @classmethod
    def from_env(cls) -> "IntegrityConfig":
        def _f(name, default):
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        return cls(
            spike_factor=_f(SPIKE_ENV, 50.0),
            audit_every_n=int(_f(AUDIT_N_ENV, 0)),
            rollback_budget=int(_f(BUDGET_ENV, 3)),
            quarantine=os.environ.get(QUARANTINE_ENV) == "1",
            loss_scale=_f(LOSS_SCALE_ENV, 1.0),
            bf16_spike_slack=_f(BF16_SLACK_ENV, 4.0),
        )


class IntegrityGuard:
    """Per-fit integrity state machine, driven by the trainer hot loop.

    NOT a callback on purpose: callbacks with batch hooks force the trainer
    into per-step blocking loss fetches (``eager_loss``); the guard instead
    rides the loop directly and reads health one execution behind, so an
    armed guard costs the hot path one method call and zero added syncs.
    """

    def __init__(self, config: Optional[IntegrityConfig] = None):
        self.cfg = config or IntegrityConfig()
        self._strategy = None
        self.checkpoint_dir: Optional[str] = None
        #: (first_gstep, k, device f32[3]) of the newest execution — its
        #: host copy is in flight; it is judged when the NEXT execution
        #: lands (or at flush()).
        self._pending: Optional[tuple] = None
        self._ema: Optional[float] = None
        self._ema_n = 0
        self._rollbacks = 0
        self._last_anomaly_gstep: Optional[int] = None
        self._last_restored: Optional[int] = None
        self.quarantined: set = set()
        self._audit_fn = None
        self._audit_key = None
        self._audit_paths = None
        self._audit_groups = None
        self._audit_devices = None
        #: Low-precision param trees get the bf16_spike_slack threshold;
        #: detected once from the first execution's params.
        self._low_precision = False
        self._lp_known = False

    def bind(self, strategy, *, checkpoint_dir=None) -> "IntegrityGuard":
        self._strategy = strategy
        if checkpoint_dir is not None:
            self.checkpoint_dir = os.fspath(checkpoint_dir)
        return self

    # -- hot-loop surface ----------------------------------------------------

    def on_execution(self, first_gstep: int, k: int, health, params) -> None:
        """Called once per compiled execution, right after dispatch.

        Starts the new health vector's async device→host copy, then judges
        the PREVIOUS execution's (already-arrived) vector — one execution
        of detection lag buys a hot loop with no blocking fetch. Runs the
        SDC audit when the period is due.
        """
        prev = self._pending
        self._pending = (first_gstep, k, health)
        try:
            health.copy_to_host_async()
        except AttributeError:  # plain numpy in unit tests
            pass
        if params is not None and not self._lp_known:
            self._lp_known = True
            self._low_precision = any(
                str(getattr(l, "dtype", "")) in _LOW_PRECISION_DTYPES
                for l in jax.tree_util.tree_leaves(params))
        if prev is not None:
            self._judge(*prev)
        n = self.cfg.audit_every_n
        if n and first_gstep and first_gstep % n == 0 and params is not None:
            self.audit(params, gstep=first_gstep)

    def flush(self) -> None:
        """Judge the in-flight health vector NOW — called at the epoch
        boundary BEFORE callbacks run, so a poisoned final step can never
        reach ModelCheckpoint's epoch-end save."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._judge(*prev)

    def should_skip(self, first_gstep: int, k: int) -> bool:
        """Quarantine check: True when this window already caused a
        rollback and the config says replaying it would just re-poison."""
        if not self.cfg.quarantine or not self.quarantined:
            return False
        return any(first_gstep + i in self.quarantined for i in range(k))

    # -- rollback bookkeeping (trainer-facing) -------------------------------

    def rollback_plan(self, rb: RollbackAndReplay) -> Optional[int]:
        """The ``before=`` bound for ``latest_complete_step``: None for a
        first-time anomaly (restore the newest published step); the last
        restored step when replay already hit this anomaly again without
        making progress — then the next restore must go strictly older."""
        if (self._last_anomaly_gstep is not None
                and rb.gstep <= self._last_anomaly_gstep
                and self._last_restored is not None):
            return self._last_restored
        return None

    def note_rollback(self, rb: RollbackAndReplay,
                      restored: Optional[int]) -> None:
        self._last_anomaly_gstep = rb.gstep
        self._last_restored = restored
        self._pending = None  # pre-rollback health is stale

    # -- judgement -----------------------------------------------------------

    def _judge(self, first_gstep: int, k: int, health) -> None:
        h = np.asarray(health, dtype=np.float64).reshape(-1)
        nonfinite, gsq, usq = float(h[0]), float(h[1]), float(h[2])
        if (nonfinite > 0 or not math.isfinite(gsq)
                or not math.isfinite(usq)):
            self._anomaly("nan_loss", first_gstep, k,
                          nonfinite=nonfinite)
        # Loss-scaled training reports S x larger raw grad norms; dividing
        # by the static scale judges (and logs) in true gradient units.
        gnorm = (math.sqrt(max(gsq, 0.0))
                 / max(float(self.cfg.loss_scale), 1e-30))
        factor = self.cfg.spike_factor
        if self._low_precision:
            factor *= max(float(self.cfg.bf16_spike_slack), 1.0)
        if (self._ema is not None and self._ema_n >= self.cfg.warmup_steps
                and gnorm > factor * max(self._ema, 1e-12)):
            self._anomaly("grad_spike", first_gstep, k,
                          grad_norm=round(gnorm, 6),
                          ema=round(self._ema, 6),
                          factor=round(factor, 6))
        d = self.cfg.ema_decay
        self._ema = gnorm if self._ema is None else d * self._ema + (1 - d) * gnorm
        self._ema_n += 1

    def _anomaly(self, kind: str, first_gstep: int, k: int,
                 **detail: Any) -> None:
        from tpu_dist.observe import metrics as metrics_lib
        from tpu_dist.resilience import events

        metrics_lib.inc("integrity.anomalies")
        events.maybe_log("integrity_anomaly", kind=kind, step=first_gstep,
                         window=k, attempt=events.current_attempt(), **detail)
        logger.warning("integrity anomaly %r at global step %d (+%d): %s",
                       kind, first_gstep, k, detail)
        self._rollbacks += 1
        if self.cfg.quarantine:
            self.quarantined.update(range(first_gstep, first_gstep + k))
        if self._rollbacks > self.cfg.rollback_budget:
            events.maybe_log("integrity_budget_exhausted", kind=kind,
                             step=first_gstep,
                             rollbacks=self._rollbacks - 1,
                             budget=self.cfg.rollback_budget)
            raise IntegrityAbort(
                f"rollback budget ({self.cfg.rollback_budget}) exhausted; "
                f"latest anomaly {kind!r} at step {first_gstep}")
        raise RollbackAndReplay(kind, first_gstep, **detail)

    # -- SDC audit -----------------------------------------------------------

    def audit(self, params, *, gstep: int) -> bool:
        """One shard-group checksum compare; True when every group agrees.

        Devices holding the same shard of a leaf (per its live
        NamedSharding) are replicas of that shard and must produce equal
        checksums; replicated leaves form one global group — so the audit
        covers TP/pipeline/MoE param trees, not just mirrored ones.
        Disagreement is a confirmed SDC anomaly: the per-leaf "bisection"
        names the corrupted leaf, shard-group, device and rank from the
        already-computed table (no extra dispatch), then the rollback
        machinery takes over.
        """
        mesh = getattr(self._strategy, "mesh", None)
        if mesh is None:
            return True
        t0 = time.perf_counter()
        flat_with_paths = jax.tree_util.tree_flatten_with_path(params)[0]
        leaves = [leaf for _, leaf in flat_with_paths]
        specs = tuple(_leaf_audit_spec(leaf, mesh) for leaf in leaves)
        key = tuple((tuple(l.shape), str(l.dtype), str(s))
                    for l, s in zip(leaves, specs))
        if self._audit_fn is None or self._audit_key != key:
            self._audit_fn = build_audit_checksum(mesh, key, specs)
            self._audit_key = key
            self._audit_paths = [jax.tree_util.keystr(p)
                                 for p, _ in flat_with_paths]
            self._audit_groups = [_leaf_shard_groups(leaf, mesh)
                                  for leaf in leaves]
            self._audit_devices = [(int(d.id), int(d.process_index))
                                   for d in mesh.devices.flat]
        table = self._audit_fn(*leaves)
        rows = self._host_rows(table)
        dt = time.perf_counter() - t0
        from tpu_dist.observe import metrics as metrics_lib

        metrics_lib.observe_value("integrity.audit_s", dt)
        # Bisection: name every (device, leaf) cell that deviates from its
        # SHARD GROUP's majority value. A group with no strict majority
        # (e.g. one corrupted member out of two) localizes the mismatch to
        # the group, so every member is named.
        culprits = []
        for col, groups in enumerate(self._audit_groups):
            for gi, members in enumerate(groups):
                vals = rows[members, col]
                if bool((vals == vals[0]).all()):
                    continue
                uniq, counts = np.unique(vals, return_counts=True)
                if int(counts.max()) * 2 > len(members):
                    majority = uniq[int(np.argmax(counts))]
                    bad = [m for m, v in zip(members, vals)
                           if v != majority]
                else:
                    bad = list(members)
                for row in bad:
                    dev_id, rank = self._audit_devices[row]
                    culprits.append({"replica": int(row),
                                     "device": dev_id,
                                     "rank": rank,
                                     "shard_group": gi,
                                     "leaf": self._audit_paths[col]})
        if not culprits:
            return True
        from tpu_dist.resilience import events

        events.maybe_log("integrity_sdc", step=gstep, culprits=culprits,
                         attempt=events.current_attempt())
        logger.warning("SDC audit mismatch at step %d: %s", gstep, culprits)
        self._anomaly("sdc", gstep, 1, culprits=culprits)
        return False

    @staticmethod
    def _host_rows(table) -> np.ndarray:
        """The global ``[n_devices, n_leaves]`` checksum table on host,
        exchanged through the collectives seam: each process contributes
        its addressable rows and ``host_all_gather`` stacks them (a
        single-process run gathers trivially but still rides the seam, so
        the audit's comm accounting is uniform)."""
        shards = sorted(table.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        from tpu_dist.parallel.collectives import host_all_gather

        gathered = np.asarray(host_all_gather(local))
        return gathered.reshape(-1, local.shape[-1])


def maybe_guard_from_env() -> Optional[IntegrityGuard]:
    """An :class:`IntegrityGuard` when ``$TPU_DIST_INTEGRITY=1`` (set by the
    chaos CLI for integrity fault plans, or by an operator), else None —
    an unarmed fit pays one env read."""
    if os.environ.get(INTEGRITY_ENV) != "1":
        return None
    return IntegrityGuard(IntegrityConfig.from_env())
