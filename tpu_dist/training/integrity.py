"""Training-integrity guard: anomaly detection, SDC audits, rollback-replay.

Crash-shaped failures (worker death, torn writes, preemption) are covered by
:mod:`tpu_dist.resilience`; the failures that burn the most accelerator-hours
at pod scale are SEMANTIC — a NaN loss, an exploding gradient, or a silent
bit-flip on one replica that crashes nothing and quietly poisons every
subsequent checkpoint. This module is the detection-and-recovery layer that
makes the previously landed recovery paths *trigger themselves*:

**In-step health vector.** :func:`health_summary` folds three scalars —
non-finite count, global grad-norm², update-norm² — into the compiled train
step itself (:meth:`Trainer._pure_step` calls it on values the step already
computes), so detection adds zero extra dispatches. The trainer hands each
execution's ``f32[3]`` health output to :meth:`IntegrityGuard.on_execution`,
which starts a NON-blocking device→host copy and inspects the *previous*
execution's vector — the same one-behind lazy-fetch discipline as
``LazyLogs``, so the dispatch pipeline never stalls on a health read.
Thresholds: any non-finite is absolute; grad-norm is judged relative to an
EMA of its own history (``spike_factor`` × EMA after ``warmup`` clean steps).

**Cross-replica SDC audit.** Every ``audit_every_n`` steps the guard runs a
collective-FREE compiled program (``shard_map`` over the whole mesh, inputs
replicated, one output row per device) that checksums the parameter tree
per replica: leaf bytes are bitcast to ``uint32`` and wrap-summed, giving a
``[n_devices, n_leaves]`` table. Rows are compared ON HOST through the
existing collectives seam (:func:`~tpu_dist.parallel.collectives.
host_all_gather`): the common case is one equality check of the per-device
totals; on mismatch the per-leaf columns name the corrupted leaf and
replica/rank. Replicated training makes this divergence otherwise
invisible — every replica keeps producing plausible losses. Tensor-/
pipeline-/expert-parallel meshes are skipped (params are not replicated
per-device there; see ROADMAP open items).

**Rollback-and-replay.** A confirmed anomaly raises
:class:`RollbackAndReplay`; ``Trainer.fit`` catches it, restores the last
*published* checkpoint (``latest_complete_step``/``restore_model`` — the
same path a gang restart resumes through, minus the restart), resets the
data iterator to the epoch boundary and replays. Epoch-index-derived RNG
keys and cardinality==steps_per_epoch demo datasets make the replay exact,
so a recovered run reproduces the no-fault baseline bit-for-bit. If replay
hits the same (or an earlier) anomaly again, the next rollback goes one
published checkpoint further back (``latest_complete_step(before=...)``).
A ``rollback_budget`` bounds the loop: exhausting it raises
:class:`IntegrityAbort`, which ``run_entry`` maps to
:data:`~tpu_dist.resilience.faults.EXIT_INTEGRITY` so the Supervisor
classifies the exit ``integrity_abort`` — restarts won't help, operators
should triage.

Environment knobs (read by :func:`maybe_guard_from_env`, set by the chaos
CLI for integrity plans):

==================================  =========================================
``TPU_DIST_INTEGRITY``              ``1`` arms the guard inside ``fit``
``TPU_DIST_INTEGRITY_SPIKE``        grad-norm spike factor vs EMA (default 50)
``TPU_DIST_INTEGRITY_AUDIT_N``      SDC-audit period in steps (0 = off)
``TPU_DIST_INTEGRITY_BUDGET``       rollbacks before abort (default 3)
``TPU_DIST_INTEGRITY_QUARANTINE``   ``1`` = skip-and-log a batch window that
                                    already triggered a rollback instead of
                                    re-running it (breaks exact replay
                                    parity; for data-dependent poison)
==================================  =========================================

The module also owns the BATCH-fault seam (:func:`install_batch_fault_hook`)
through which the fault injector corrupts a target step's batch
(``nan_loss``/``grad_spike``/``corrupt_batch`` fault kinds) without touching
training code — the same hook pattern as the collectives and checkpoint
seams.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import time
from typing import Any, Optional

import jax
import numpy as np

logger = logging.getLogger("tpu_dist.integrity")

#: Fault kinds delivered through the batch seam (the injector corrupts the
#: target step's batch; detection is the health vector's job).
BATCH_FAULT_KINDS = ("nan_loss", "grad_spike", "corrupt_batch")

INTEGRITY_ENV = "TPU_DIST_INTEGRITY"
SPIKE_ENV = "TPU_DIST_INTEGRITY_SPIKE"
AUDIT_N_ENV = "TPU_DIST_INTEGRITY_AUDIT_N"
BUDGET_ENV = "TPU_DIST_INTEGRITY_BUDGET"
QUARANTINE_ENV = "TPU_DIST_INTEGRITY_QUARANTINE"


class RollbackAndReplay(Exception):
    """A confirmed anomaly: unwind to ``fit``'s rollback handler, restore
    the last published checkpoint, replay. Never escapes ``fit``."""

    def __init__(self, kind: str, gstep: int, **detail: Any):
        self.kind = kind
        self.gstep = int(gstep)
        self.detail = detail
        super().__init__(
            f"training-integrity anomaly {kind!r} at global step {gstep}"
            + (f" ({detail})" if detail else ""))


class IntegrityAbort(Exception):
    """Rollback budget exhausted — recovery by replay is not converging.
    Escapes ``fit``; ``run_entry`` maps it to ``EXIT_INTEGRITY``."""


# -- batch-fault seam ---------------------------------------------------------
# Module-global hook + install/fire pair, same shape as
# collectives.install_fault_hook and checkpoint.install_write_fault_hook.

_BATCH_FAULT_HOOK = None


def install_batch_fault_hook(hook):
    """Install (or, with None, remove) the batch fault hook.

    ``hook(first_gstep, k, x, y) -> (x, y)`` is called once per compiled
    execution with the window's first global step, its step count ``k`` and
    the (already device-placed) batch; it returns the batch to actually
    train on. Returns the previously installed hook.
    """
    global _BATCH_FAULT_HOOK
    prev = _BATCH_FAULT_HOOK
    _BATCH_FAULT_HOOK = hook
    return prev


def fire_batch_hook(first_gstep: int, k: int, x, y):
    """Run the installed batch hook (identity when none is installed).
    Called by the trainer hot loop right before each dispatch; the no-hook
    fast path is one global read and a compare."""
    hook = _BATCH_FAULT_HOOK
    if hook is None:
        return x, y
    return hook(first_gstep, k, x, y)


# -- in-step health vector ----------------------------------------------------

def health_summary(loss, grads, params, new_params):
    """The device-side health vector, computed INSIDE the train step.

    ``f32[3] = [nonfinite_count, grad_norm², update_norm²]`` from values the
    step already produced — no extra forward/backward work, and XLA fuses
    the reductions into the step program, so the vector costs a few scalar
    ops and one tiny output buffer. All three entries are replicated
    scalars (grads are all-reduced, params mirrored), so the trainer's
    lazy fetch moves 12 bytes.
    """
    import jax.numpy as jnp

    def _sumsq(tree):
        total = jnp.float32(0.0)
        for leaf in jax.tree_util.tree_leaves(tree):
            total = total + jnp.sum(jnp.square(
                jnp.asarray(leaf, jnp.float32)))
        return total

    gsq = _sumsq(grads)
    usq = _sumsq(jax.tree_util.tree_map(
        lambda a, b: jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32),
        new_params, params))
    bad = ((~jnp.isfinite(jnp.asarray(loss, jnp.float32))).astype(jnp.float32)
           + (~jnp.isfinite(gsq)).astype(jnp.float32)
           + (~jnp.isfinite(usq)).astype(jnp.float32))
    return jnp.stack([bad, gsq, usq])


def reduce_window_health(healths):
    """Fold a scanned execution's ``[k, 3]`` per-step health stack into one
    ``f32[3]``: non-finite counts sum; norms take the window max (a single
    spiked step must survive the fold)."""
    import jax.numpy as jnp

    return jnp.stack([healths[:, 0].sum(),
                      healths[:, 1].max(),
                      healths[:, 2].max()])


# -- cross-replica SDC audit --------------------------------------------------

def build_audit_checksum(mesh, leaf_shapes_dtypes):
    """The compiled per-replica checksum program for one param-tree layout.

    A ``shard_map`` over the WHOLE mesh with replicated inputs: every device
    checksums its own local copy of each leaf (bytes bitcast to ``uint32``,
    wrap-summed) and contributes one ``[1, n_leaves]`` row; rows concatenate
    across devices to the global ``[n_devices, n_leaves]`` table. No
    collective appears in the program — the comparison happens on host —
    so its baselined comm payload is exactly 0 bytes.
    """
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    names = tuple(mesh.axis_names)
    n_leaves = len(leaf_shapes_dtypes)

    def per_device(*leaves):
        sums = []
        for leaf in leaves:
            flat = jnp.ravel(jnp.asarray(leaf, jnp.float32))
            sums.append(jnp.sum(
                jax.lax.bitcast_convert_type(flat, jnp.uint32),
                dtype=jnp.uint32))
        return jnp.stack(sums).reshape(1, n_leaves)

    shmapped = shard_map(per_device, mesh=mesh,
                         in_specs=tuple(P() for _ in range(n_leaves)),
                         out_specs=P(names), check_rep=False)
    return jax.jit(shmapped)


def flip_param_bit(variables: dict, *, replica: int, bit: int = 22) -> dict:
    """Inject silent data corruption: XOR one mantissa bit of element 0 of
    the first parameter leaf, on ONE replica's copy only.

    Used by the ``bitflip`` fault kind. Rebuilds the (nominally replicated)
    array from per-device buffers via
    ``jax.make_array_from_single_device_arrays`` so exactly one device's
    copy diverges — the SDC model: nothing crashes, the loss stays
    plausible, only a cross-replica checksum can see it. In multi-process
    runs the caller has already matched the fault's rank to this process,
    so the flip lands on local replica 0; single-process multi-device runs
    use ``replica`` as the local device index. Returns a description of
    what was flipped (leaf name, replica, bit) for the event log.
    """
    params = variables["params"]
    flat, treedef = jax.tree_util.tree_flatten(params)
    paths = jax.tree_util.tree_flatten_with_path(params)[0]
    arr = flat[0]
    leaf_name = jax.tree_util.keystr(paths[0][0])
    shards = sorted(arr.addressable_shards, key=lambda s: s.device.id)
    datas = [np.array(s.data) for s in shards]
    idx = 0 if jax.process_count() > 1 else replica % len(datas)
    buf = datas[idx].reshape(-1)
    if buf.dtype == np.float32:
        view = buf.view(np.uint32)
        view[0] ^= np.uint32(1 << bit)
    else:  # generic fallback: flip a low bit of the first byte
        view = buf.view(np.uint8)
        view[0] ^= np.uint8(1 << (bit % 8))
    rebuilt = jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding,
        [jax.device_put(d.reshape(arr.shape), s.device)
         for d, s in zip(datas, shards)])
    flat[0] = rebuilt
    variables["params"] = jax.tree_util.tree_unflatten(treedef, flat)
    return {"leaf": leaf_name, "replica": idx, "bit": bit}


# -- the guard ----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    spike_factor: float = 50.0     # grad-norm anomaly = factor x EMA
    ema_decay: float = 0.9
    warmup_steps: int = 3          # clean executions before spike checks arm
    audit_every_n: int = 0         # SDC-audit period in global steps; 0 = off
    rollback_budget: int = 3       # rollbacks before IntegrityAbort
    quarantine: bool = False       # skip-and-log windows that caused rollback

    @classmethod
    def from_env(cls) -> "IntegrityConfig":
        def _f(name, default):
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        return cls(
            spike_factor=_f(SPIKE_ENV, 50.0),
            audit_every_n=int(_f(AUDIT_N_ENV, 0)),
            rollback_budget=int(_f(BUDGET_ENV, 3)),
            quarantine=os.environ.get(QUARANTINE_ENV) == "1",
        )


class IntegrityGuard:
    """Per-fit integrity state machine, driven by the trainer hot loop.

    NOT a callback on purpose: callbacks with batch hooks force the trainer
    into per-step blocking loss fetches (``eager_loss``); the guard instead
    rides the loop directly and reads health one execution behind, so an
    armed guard costs the hot path one method call and zero added syncs.
    """

    def __init__(self, config: Optional[IntegrityConfig] = None):
        self.cfg = config or IntegrityConfig()
        self._strategy = None
        self.checkpoint_dir: Optional[str] = None
        #: (first_gstep, k, device f32[3]) of the newest execution — its
        #: host copy is in flight; it is judged when the NEXT execution
        #: lands (or at flush()).
        self._pending: Optional[tuple] = None
        self._ema: Optional[float] = None
        self._ema_n = 0
        self._rollbacks = 0
        self._last_anomaly_gstep: Optional[int] = None
        self._last_restored: Optional[int] = None
        self.quarantined: set = set()
        self._audit_fn = None
        self._audit_key = None
        self._audit_paths = None

    def bind(self, strategy, *, checkpoint_dir=None) -> "IntegrityGuard":
        self._strategy = strategy
        if checkpoint_dir is not None:
            self.checkpoint_dir = os.fspath(checkpoint_dir)
        return self

    # -- hot-loop surface ----------------------------------------------------

    def on_execution(self, first_gstep: int, k: int, health, params) -> None:
        """Called once per compiled execution, right after dispatch.

        Starts the new health vector's async device→host copy, then judges
        the PREVIOUS execution's (already-arrived) vector — one execution
        of detection lag buys a hot loop with no blocking fetch. Runs the
        SDC audit when the period is due.
        """
        prev = self._pending
        self._pending = (first_gstep, k, health)
        try:
            health.copy_to_host_async()
        except AttributeError:  # plain numpy in unit tests
            pass
        if prev is not None:
            self._judge(*prev)
        n = self.cfg.audit_every_n
        if n and first_gstep and first_gstep % n == 0 and params is not None:
            self.audit(params, gstep=first_gstep)

    def flush(self) -> None:
        """Judge the in-flight health vector NOW — called at the epoch
        boundary BEFORE callbacks run, so a poisoned final step can never
        reach ModelCheckpoint's epoch-end save."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._judge(*prev)

    def should_skip(self, first_gstep: int, k: int) -> bool:
        """Quarantine check: True when this window already caused a
        rollback and the config says replaying it would just re-poison."""
        if not self.cfg.quarantine or not self.quarantined:
            return False
        return any(first_gstep + i in self.quarantined for i in range(k))

    # -- rollback bookkeeping (trainer-facing) -------------------------------

    def rollback_plan(self, rb: RollbackAndReplay) -> Optional[int]:
        """The ``before=`` bound for ``latest_complete_step``: None for a
        first-time anomaly (restore the newest published step); the last
        restored step when replay already hit this anomaly again without
        making progress — then the next restore must go strictly older."""
        if (self._last_anomaly_gstep is not None
                and rb.gstep <= self._last_anomaly_gstep
                and self._last_restored is not None):
            return self._last_restored
        return None

    def note_rollback(self, rb: RollbackAndReplay,
                      restored: Optional[int]) -> None:
        self._last_anomaly_gstep = rb.gstep
        self._last_restored = restored
        self._pending = None  # pre-rollback health is stale

    # -- judgement -----------------------------------------------------------

    def _judge(self, first_gstep: int, k: int, health) -> None:
        h = np.asarray(health, dtype=np.float64).reshape(-1)
        nonfinite, gsq, usq = float(h[0]), float(h[1]), float(h[2])
        if (nonfinite > 0 or not math.isfinite(gsq)
                or not math.isfinite(usq)):
            self._anomaly("nan_loss", first_gstep, k,
                          nonfinite=nonfinite)
        gnorm = math.sqrt(max(gsq, 0.0))
        if (self._ema is not None and self._ema_n >= self.cfg.warmup_steps
                and gnorm > self.cfg.spike_factor * max(self._ema, 1e-12)):
            self._anomaly("grad_spike", first_gstep, k,
                          grad_norm=round(gnorm, 6),
                          ema=round(self._ema, 6))
        d = self.cfg.ema_decay
        self._ema = gnorm if self._ema is None else d * self._ema + (1 - d) * gnorm
        self._ema_n += 1

    def _anomaly(self, kind: str, first_gstep: int, k: int,
                 **detail: Any) -> None:
        from tpu_dist.observe import metrics as metrics_lib
        from tpu_dist.resilience import events

        metrics_lib.inc("integrity.anomalies")
        events.maybe_log("integrity_anomaly", kind=kind, step=first_gstep,
                         window=k, attempt=events.current_attempt(), **detail)
        logger.warning("integrity anomaly %r at global step %d (+%d): %s",
                       kind, first_gstep, k, detail)
        self._rollbacks += 1
        if self.cfg.quarantine:
            self.quarantined.update(range(first_gstep, first_gstep + k))
        if self._rollbacks > self.cfg.rollback_budget:
            events.maybe_log("integrity_budget_exhausted", kind=kind,
                             step=first_gstep,
                             rollbacks=self._rollbacks - 1,
                             budget=self.cfg.rollback_budget)
            raise IntegrityAbort(
                f"rollback budget ({self.cfg.rollback_budget}) exhausted; "
                f"latest anomaly {kind!r} at step {first_gstep}")
        raise RollbackAndReplay(kind, first_gstep, **detail)

    # -- SDC audit -----------------------------------------------------------

    def _auditable(self) -> bool:
        s = self._strategy
        if s is None:
            return False
        if (getattr(s, "model_parallel", False)
                or getattr(s, "pipeline_parallel", False)
                or getattr(s, "expert_parallel", False)):
            # Params are SHARDED per-device on these meshes; a per-device
            # checksum of different shards tells us nothing about SDC.
            # ROADMAP open item: shard-aware audit.
            return False
        return True

    def audit(self, params, *, gstep: int) -> bool:
        """One cross-replica checksum compare; True when replicas agree.

        Disagreement is a confirmed SDC anomaly: the per-leaf "bisection"
        names the corrupted leaf and replica from the already-computed
        table (no extra dispatch), then the rollback machinery takes over.
        """
        if not self._auditable():
            if self._audit_key != "skipped":
                self._audit_key = "skipped"
                logger.info("integrity audit skipped: params are not "
                            "replicated per-device on this mesh")
            return True
        t0 = time.perf_counter()
        flat_with_paths = jax.tree_util.tree_flatten_with_path(params)[0]
        leaves = [leaf for _, leaf in flat_with_paths]
        key = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
        if self._audit_fn is None or self._audit_key != key:
            self._audit_fn = build_audit_checksum(self._strategy.mesh, key)
            self._audit_key = key
            self._audit_paths = [jax.tree_util.keystr(p)
                                 for p, _ in flat_with_paths]
        table = self._audit_fn(*leaves)
        rows = self._host_rows(table)
        ok = bool((rows == rows[0]).all())
        dt = time.perf_counter() - t0
        from tpu_dist.observe import metrics as metrics_lib

        metrics_lib.observe_value("integrity.audit_s", dt)
        if ok:
            return True
        # Bisection: name every (replica, leaf) cell that deviates from the
        # column's majority value.
        culprits = []
        for col in range(rows.shape[1]):
            vals, counts = np.unique(rows[:, col], return_counts=True)
            majority = vals[int(np.argmax(counts))]
            for row in np.nonzero(rows[:, col] != majority)[0]:
                culprits.append({"replica": int(row),
                                 "rank": int(row) // max(
                                     1, rows.shape[0] // jax.process_count()),
                                 "leaf": self._audit_paths[col]})
        from tpu_dist.resilience import events

        events.maybe_log("integrity_sdc", step=gstep, culprits=culprits,
                         attempt=events.current_attempt())
        logger.warning("SDC audit mismatch at step %d: %s", gstep, culprits)
        self._anomaly("sdc", gstep, 1, culprits=culprits)
        return False

    @staticmethod
    def _host_rows(table) -> np.ndarray:
        """The global ``[n_devices, n_leaves]`` checksum table on host,
        exchanged through the collectives seam: each process contributes
        its addressable rows and ``host_all_gather`` stacks them (a
        single-process run gathers trivially but still rides the seam, so
        the audit's comm accounting is uniform)."""
        shards = sorted(table.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        from tpu_dist.parallel.collectives import host_all_gather

        gathered = np.asarray(host_all_gather(local))
        return gathered.reshape(-1, local.shape[-1])


def maybe_guard_from_env() -> Optional[IntegrityGuard]:
    """An :class:`IntegrityGuard` when ``$TPU_DIST_INTEGRITY=1`` (set by the
    chaos CLI for integrity fault plans, or by an operator), else None —
    an unarmed fit pays one env read."""
    if os.environ.get(INTEGRITY_ENV) != "1":
        return None
    return IntegrityGuard(IntegrityConfig.from_env())
