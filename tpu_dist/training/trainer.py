"""The training engine: jitted SPMD train step + Keras-2-style fit loop.

Re-provides the Keras trainer + distributed optimizer path (SURVEY.md D15-D17,
§3.3) the reference drives through ``model.fit(x=dataset, epochs=10,
steps_per_epoch=20)`` (tf_dist_example.py:59). The idiom shift:

TF reference                          | here
--------------------------------------|------------------------------------
tf.function traces the step once      | jax.jit compiles the WHOLE step (fwd,
(graph, Grappler, per-op kernels)     | loss, bwd, all-reduce, update) into
                                      | one XLA program — always compiled
strategy.run + PerReplica values      | one global batch array, sharded on the
                                      | mesh data axis; no per-replica values
replica_context.all_reduce(SUM) on    | nothing explicit: params are
grads (keras optimizer:151-160)       | replicated, batch is sharded, so the
                                      | loss-mean's gradient REQUIRES a
                                      | cross-replica sum — XLA's partitioner
                                      | emits the AllReduce (over ICI/DCN) and
                                      | overlaps it with compute
merge_call per-variable updates       | optimizer update fused into the step
PerReplica metric reduce on host      | metric state replicated in-program

Because the loss is the mean over the *global* (sharded) batch and parameters
are replicated, the distributed step is numerically identical to a
single-device step over the concatenated batch — the reference's verified
invariant (identical losses on every worker, SURVEY.md §3.5).

Epoch semantics are Keras-2-era (SURVEY.md D15 era note): one persistent
iterator across epochs when ``steps_per_epoch`` is set, re-created (fresh
shuffle) on exhaustion.
"""

from __future__ import annotations

import itertools
import logging
import sys
import time
from typing import Any, Optional, Sequence

import jax
import numpy as np

from tpu_dist.cluster import bootstrap
from tpu_dist.data.distribute import DistributedDataset
from tpu_dist.data.pipeline import Dataset
from tpu_dist.training.callbacks import (CallbackList, History, LazyLogs,
                                         StopTraining)
from tpu_dist.utils import profiler
from tpu_dist.utils.progbar import ProgressBar

logger = logging.getLogger("tpu_dist.trainer")


def _aux_loss_total(state_tree):
    """Sum of every state leaf keyed 'aux_loss' (model-internal auxiliary
    losses — Keras add_loss analog; see parallel/expert.py). 0.0 when the
    model declares none, so pure models trace identically."""
    import jax.numpy as jnp

    total = 0.0
    for path, leaf in jax.tree_util.tree_flatten_with_path(state_tree)[0]:
        last = path[-1] if path else None
        key = getattr(last, "key", None)
        if key == "aux_loss":
            total = total + jnp.asarray(leaf, jnp.float32)
    return total


def jnp_stack_keys(root_key, base: int, k: int):
    """[k, keydim] stacked fold_in keys for a scanned multi-step execution."""
    import jax.numpy as jnp

    return jax.vmap(lambda i: jax.random.fold_in(root_key, i))(
        base + jnp.arange(k))


def _current_job():
    """The active multi-tenant job scope — checked through sys.modules so
    a solo run that never imports :mod:`tpu_dist.jobs` pays nothing, not
    even the import (the jobs runtime's solo no-op contract)."""
    mod = sys.modules.get("tpu_dist.jobs.runtime")
    return mod.current_job() if mod is not None else None


#: Monotonic Trainer generation counter — the program-cache key component
#: that keeps one model's successive trainers (recompiles) from aliasing
#: each other's pool-cached programs.
_TRAINER_SERIALS = itertools.count()


class Trainer:
    """Owns device-resident training variables and the compiled steps."""

    def __init__(self, model):
        from tpu_dist.parallel.strategy import get_strategy

        self.model = model
        # Mesh acquisition goes through the job runtime when a job scope
        # is active: the strategy is the job's leased submesh slice, and
        # compiled programs land in the pool-owned cache (_acquire_program)
        # instead of on this instance alone.
        self._job = _current_job()
        self._serial = next(_TRAINER_SERIALS)
        if self._job is not None:
            self.strategy = model.strategy or self._job.strategy
        else:
            self.strategy = model.strategy or get_strategy()
        self.variables: Optional[dict] = None  # params/state/opt/metrics
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None
        self._iterator = None
        self._iterator_source = None
        self._iterator_kind = "device"
        self._prefetcher = None
        self._bucket_bytes = 0
        self._multi_step = None
        self._built_policy: Optional[str] = None
        self._metric_init_fn = None
        self._loss_acc_init_fn = None
        self._class_weight: Optional[dict] = None
        #: Per-dataset jittable x-batch transforms (u8-over-the-wire
        #: normalization split, data/vectorize.py) — trace-time constants
        #: of the compiled steps, so a change invalidates the cache.
        self._device_transform = None
        self._eval_transform = None

    @staticmethod
    def _transform_key(t):
        """Semantic identity for device transforms: scale transforms with
        equal (op, scale) are the same program even when each
        DistributedDataset built a fresh closure — comparing by object
        identity would re-jit the step on EVERY fit()/evaluate() call."""
        if t is None:
            return None
        op, k = getattr(t, "_op", None), getattr(t, "_scale", None)
        return ("scale", op, k) if k is not None else id(t)

    def _sync_device_transform(self, dist, *, role: str) -> None:
        """Adopt ``dist``'s device transform for the given step family,
        recompiling if it changed. Train and eval keep separate slots so a
        fit with a u8-transform training set and a plain validation set
        doesn't thrash the caches every epoch."""
        t = getattr(dist, "device_transform", None)
        if role == "train":
            if self._transform_key(t) != self._transform_key(
                    self._device_transform):
                self._device_transform = t
                self._train_step = None
                self._multi_step = None
        else:
            if self._transform_key(t) != self._transform_key(
                    self._eval_transform):
                self._eval_transform = t
                self._eval_step = None
                self._predict_fn = None

    def _maybe_invalidate_for_policy(self) -> None:
        """Drop cached compiled steps when the global mixed-precision policy
        changed after they were traced — compute_dtype() is read at trace
        time, so a stale cache would silently keep the old dtype."""
        from tpu_dist.models.policy import policy

        current = policy()
        if self._built_policy is not None and self._built_policy != current:
            logger.info("precision policy changed %s -> %s; recompiling steps",
                        self._built_policy, current)
            self._train_step = None
            self._multi_step = None
            self._eval_step = None
            self._predict_fn = None
        self._built_policy = current

    # -- variable materialization (D4: mirrored init, chief broadcast) -------

    def ensure_variables(self, seed: int = 0) -> None:
        if self.variables is not None:
            return
        carried = getattr(self.model, "_carryover", None)
        if carried is not None:
            # Weights survive a recompile (Keras semantics); optimizer slots
            # are rebuilt for the (possibly new) optimizer.
            self.model._carryover = None
            host_params = jax.tree_util.tree_map(np.asarray, carried["params"])
            host = {
                "params": host_params,
                "state": jax.tree_util.tree_map(np.asarray, carried["state"]),
                "opt": self.model.optimizer.init(host_params)
                if self.model.optimizer else (),
            }
        else:
            model_vars = self.model.init(seed)
            host = {
                "params": model_vars["params"],
                "state": model_vars["state"],
                "opt": self.model.optimizer.init(model_vars["params"])
                if self.model.optimizer else (),
            }
        # Place onto the mesh; multi-process jobs broadcast process 0's
        # values so every replica starts identical (SURVEY.md D4, §3.2).
        # The strategy owns the per-leaf policy: mirrored everywhere on a
        # data(/seq) mesh, Megatron shards for params/optimizer under a
        # 'model' axis (parallel/tensor.py).
        placed = self.strategy.place_variables(host["params"], host)
        placed["metrics"] = self._init_metric_states()
        self.variables = placed
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(
            host["params"]))
        logger.info("%s: materialized %d parameters on %d replica(s)",
                    self.model.name, n_params, self.strategy.num_replicas_in_sync)

    def _device_zero_fn(self, make_host_tree):
        """A cached no-arg jit producing ``make_host_tree()`` as replicated
        device arrays. These zero-states are re-created every epoch; building
        them host-side (``strategy.replicate``) costs ~100 ms/epoch on a
        tunneled runtime, while a compiled constant program is ~free."""
        rep = self.strategy.param_sharding()

        def zeros():
            import jax.numpy as jnp

            return jax.tree_util.tree_map(jnp.asarray, make_host_tree())

        out_sh = jax.tree_util.tree_map(lambda _: rep, jax.eval_shape(zeros))
        return jax.jit(zeros, out_shardings=out_sh)

    def _init_metric_states(self):
        if self._metric_init_fn is None:
            metrics = tuple(self.model.metrics)
            self._metric_init_fn = self._device_zero_fn(
                lambda: tuple(m.init() for m in metrics))
        return self._metric_init_fn()

    def _bounded_dispatch(self) -> bool:
        """True when in-flight compiled executions must be bounded to one.

        XLA:CPU runs every partition's thunks on one shared intra-op pool;
        with free-running async dispatch, a later execution's thunks can be
        queued ahead of an earlier execution's unfinished collective
        rendezvous and starve it — the runtime aborts the process after its
        40 s rendezvous termination timeout (observed on a 1-core host).
        Blocking on each execution's result keeps rendezvous pairs
        adjacent. The hazard is per-process (one shared pool per process),
        so this keys off LOCAL device count: multi-process CPU clusters
        with one device per process keep the pipeline, as do TPU/GPU —
        tiny steps there are dispatch-bound and pipelining is the point
        (BASELINE.md hard-part #5)."""
        return (jax.default_backend() == "cpu"
                and len(self.strategy.mesh.local_devices) > 1)

    def _init_loss_acc(self):
        if self._loss_acc_init_fn is None:
            self._loss_acc_init_fn = self._device_zero_fn(
                lambda: (np.float32(0.0), np.float32(0.0)))
        return self._loss_acc_init_fn()

    # -- compiled steps -------------------------------------------------------

    def _pure_step(self):
        """The un-jitted SPMD train step: (vars..., x, y, rng) -> (loss,
        vars...). Shared by the single-step jit and the scanned multi-step."""
        model, loss_obj, optimizer = (self.model, self.model.loss,
                                      self.model.optimizer)
        metrics = tuple(model.metrics)

        import jax.numpy as jnp

        class_weight = self._class_weight
        device_transform = self._device_transform

        def step(params, state, opt_state, metric_states, loss_acc, x, y, rng):
            if device_transform is not None:
                # The device half of the wire-dtype split (u8 arrives, scale
                # happens here) — fused by XLA into the first conv/matmul.
                x = device_transform(x)

            def loss_fn(p):
                logits, new_state = model.apply(p, state, x, training=True,
                                                rng=rng)
                # Model-internal auxiliary losses (the Keras add_loss
                # analog): any state leaf named 'aux_loss' — e.g. the MoE
                # load-balance term (parallel/expert.py, pre-scaled by the
                # layer) — joins the training objective. Metrics and
                # evaluate() keep reporting the pure task loss.
                aux = _aux_loss_total(new_state)
                if class_weight is not None:
                    # Keras class_weight semantics: scale each sample's loss
                    # contribution by its class's weight (default 1.0)
                    # before the batch-size mean. Built with per-class
                    # where() — an index table would CLAMP labels outside
                    # its range under jit, silently mis-weighting them.
                    if not jnp.issubdtype(y.dtype, jnp.integer):
                        raise ValueError(
                            "class_weight requires sparse integer labels; "
                            f"got labels of dtype {y.dtype}")
                    per = loss_obj.per_example(logits, y)
                    if per.shape != y.shape:
                        raise ValueError(
                            "class_weight requires per-example labels "
                            f"matching the loss (labels {y.shape} vs "
                            f"per-example loss {per.shape})")
                    w = jnp.ones_like(per)
                    for c, wt in class_weight.items():
                        w = jnp.where(y == c, jnp.float32(wt), w)
                    return (per * w).mean() + aux, (logits, new_state)
                return loss_obj(logits, y) + aux, (logits, new_state)

            (loss, (logits, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = optimizer.update(grads, opt_state, params)
            new_metrics = tuple(
                m.update(ms, logits, y) for m, ms in zip(metrics, metric_states))
            # Device-side epoch-loss accumulator — the epoch 'loss' reported to
            # History/callbacks is the epoch mean (Keras semantics), not the
            # final batch's sample, and accumulating on device keeps the hot
            # loop free of host syncs.
            new_acc = (loss_acc[0] + loss, loss_acc[1] + 1.0)
            # In-step integrity health vector (tpu_dist.training.integrity):
            # f32[3] from values this step already computed — a few fused
            # scalar reductions, one tiny fresh (non-donated) output, read
            # one execution behind by the guard. Always present so an armed
            # guard reuses the SAME compiled program as an unarmed fit.
            from tpu_dist.training.integrity import health_summary

            health = health_summary(loss, grads, params, new_params)
            return (loss, new_params, new_state, new_opt, new_metrics,
                    new_acc, health)

        return step

    def _pure_step_bucketed(self, bucket_bytes: int):
        """The explicit-schedule variant of :meth:`_pure_step`: forward/
        backward runs per data shard under ``shard_map`` and the gradient
        tree is reduced by :func:`~tpu_dist.parallel.collectives.
        bucketed_all_reduce` in reverse-topological size buckets, instead
        of leaving one fused end-of-step AllReduce to the XLA partitioner.
        Each bucket is an independent psum launch the latency-hiding
        scheduler can overlap with the remaining backward compute.

        Parity contract: shards are equal-sized (iter_local validates
        divisibility), so the mean-of-per-shard-means loss and the
        bucket-packed gradient reduction match the fused schedule to float
        tolerance — NOT bitwise; sums are reassociated (gated by allclose
        in benchmarks/step_bench.py and tests/test_step_perf.py).
        """
        model, loss_obj, optimizer = (self.model, self.model.loss,
                                      self.model.optimizer)
        metrics = tuple(model.metrics)

        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from tpu_dist.parallel import collectives
        from tpu_dist.parallel.mesh import get_shard_map

        class_weight = self._class_weight
        device_transform = self._device_transform
        mesh = self.strategy.mesh
        axis = self.strategy.data_axis

        def shard_body(params, state, x, y, rng):
            def loss_fn(p):
                logits, new_state = model.apply(p, state, x, training=True,
                                                rng=rng)
                aux = _aux_loss_total(new_state)
                if class_weight is not None:
                    if not jnp.issubdtype(y.dtype, jnp.integer):
                        raise ValueError(
                            "class_weight requires sparse integer labels; "
                            f"got labels of dtype {y.dtype}")
                    per = loss_obj.per_example(logits, y)
                    if per.shape != y.shape:
                        raise ValueError(
                            "class_weight requires per-example labels "
                            f"matching the loss (labels {y.shape} vs "
                            f"per-example loss {per.shape})")
                    w = jnp.ones_like(per)
                    for c, wt in class_weight.items():
                        w = jnp.where(y == c, jnp.float32(wt), w)
                    return (per * w).mean() + aux, (logits, new_state)
                return loss_obj(logits, y) + aux, (logits, new_state)

            (loss, (logits, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            loss = jax.lax.pmean(loss, axis)
            grads = collectives.bucketed_all_reduce(
                grads, axis, collectives.ReduceOp.MEAN,
                bucket_bytes=bucket_bytes)
            # Cross-replica state mean (sync-BatchNorm-like semantics for
            # stateful layers); a pure model's empty state tree is free.
            new_state = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, axis), new_state)
            return loss, grads, logits, new_state

        sm = get_shard_map()
        in_specs = (P(), P(), P(axis), P(axis), P())
        out_specs = (P(), P(), P(axis), P())
        try:
            sharded = sm(shard_body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
        except TypeError:  # pre-0.8 jax spells it check_rep
            sharded = sm(shard_body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

        def step(params, state, opt_state, metric_states, loss_acc, x, y,
                 rng):
            if device_transform is not None:
                x = device_transform(x)
            loss, grads, logits, new_state = sharded(params, state, x, y, rng)
            new_params, new_opt = optimizer.update(grads, opt_state, params)
            new_metrics = tuple(
                m.update(ms, logits, y)
                for m, ms in zip(metrics, metric_states))
            new_acc = (loss_acc[0] + loss, loss_acc[1] + 1.0)
            from tpu_dist.training.integrity import health_summary

            health = health_summary(loss, grads, params, new_params)
            return (loss, new_params, new_state, new_opt, new_metrics,
                    new_acc, health)

        return step

    def _pure_train_step(self):
        """The schedule the compiled steps build from: bucketed when the
        model compiled with ``gradient_bucket_bytes > 0``, else fused."""
        if self._bucket_bytes > 0:
            return self._pure_step_bucketed(self._bucket_bytes)
        return self._pure_step()

    def _sync_step_knobs(self) -> None:
        """Adopt the model's gradient-schedule knob; a changed bucket size
        is a trace-time property, so the compiled steps rebuild."""
        bb = int(getattr(self.model, "gradient_bucket_bytes", 0) or 0)
        if bb != self._bucket_bytes:
            self._bucket_bytes = bb
            self._train_step = None
            self._multi_step = None

    def _out_shardings(self):
        rep = self.strategy.param_sharding()

        def rep_like(tree):
            return jax.tree_util.tree_map(lambda _: rep, tree)

        v = self.variables
        acc = self._init_loss_acc()
        p_sh = self.strategy.variable_shardings(v["params"], v["params"])
        o_sh = self.strategy.variable_shardings(v["params"], v["opt"])
        return (None, p_sh, rep_like(v["state"]),
                o_sh, rep_like(v["metrics"]), rep_like(acc), rep)

    def _acquire_program(self, kind: str, builder, *variant):
        """Build — or acquire — one compiled program. Solo runs call the
        builder directly: the exact pre-jobs path. Under an active job
        scope the program lives in the pool's
        :class:`~tpu_dist.jobs.runtime.MeshRuntime` cache instead, keyed
        by job, model identity, and every trace-time dimension the
        invalidation logic tracks (policy, device transform, class
        weights) — so the pool owns its compiled-program population and
        a dimension that thrashes back becomes a cache hit, not a
        recompile."""
        if self._job is None:
            return builder()
        # The serial (not id(), which the allocator reuses) keys programs
        # to THIS trainer generation: a model recompile makes a new
        # Trainer — and its steps bake in the new optimizer/loss, so they
        # must never alias the old generation's cache entries.
        key = self._job.program_key(self.model.name, self._serial,
                                    kind, *variant)
        return self._job.runtime.cached(key, builder)

    def _train_variant(self) -> tuple:
        cw = self._class_weight
        return (self._built_policy,
                self._transform_key(self._device_transform),
                None if cw is None else tuple(sorted(cw.items())),
                self._bucket_bytes)

    def _eval_variant(self) -> tuple:
        return (self._built_policy,
                self._transform_key(self._eval_transform))

    def _build_train_step(self):
        return jax.jit(
            self._pure_train_step(),
            out_shardings=self._out_shardings(),
            donate_argnums=(0, 1, 2, 3, 4),
        )

    def _build_multi_step(self):
        """``lax.scan`` over K train steps inside ONE compiled dispatch —
        the Keras ``steps_per_execution`` knob, and the cure for
        dispatch-bound tiny steps (SURVEY.md hard-part #5): host dispatch
        cost is paid once per K steps instead of per step.

        Batches and rng keys for the K steps arrive stacked on a leading
        axis (K is a trace-time constant from the stack shape); the scan
        carries (params, state, opt, metrics, loss_acc) and the mean of the
        K losses is returned as the execution's loss.
        """
        step = self._pure_train_step()

        def one(carry, xs):
            x, y, rng = xs
            loss, *new_carry, health = step(*carry, x, y, rng)
            return tuple(new_carry), (loss, health)

        def multi(params, state, opt_state, metric_states, loss_acc,
                  xs_stack, ys_stack, rngs):
            from tpu_dist.training.integrity import reduce_window_health

            carry, (losses, healths) = jax.lax.scan(
                one, (params, state, opt_state, metric_states, loss_acc),
                (xs_stack, ys_stack, rngs))
            params, state, opt_state, metric_states, loss_acc = carry
            return (losses.mean(), params, state, opt_state, metric_states,
                    loss_acc, reduce_window_health(healths))

        return jax.jit(
            multi,
            out_shardings=self._out_shardings(),
            donate_argnums=(0, 1, 2, 3, 4),
        )

    def make_train_function(self, steps_per_execution: Optional[int] = None):
        """The compiled train step — public surface for benchmarks and custom
        loops (the Keras-2 ``make_train_function`` analog, SURVEY.md D15).

        With ``steps_per_execution`` (default: the model's compiled value) of
        1, returns the jitted single step::

            fn(params, state, opt, metrics, loss_acc, x, y, rng)
              -> (loss, params, state, opt, metrics, loss_acc, health)

        ``health`` is the in-step integrity vector (``f32[3]``, see
        :func:`tpu_dist.training.integrity.health_summary`) — custom loops
        thread ``out[1:6]`` as the next call's state and may ignore it.
        With K > 1, returns the scanned multi-step, whose ``x``/``y``/``rng``
        carry a leading K axis (stack K batches; see ``jnp_stack_keys``) and
        whose loss is the K-mean. Both donate their variable arguments —
        callers must thread the returned state into the next call.

        Always the UNWEIGHTED loss: if a prior ``fit(class_weight=...)``
        baked weights into the cached step, the step is rebuilt without
        them (weighted training is a fit-loop feature; a benchmark or
        custom loop asking for "the train step" must not inherit it
        silently).
        """
        self.ensure_variables()
        self._maybe_invalidate_for_policy()
        self._sync_step_knobs()
        if self._class_weight is not None:
            self._class_weight = None
            self._train_step = None
            self._multi_step = None
        if self._device_transform is not None:
            # Same rule as class_weight: a prior fit's dataset-specific
            # input transform (e.g. the u8 wire-dtype scale) must not leak
            # into the public step — callers feed already-prepared batches.
            self._device_transform = None
            self._train_step = None
            self._multi_step = None
        k = (steps_per_execution if steps_per_execution is not None
             else max(1, int(getattr(self.model, "steps_per_execution", 1))))
        if k > 1:
            if self._multi_step is None:
                self._multi_step = self._acquire_program(
                    "multi_step", self._build_multi_step,
                    *self._train_variant())
            return self._multi_step
        if self._train_step is None:
            self._train_step = self._acquire_program(
                "train_step", self._build_train_step, *self._train_variant())
        return self._train_step

    def train_state(self) -> tuple:
        """A fresh ``(params, state, opt, metrics, loss_acc)`` tuple, in the
        positional order the ``make_train_function`` callable consumes."""
        self.ensure_variables()
        v = self.variables
        return (v["params"], v["state"], v["opt"], v["metrics"],
                self._init_loss_acc())

    def _build_eval_step(self):
        model, loss_obj = self.model, self.model.loss
        metrics = tuple(model.metrics)
        device_transform = self._eval_transform

        def step(params, state, metric_states, loss_acc, x, y):
            if device_transform is not None:
                x = device_transform(x)
            logits, _ = model.apply(params, state, x, training=False)
            loss = loss_obj(logits, y)
            new_metrics = tuple(
                m.update(ms, logits, y) for m, ms in zip(metrics, metric_states))
            new_loss_acc = (loss_acc[0] + loss, loss_acc[1] + 1.0)
            return new_metrics, new_loss_acc

        return jax.jit(step, donate_argnums=(2, 3))

    # -- data plumbing (D14/D15 auto-wrap) ------------------------------------

    def _distribute(self, x):
        from tpu_dist.data.device import DeviceDataset

        if isinstance(x, DeviceDataset):
            # Pin the dataset to the training mesh (it may have been built
            # outside strategy.scope()).
            return x.bind_strategy(self.strategy)
        if isinstance(x, DistributedDataset):
            return x
        if isinstance(x, Dataset):
            # Device-residency promotion first (data/vectorize.py): an
            # HBM-sized reference-shaped chain uploads once and streams only
            # index vectors — the TPU-idiomatic delivery. Falls through to
            # the Keras-trainer auto-wrap (keras:src/backend/tensorflow/
            # trainer.py:750-755), which honors the auto-shard options.
            from tpu_dist.data import vectorize

            promoted = vectorize.try_promote_to_device(x)
            if promoted is not None:
                return promoted.bind_strategy(self.strategy)
            # allow_device_transform: the trainer applies dataset device
            # transforms inside its compiled steps (_sync_device_transform),
            # so the u8-wire split is safe here — unlike user-iterated wraps.
            return DistributedDataset(x, self.strategy,
                                      allow_device_transform=True)
        if isinstance(x, (tuple, list)) and len(x) == 2:
            ds = Dataset.from_tensor_slices(tuple(np.asarray(a) for a in x))
            return DistributedDataset(ds.batch(32), self.strategy)
        raise TypeError(
            f"fit/evaluate expects a Dataset, DistributedDataset, "
            f"DeviceDataset or (x, y) arrays; got {type(x).__name__}")

    @staticmethod
    def _cardinality_of(dist) -> Optional[int]:
        from tpu_dist.data.device import DeviceDataset

        if isinstance(dist, DeviceDataset):
            return dist.cardinality()
        return dist._local.cardinality()

    def _next_batch(self, dist: DistributedDataset, *, host: bool = False):
        """Persistent-iterator semantics across epochs (Keras 2): re-create on
        exhaustion — a fresh pass implies a fresh (re)shuffle. ``host=True``
        yields the pre-placement numpy batch (multi-step stacking path).
        With ``prefetch_to_device > 0`` compiled on the model, the device
        path routes through a :class:`~tpu_dist.data.pipeline.
        DevicePrefetcher` — batch k+1's device placement runs on a
        background thread while step k executes."""
        if not host and int(getattr(self.model, "prefetch_to_device", 0)
                            or 0) > 0:
            return self._next_prefetched(
                dist, int(self.model.prefetch_to_device))
        kind = "host" if host else "device"
        if (self._iterator is None or self._iterator_source is not dist
                or self._iterator_kind != kind):
            self._close_prefetcher()
            self._iterator = dist.iter_local() if host else iter(dist)
            self._iterator_source = dist
            self._iterator_kind = kind
        try:
            return next(self._iterator)
        except StopIteration:
            self._iterator = dist.iter_local() if host else iter(dist)
            batch = next(self._iterator, None)
            if batch is None:
                raise RuntimeError("dataset yielded no batches")
            return batch

    def _next_prefetched(self, dist: DistributedDataset, depth: int):
        """Double-buffered device fetch: same persistent-iterator semantics
        as :meth:`_next_batch`'s device path, with the iteration (and its
        ``device_put``) pushed onto the prefetcher's producer thread."""
        from tpu_dist.data.pipeline import DevicePrefetcher

        if (self._prefetcher is None or self._iterator_source is not dist
                or self._iterator_kind != "prefetch"):
            self._close_prefetcher()
            self._iterator = None
            self._prefetcher = DevicePrefetcher(iter(dist), depth=depth)
            self._iterator_source = dist
            self._iterator_kind = "prefetch"
        try:
            return next(self._prefetcher)
        except StopIteration:
            self._close_prefetcher()
            self._prefetcher = DevicePrefetcher(iter(dist), depth=depth)
            self._iterator_source = dist
            self._iterator_kind = "prefetch"
            try:
                return next(self._prefetcher)
            except StopIteration:
                self._close_prefetcher()
                raise RuntimeError("dataset yielded no batches") from None

    def _close_prefetcher(self) -> None:
        """Tear down the device prefetcher (epoch-loop exit, StopTraining,
        preemption drain, rollback): stops the producer, drains in-flight
        batches, joins the thread."""
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
            if self._iterator_kind == "prefetch":
                self._iterator_source = None

    # -- fit / evaluate / predict ---------------------------------------------

    def fit(self, x, *, epochs: int, steps_per_epoch: Optional[int],
            verbose: int, callbacks: Sequence, initial_epoch: int,
            seed: int, profile_dir: Optional[str] = None,
            validation_data=None, validation_steps: Optional[int] = None,
            checkpoint_dir: Optional[str] = None,
            class_weight: Optional[dict] = None) -> History:
        self.ensure_variables(seed)
        self._maybe_invalidate_for_policy()
        self._sync_step_knobs()
        from tpu_dist.parallel.ps_strategy import ParameterServerStrategy

        if isinstance(self.strategy, ParameterServerStrategy):
            # The async second execution model: no gang-synchronous step, no
            # collective in the hot loop — pull → local step → push against
            # the PS transport instead of the epoch machinery below.
            if not self.strategy.is_worker:
                raise ValueError(
                    "fit() under ParameterServerStrategy runs on worker "
                    "ranks; the server rank runs PSServer.run() "
                    "(tpu_dist.parallel.ps_strategy)")
            if class_weight:
                raise ValueError(
                    "class_weight is not supported under "
                    "ParameterServerStrategy")
            return self._fit_ps(x, epochs=epochs,
                                steps_per_epoch=steps_per_epoch,
                                verbose=verbose, callbacks=callbacks,
                                initial_epoch=initial_epoch, seed=seed)
        if class_weight is not None:
            class_weight = {int(c): float(w) for c, w in class_weight.items()}
            if any(c < 0 for c in class_weight):
                raise ValueError(f"negative class index in {class_weight}")
            if not class_weight:  # {} means no weighting, like None
                class_weight = None
        if class_weight != self._class_weight:
            # The weight table is baked into the compiled step; a different
            # weighting needs a rebuild (weights carry over untouched).
            self._class_weight = class_weight
            self._train_step = None
            self._multi_step = None
        # Distribute BEFORE building steps: the dataset may carry a device
        # transform that is a trace-time constant of the compiled step.
        dist = self._distribute(x)
        self._sync_device_transform(dist, role="train")
        if self._train_step is None:
            self._train_step = self._acquire_program(
                "train_step", self._build_train_step, *self._train_variant())
        if (getattr(self.model, "steps_per_execution", 1) > 1
                and self._multi_step is None):
            self._multi_step = self._acquire_program(
                "multi_step", self._build_multi_step, *self._train_variant())
        if steps_per_epoch is None:
            steps_per_epoch = self._cardinality_of(dist)
            if steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required for datasets of unknown "
                    "cardinality (e.g. repeated/generator datasets)")

        callbacks = list(callbacks)
        # Code-edit-free chaos wiring (tpu_dist.resilience): a fault plan in
        # $TPU_DIST_FAULT_PLAN — set by the resilience CLI / Supervisor —
        # rides this fit as one more callback. None in production runs.
        from tpu_dist.resilience.injector import (maybe_injector_from_env,
                                                  maybe_preemption_drain,
                                                  maybe_rejoin_gate)

        fault_injector = maybe_injector_from_env(
            steps_per_epoch=steps_per_epoch)
        if fault_injector is not None:
            callbacks.append(fault_injector)
        # Graceful-preemption drain: armed only when the SIGTERM seam is
        # installed (run_entry workers), so a notebook fit pays nothing.
        # Appended AFTER the injector so an injected `preempt` fault is
        # observed by the drain in the same step-boundary callback round.
        drain = maybe_preemption_drain()
        if drain is not None:
            callbacks.append(drain)
        # Elastic epoch-boundary rejoin: $TPU_DIST_REJOIN_DIR (set by the
        # operator / chaos CLI) holds every worker at each epoch start
        # until the whole gang — including a relaunched member — arrives.
        rejoin = maybe_rejoin_gate()
        if rejoin is not None:
            callbacks.append(rejoin)
        # Mid-epoch gang reform: $TPU_DIST_GANG_DIR (set by the Supervisor
        # in step-rejoin mode) arms the step-boundary reform gate — on a
        # detected peer loss survivors drain here, reform the collective
        # clique under a fresh generation, and meet the relaunched rank at
        # a step-granular rendezvous instead of paying a gang restart.
        from tpu_dist.resilience import rejoin as rejoin_lib

        gang_gate = rejoin_lib.maybe_step_rejoin_gate(
            steps_per_epoch=steps_per_epoch)
        if gang_gate is not None:
            callbacks.append(gang_gate)
        # Same env-armed pattern for telemetry (tpu_dist.observe): an
        # observe dir in $TPU_DIST_OBSERVE_DIR — set by the Supervisor for
        # chaos workers, or by a shell — attaches the Telemetry callback.
        # Skipped when the caller already passed one (theirs wins).
        from tpu_dist.observe.telemetry import (Telemetry,
                                                maybe_telemetry_from_env)

        if not any(isinstance(cb, Telemetry) for cb in callbacks):
            telemetry = maybe_telemetry_from_env()
            if telemetry is not None:
                callbacks.append(telemetry)
        if checkpoint_dir is not None:
            # SURVEY.md §5.4: fit(checkpoint_dir=) = chief-writes-per-epoch +
            # resume-from-latest. A restored step N means epoch N finished.
            from tpu_dist.training import checkpoint as ckpt_lib
            from tpu_dist.training.callbacks import ModelCheckpoint

            import os as _os

            # A worker relaunched into a reformed gang restores the
            # CONSENSUS step the supervisor stamped ("none" = scratch),
            # not its own directory's latest — its dead predecessor's dir
            # may be ahead of or behind the survivors'.
            forced = _os.environ.get("TPU_DIST_RESTORE_STEP")
            try:
                if forced is None or forced == "":
                    restored = ckpt_lib.restore_model(
                        checkpoint_dir, self.model, trainer=self)
                elif forced == "none":
                    restored = None
                else:
                    restored = ckpt_lib.restore_model(
                        checkpoint_dir, self.model, step=int(forced),
                        trainer=self)
                if restored is not None:
                    initial_epoch = max(initial_epoch, restored + 1)
                    logger.info("resumed from checkpoint step %d; starting "
                                "at epoch %d", restored, initial_epoch)
                    from tpu_dist.resilience import events

                    events.maybe_log("checkpoint_resume", step=restored,
                                     initial_epoch=initial_epoch)
            except FileNotFoundError:
                pass
            # Don't double up save+barrier work if the caller already passed
            # a ModelCheckpoint for this same directory (str/Path agnostic).
            import os as _os

            def _same_dir(cb):
                d = getattr(cb, "directory", None)
                return (d is not None
                        and _os.fspath(d) == _os.fspath(checkpoint_dir))

            if not any(isinstance(cb, ModelCheckpoint) and _same_dir(cb)
                       for cb in callbacks):
                callbacks.append(ModelCheckpoint(checkpoint_dir))

        val_dist = val_steps = None
        if validation_data is not None:
            val_dist = self._distribute(validation_data)
            val_steps = validation_steps
            if val_steps is None:
                val_steps = self._cardinality_of(val_dist)
                if val_steps is None:
                    raise ValueError(
                        "validation_steps is required for validation datasets "
                        "of unknown cardinality")

        # Env-armed training-integrity guard (tpu_dist.training.integrity):
        # in-step anomaly detection + periodic cross-replica SDC audit +
        # rollback-and-replay, riding the hot loop directly (NOT a callback
        # — a batch-hook callback would force per-step blocking loss reads).
        from tpu_dist.training import integrity as integrity_lib

        guard = integrity_lib.maybe_guard_from_env()
        if guard is not None:
            guard.bind(self.strategy, checkpoint_dir=checkpoint_dir)

        history = History()
        cbs = CallbackList([history, *callbacks], model=self.model)
        chief = bootstrap.is_chief()
        show = verbose and chief
        root_key = jax.random.PRNGKey(seed ^ 0x5EED)

        cbs.on_train_begin()
        # Chief-only TensorBoard-compatible trace around the whole fit span
        # (SURVEY.md §5.1; README.md:51 chief duty).
        import contextlib

        ctx = (profiler.trace(profile_dir) if profile_dir
               else contextlib.nullcontext())
        try:
            with ctx:
                start_epoch = initial_epoch
                while True:
                    try:
                        self._run_epochs(dist, cbs, start_epoch, epochs,
                                         steps_per_epoch, show, root_key,
                                         val_dist=val_dist,
                                         val_steps=val_steps, guard=guard)
                        break
                    except integrity_lib.RollbackAndReplay as rb:
                        # Confirmed anomaly: restore the last published
                        # checkpoint and replay from that epoch boundary.
                        # Budget enforcement lives in the guard — it raises
                        # IntegrityAbort (escapes fit) when replay is not
                        # converging.
                        start_epoch = self._integrity_rollback(
                            rb, guard, checkpoint_dir, seed)
                    except rejoin_lib.GangReform as gr:
                        # A peer died mid-epoch: run the survivor side of
                        # the reform protocol (publish in-flight checkpoint,
                        # ack, re-init the clique at generation g+1, restore,
                        # meet the relaunched rank) and re-enter the loop —
                        # same rollback-and-replay RNG discipline, so losses
                        # stay exact.
                        start_epoch = self._gang_reform(
                            gr, gang_gate, cbs, checkpoint_dir, seed,
                            steps_per_epoch)
        except StopTraining as e:
            logger.info("training stopped early: %s", e)
        finally:
            # Tear down the device prefetcher FIRST — StopTraining and a
            # preemption drain land here with a producer thread possibly
            # mid-device_put, and callbacks (checkpoint publish) must see a
            # quiesced pipeline.
            self._close_prefetcher()
            # Runs even on the failure path (e.g. PeerUnavailableError) so
            # callbacks finalize — a JSONLogger's file matters most there.
            cbs.on_train_end()
        return history

    # -- parameter-server worker path ----------------------------------------

    def _build_ps_worker_step(self):
        """The PS worker's compiled local step: ``(params, state, x, y, rng)
        -> (loss, grads, state)`` — forward/backward ONLY. No optimizer
        update (the server owns optimizer state) and no collective (the
        strategy's mesh is one local device), which is the property
        shardcheck pins for the ``ps_worker_step`` entry point."""
        model, loss_obj = self.model, self.model.loss
        device_transform = self._device_transform

        def step(params, state, x, y, rng):
            if device_transform is not None:
                x = device_transform(x)

            def loss_fn(p):
                logits, new_state = model.apply(p, state, x, training=True,
                                                rng=rng)
                return loss_obj(logits, y) + _aux_loss_total(new_state), \
                    new_state

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, grads, new_state

        return jax.jit(step)

    def _fit_ps(self, x, *, epochs: int, steps_per_epoch: Optional[int],
                verbose: int, callbacks: Sequence, initial_epoch: int,
                seed: int) -> History:
        """The worker side of async PS training: pull params (bounded
        staleness enforced there), run ONE local step, push grads, repeat
        until the server orders STOP or the local step budget runs out.

        Epochless by nature — "epoch" here is local-step bookkeeping
        (``local_step // steps_per_epoch``) so History/callbacks keep their
        shape. The RNG stream is step-derived per (rank, local step)
        (:func:`~tpu_dist.parallel.ps_strategy.worker_step_key`), NOT
        epoch-derived: reproducibility is per-packet given the server's
        apply-order log, not per-epoch. Checkpointing/validation stay
        server-side; ``fit(checkpoint_dir=)`` is ignored here by design.
        """
        from tpu_dist.parallel import collectives
        from tpu_dist.parallel.ps_strategy import worker_step_key
        from tpu_dist.resilience.injector import (maybe_injector_from_env,
                                                  maybe_preemption_drain)

        strategy = self.strategy
        dist = self._distribute(x)
        self._sync_device_transform(dist, role="train")
        if steps_per_epoch is None:
            steps_per_epoch = self._cardinality_of(dist)
            if steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required for datasets of unknown "
                    "cardinality (e.g. repeated/generator datasets)")
        ps_step = self._acquire_program("ps_worker_step",
                                        self._build_ps_worker_step,
                                        self._transform_key(
                                            self._device_transform))

        callbacks = list(callbacks)
        fault_injector = maybe_injector_from_env(
            steps_per_epoch=steps_per_epoch)
        if fault_injector is not None:
            callbacks.append(fault_injector)
        drain = maybe_preemption_drain()
        if drain is not None:
            callbacks.append(drain)
        from tpu_dist.observe.telemetry import (Telemetry,
                                                maybe_telemetry_from_env)

        if not any(isinstance(cb, Telemetry) for cb in callbacks):
            telemetry = maybe_telemetry_from_env()
            if telemetry is not None:
                callbacks.append(telemetry)

        history = History()
        cbs = CallbackList([history, *callbacks], model=self.model)
        show = bool(verbose)
        root_key = jax.random.PRNGKey(seed ^ 0x5EED)  # shardcheck: disable=SC604 -- deliberately mirrors fit()'s root-key derivation so the PS sync control is stream-identical to the sync trainer
        params_template = self.variables["params"]
        state = self.variables["state"]
        rank = strategy.rank
        # A worker caps at the GLOBAL step budget, not its 1/world share:
        # under a straggler the fast workers must be free to cover the
        # applies the slow one doesn't produce — the server's STOP (at its
        # apply budget) is the real terminator.
        max_local = (epochs - initial_epoch) * steps_per_epoch \
            * max(1, strategy.num_workers)
        local_step = 0
        stopped = False
        logger.info("PS worker %d: staleness=%d, steps_per_epoch=%d, "
                    "local cap=%d", rank, strategy.staleness,
                    steps_per_epoch, max_local)
        cbs.on_train_begin()
        try:
            for epoch in range(initial_epoch, epochs * max(
                    1, strategy.num_workers)):
                cbs.on_epoch_begin(epoch)
                if show:
                    print(f"Epoch {epoch + 1}/{epochs} (PS worker {rank})")
                bar = ProgressBar(steps_per_epoch, enabled=show)
                loss_sum = 0.0
                steps_this_epoch = 0
                t_epoch = time.perf_counter()
                for si in range(steps_per_epoch):
                    pulled = strategy.pull(params_template)
                    if pulled is None:  # server ordered STOP
                        stopped = True
                        break
                    params, _version = pulled
                    xb, yb = self._next_batch(dist)
                    rng = worker_step_key(root_key, rank=rank,
                                          local_step=local_step)
                    loss, grads, state = ps_step(params, state, xb, yb, rng)
                    # The straggler seam: a `delay@step*:rankN:always` plan
                    # sleeps HERE, between compute and push — exactly where
                    # a slow worker loses time. Same hook the sync stack's
                    # collectives fire, so one fault grammar serves both
                    # execution models.
                    collectives.fire_fault_hook("ps_step")
                    loss_val = float(loss)
                    strategy.push(grads, loss=loss_val)
                    strategy.heartbeat(step=local_step)
                    local_step += 1
                    steps_this_epoch += 1
                    loss_sum += loss_val
                    bar.update(si + 1, loss=loss_sum / steps_this_epoch)
                    cbs.on_batch_end(si, {"loss": loss_val})
                    if local_step >= max_local:
                        stopped = True
                        break
                if steps_this_epoch:
                    logs = {"loss": loss_sum / steps_this_epoch,
                            "epoch_time": time.perf_counter() - t_epoch}
                    bar.finish(logs)
                    cbs.on_epoch_end(epoch, logs)
                if stopped:
                    break
        except StopTraining as e:
            logger.info("PS worker %d stopped early: %s", rank, e)
        finally:
            self._close_prefetcher()
            strategy.mark_done(steps=local_step)
            cbs.on_train_end()
        logger.info("PS worker %d done: %d local steps, %d pushes",
                    rank, local_step, strategy.pushed)
        return history

    def _integrity_rollback(self, rb, guard, checkpoint_dir, seed) -> int:
        """Rollback-and-replay: restore the newest published checkpoint
        (strictly older than the last restore when replay re-hit the same
        anomaly), reset the data iterator to the epoch boundary, and return
        the epoch to re-enter the loop at. With no published checkpoint the
        run re-initializes from the seed and replays from epoch 0 — exact
        for the epoch-keyed RNG + per-epoch-pass datasets of the demo
        paths."""
        from tpu_dist.observe import metrics as metrics_lib
        from tpu_dist.resilience import events
        from tpu_dist.training import checkpoint as ckpt_lib

        restored = None
        if checkpoint_dir is not None:
            step = ckpt_lib.latest_complete_step(
                checkpoint_dir, before=guard.rollback_plan(rb))
            if step is not None:
                restored = ckpt_lib.restore_model(checkpoint_dir, self.model,
                                                  step=step, trainer=self)
        if restored is None:
            self.variables = None
            self.ensure_variables(seed)
            next_epoch = 0
        else:
            next_epoch = restored + 1
        # Fresh iterator: replay re-reads the epoch's batches from the top —
        # identical to what a gang-restarted attempt would see (persistent
        # iterators are recreated per pass when cardinality matches).
        self._iterator = None
        self._close_prefetcher()
        guard.note_rollback(rb, restored)
        metrics_lib.inc("integrity.rollbacks")
        events.maybe_log("integrity_rollback", kind=rb.kind, step=rb.gstep,
                         restored_step=restored, next_epoch=next_epoch,
                         attempt=events.current_attempt())
        logger.warning(
            "integrity rollback: anomaly %r at global step %d; restored "
            "checkpoint step %s, replaying from epoch %d",
            rb.kind, rb.gstep, restored, next_epoch)
        return next_epoch

    def _gang_reform(self, gr, gate, cbs, checkpoint_dir, seed,
                     steps_per_epoch) -> int:
        """Survivor side of a mid-epoch gang reform.

        Phase order matters: (1) quiesce the input pipeline; (2) make the
        latest epoch checkpoint durable and ACK — the supervisor relaunches
        the lost rank only after every survivor has acked, so the rejoiner's
        restore is guaranteed to see the published state; (3) re-initialize
        the collective clique under the new generation; (4) restore the last
        complete checkpoint (every rank converges on the same step, hence
        the same rendezvous coordinate); (5) meet the reformed gang at the
        step-granular barrier. Each phase's wall time is recorded — the
        recovery breakdown the chaos report prints.
        """
        import time as _time

        from tpu_dist.cluster import bootstrap as bootstrap_lib
        from tpu_dist.observe import metrics as metrics_lib
        from tpu_dist.resilience import events
        from tpu_dist.training import checkpoint as ckpt_lib
        from tpu_dist.training.callbacks import ModelCheckpoint

        # -- drain: quiesce + publish in-flight checkpoints ----------------
        self._iterator = None
        self._close_prefetcher()
        for cb in cbs.callbacks:
            if isinstance(cb, ModelCheckpoint):
                cb.publish_in_flight()
        available = (ckpt_lib.latest_complete_step(checkpoint_dir)
                     if checkpoint_dir is not None else None)
        drain_s = _time.monotonic() - gr.seen_at
        bootstrap_lib.ack_reform(gate.directory, generation=gr.generation,
                                 rank=gate.rank, available_step=available)

        # -- reform: new clique under generation g+1 -----------------------
        t_reform = _time.monotonic()
        bootstrap_lib.reinitialize(generation=gr.generation)
        gate.generation = gr.generation

        # -- restore: converge every rank on the CONSENSUS step ------------
        # Per-rank checkpoint dirs can disagree by an epoch or two (ranks
        # are only loosely coupled between barriers; the dead rank's async
        # save may never have published). Restoring each rank's own latest
        # would put the gang at different epochs and deadlock the reformed
        # rendezvous — so the supervisor collects every ack's available
        # step, takes the gang-wide minimum, and publishes it for all.
        t_restore = _time.monotonic()
        deadline = _time.monotonic() + gate.timeout_s
        while True:
            published, step = bootstrap_lib.read_restore_step(
                gate.directory, generation=gr.generation)
            if published:
                break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"gang reform: no consensus restore step for generation "
                    f"{gr.generation} within {gate.timeout_s:.1f}s")
            _time.sleep(0.05)
        restored = None
        if step is not None and checkpoint_dir is not None:
            restored = ckpt_lib.restore_model(checkpoint_dir, self.model,
                                              step=step, trainer=self)
        if restored is None:
            self.variables = None
            self.ensure_variables(seed)
            next_epoch = 0
        else:
            next_epoch = restored + 1
        restore_s = _time.monotonic() - t_restore

        # -- rendezvous: meet the relaunched rank mid-run ------------------
        gate.rendezvous(step=next_epoch * steps_per_epoch, epoch=next_epoch)
        reform_s = _time.monotonic() - t_reform

        metrics_lib.inc("elastic.gang_reforms")
        metrics_lib.observe_value("elastic.drain_s", drain_s)
        metrics_lib.observe_value("elastic.reform_s", reform_s)
        metrics_lib.observe_value("elastic.restore_s", restore_s)
        events.maybe_log(
            "gang_reform", generation=gr.generation,
            lost_ranks=gr.lost_ranks, rank=gate.rank,
            detect_s=gr.request.get("detect_s"),
            drain_s=round(drain_s, 6), reform_s=round(reform_s, 6),
            restore_s=round(restore_s, 6), restored_step=restored,
            next_epoch=next_epoch, attempt=events.current_attempt())
        logger.warning(
            "gang reform: lost rank(s) %s; reformed at generation %d, "
            "restored checkpoint step %s, replaying from epoch %d "
            "(drain %.3fs reform %.3fs restore %.3fs)",
            gr.lost_ranks, gr.generation, restored, next_epoch,
            drain_s, reform_s, restore_s)
        return next_epoch

    def _run_epochs(self, dist, cbs, initial_epoch, epochs, steps_per_epoch,
                    show, root_key, val_dist=None, val_steps=None,
                    guard=None):
        from tpu_dist.data.device import DeviceDataset
        from tpu_dist.observe.telemetry import active_step_timer
        from tpu_dist.training.integrity import fire_batch_hook

        device_ds = isinstance(dist, DeviceDataset)
        monitor = getattr(self.strategy, "liveness_monitor", None)
        # Installed by a Telemetry callback's on_train_begin (which has
        # already run); None on uninstrumented fits — the hot loop then
        # pays exactly one is-None check per execution.
        timer = active_step_timer()
        for epoch in range(initial_epoch, epochs):
            if monitor is not None:
                # Surface a dead peer as a restartable error instead of letting
                # the next collective hang (SURVEY.md §5.3 failure semantics).
                monitor.raise_if_failed()
            cbs.on_epoch_begin(epoch)
            if show:
                print(f"Epoch {epoch + 1}/{epochs}")
            bar = ProgressBar(steps_per_epoch, enabled=bool(show))
            v = self.variables
            v["metrics"] = self._init_metric_states()  # reset per epoch
            loss_acc = self._init_loss_acc()
            # Per-step host sync (float(loss)) is only paid when something
            # consumes it — otherwise steps stay fully async on device and the
            # host runs ahead filling the dispatch pipeline (BASELINE.md
            # hard-part #5: tiny MNIST steps are dispatch-bound).
            eager_loss = bool(show) or cbs.has_batch_hooks
            bounded = self._bounded_dispatch()
            loss_running = 0.0
            t_epoch = time.perf_counter()
            k = max(1, int(getattr(self.model, "steps_per_execution", 1)))
            # All of this epoch's step keys in ONE device op, then pre-sliced
            # into per-execution chunks BEFORE the hot loop: eager device ops
            # interleaved with compiled executions measurably stall the
            # dispatch pipeline on a tunneled runtime, while a burst of
            # consecutive slices up front is free. Values are identical to
            # fold_in(root_key, epoch*100003 + step_i).
            epoch_keys = jnp_stack_keys(
                root_key, epoch * 100003, steps_per_epoch)
            key_chunks = []
            _i = 0
            while _i < steps_per_epoch:
                _kk = min(k, steps_per_epoch - _i)
                key_chunks.append(epoch_keys[_i] if _kk == 1
                                  else epoch_keys[_i:_i + _kk])
                _i += _kk
            step_i = 0
            executions = 0
            while step_i < steps_per_epoch:
                kk = min(k, steps_per_epoch - step_i)
                gstep0 = epoch * steps_per_epoch + step_i
                if guard is not None and guard.should_skip(gstep0, kk):
                    # Quarantined window (integrity guard, opt-in): pull the
                    # batches so the iterator stays aligned, but skip the
                    # dispatch — replaying a data-poisoned window would just
                    # re-trigger the same rollback.
                    if device_ds:
                        dist.next_batch() if kk == 1 else dist.next_stack(kk)
                    elif k > 1:
                        for _ in range(kk):
                            self._next_batch(dist, host=True)
                    else:
                        self._next_batch(dist)
                    from tpu_dist.resilience import events as _events

                    _events.maybe_log("integrity_quarantine_skip",
                                      step=gstep0, window=kk)
                    step_i += kk
                    executions += 1
                    continue
                # Step-phase timing (tpu_dist.observe): data-wait ends at
                # t_fetch, dispatch at the compiled call's return, device
                # time is the block_until_ready below. perf_counter calls
                # only when a Telemetry span is active.
                t_exec0 = time.perf_counter() if timer is not None else 0.0
                t_fetch = t_exec0
                with profiler.step_annotation(gstep0):
                    if kk == 1:
                        if device_ds:
                            xb, yb = dist.next_batch()
                        elif k > 1:
                            # Tail step of a multi-step run: stay on the HOST
                            # iterator — switching kinds would recreate the
                            # iterator mid-epoch and replay batches.
                            hb = self._next_batch(dist, host=True)
                            xb, yb = self.strategy.distribute_batch(hb)
                        else:
                            xb, yb = self._next_batch(dist)
                        xb, yb = fire_batch_hook(gstep0, 1, xb, yb)
                        rng = key_chunks[executions]
                        if timer is not None:
                            t_fetch = time.perf_counter()
                        (loss, v["params"], v["state"], v["opt"], v["metrics"],
                         loss_acc, health) = self._train_step(
                            v["params"], v["state"], v["opt"], v["metrics"],
                            loss_acc, xb, yb, rng)
                    elif device_ds:
                        # Device-resident path: batches gathered ON device
                        # (index transfer only), one scanned dispatch.
                        xb, yb = dist.next_stack(kk)
                        xb, yb = fire_batch_hook(gstep0, kk, xb, yb)
                        if timer is not None:
                            t_fetch = time.perf_counter()
                        (loss, v["params"], v["state"], v["opt"],
                         v["metrics"], loss_acc, health) = self._multi_step(
                            v["params"], v["state"], v["opt"],
                            v["metrics"], loss_acc, xb, yb,
                            key_chunks[executions])
                    else:
                        # steps_per_execution: stack kk host batches, ONE
                        # dispatch runs the scanned step (SURVEY.md
                        # hard-part #5). loss comes back as the kk-mean.
                        batches = [self._next_batch(dist, host=True)
                                   for _ in range(kk)]
                        if timer is not None:
                            # Host-iterator pulls are the data wait; the
                            # stack/placement below is charged to dispatch.
                            t_fetch = time.perf_counter()
                        if len({b[0].shape for b in batches}) == 1:
                            xs = np.stack([b[0] for b in batches])
                            ys = np.stack([b[1] for b in batches])
                            xb, yb = self.strategy.distribute_batch_stack(
                                (xs, ys))
                            xb, yb = fire_batch_hook(gstep0, kk, xb, yb)
                            (loss, v["params"], v["state"], v["opt"],
                             v["metrics"], loss_acc,
                             health) = self._multi_step(
                                v["params"], v["state"], v["opt"],
                                v["metrics"], loss_acc, xb, yb,
                                key_chunks[executions])
                        else:
                            # Ragged batch in the window (drop_remainder=False
                            # tail): un-stackable — run the collected batches
                            # per-step instead of crashing.
                            for j, hb in enumerate(batches):
                                xb, yb = self.strategy.distribute_batch(hb)
                                xb, yb = fire_batch_hook(gstep0 + j, 1,
                                                         xb, yb)
                                (loss, v["params"], v["state"], v["opt"],
                                 v["metrics"], loss_acc,
                                 health) = self._train_step(
                                    v["params"], v["state"], v["opt"],
                                    v["metrics"], loss_acc, xb, yb,
                                    key_chunks[executions][j])
                step_i += kk
                executions += 1
                if guard is not None:
                    # One-behind health judgement + periodic SDC audit: the
                    # new vector's host copy starts now (non-blocking), the
                    # previous execution's — already in flight — is judged.
                    guard.on_execution(gstep0, kk, health, v["params"])
                if timer is not None:
                    # The blocking wait IS the device-time measurement; it
                    # also satisfies the bounded-dispatch requirement.
                    t_disp = time.perf_counter()
                    jax.block_until_ready(loss)
                    timer.record_execution(
                        steps=kk, data_wait_s=t_fetch - t_exec0,
                        dispatch_s=t_disp - t_fetch,
                        device_block_s=time.perf_counter() - t_disp)
                elif bounded:
                    jax.block_until_ready(loss)
                if eager_loss:
                    loss_val = float(loss)
                    loss_running += loss_val
                    bar.update(step_i, loss=loss_running / executions)
                    # Keras steps_per_execution semantics: batch hooks fire
                    # once per execution, logs carry the execution's loss.
                    cbs.on_batch_end(step_i - 1, {"loss": loss_val})
            if guard is not None:
                # Judge the final in-flight health vector BEFORE epoch-end
                # callbacks run: a poisoned last step must trigger rollback
                # here, not after ModelCheckpoint has published the epoch.
                guard.flush()
            # ZERO host syncs on the epoch boundary: the loss mean and each
            # metric result are queued as device ops right behind the last
            # step's dispatch, a single batched non-blocking device→host
            # transfer is issued (LazyLogs), and the actual wait happens only
            # if/when a consumer reads a value — the progress bar when
            # verbose, a monitor callback, or History at `.history` access
            # after fit. The old eager device_get here was a full round-trip
            # (~100 ms through a tunneled runtime — measured to dominate
            # short epochs); a verbose=0 fit with no log-reading callbacks
            # now skips the fetch entirely. The scalars below are all fresh
            # (never-donated) outputs, so deferred reads stay valid.
            import jax.numpy as jnp

            device_logs = {"loss": loss_acc[0] / jnp.maximum(loss_acc[1], 1.0)}
            for metric, mstate in zip(self.model.metrics, v["metrics"]):
                device_logs[metric.name] = metric.result(mstate)
            logs = LazyLogs({"epoch_time": time.perf_counter() - t_epoch},
                            device_logs)
            if val_dist is not None:
                # Keras validation semantics: full validation pass at each
                # epoch end, reported as val_-prefixed logs (feeds
                # EarlyStopping/ModelCheckpoint monitors); absorbed without
                # forcing a fetch — the val scalars stay lazy too.
                val_logs = self._evaluate_on(val_dist, steps=val_steps)
                logs.absorb(val_logs, prefix="val_")
            bar.finish(logs)
            cbs.on_epoch_end(epoch, logs)

    def evaluate(self, x, *, steps: Optional[int], verbose: int) -> dict:
        self.ensure_variables()
        self._maybe_invalidate_for_policy()
        logs = self._evaluate_on(self._distribute(x), steps=steps)
        if verbose and bootstrap.is_chief():
            print(" - ".join(f"{k}: {v_:.4f}" for k, v_ in logs.items()))
        return logs

    def _evaluate_on(self, dist: DistributedDataset,
                     steps: Optional[int]) -> dict:
        """One evaluation pass over ``dist``; shared by evaluate() and the
        per-epoch validation hook of fit()."""
        self._sync_device_transform(dist, role="eval")
        if self._eval_step is None:
            self._eval_step = self._acquire_program(
                "eval_step", self._build_eval_step, *self._eval_variant())
        v = self.variables
        metric_states = self._init_metric_states()
        loss_acc = self._init_loss_acc()
        count = 0
        # islice stops BEFORE pulling batch steps+1 — a plain for-loop with a
        # break-on-count would do one extra batch of host pipeline work per
        # bounded pass only to discard it.
        import itertools

        bounded_dispatch = self._bounded_dispatch()
        bounded = dist if steps is None else itertools.islice(iter(dist), steps)
        for xb, yb in bounded:
            metric_states, loss_acc = self._eval_step(
                v["params"], v["state"], metric_states, loss_acc, xb, yb)
            if bounded_dispatch:
                jax.block_until_ready(loss_acc)
            count += 1
        if count == 0:
            raise RuntimeError("evaluate: dataset yielded no batches")
        # Same zero-sync pattern as the epoch end: queue the scalar ops on
        # device, start one batched non-blocking transfer, and let the
        # caller's first read await it (LazyLogs is a dict, so evaluate()'s
        # public contract is unchanged).
        import jax.numpy as jnp

        device_logs = {"loss": loss_acc[0] / jnp.maximum(loss_acc[1], 1.0)}
        for metric, mstate in zip(self.model.metrics, metric_states):
            device_logs[metric.name] = metric.result(mstate)
        return LazyLogs(device_logs=device_logs)

    def predict(self, x):
        self.ensure_variables()
        self._maybe_invalidate_for_policy()
        model = self.model
        is_array = isinstance(x, np.ndarray) or hasattr(x, "__array__")
        t = None if is_array else getattr(
            x, "device_transform", getattr(x, "_device_transform", None))
        if self._transform_key(t) != self._transform_key(
                self._eval_transform):
            self._eval_transform = t
            self._eval_step = None
            self._predict_fn = None
        if self._predict_fn is None:
            dt = self._eval_transform

            def fwd(p, s, xb):
                if dt is not None:
                    xb = dt(xb)
                return model.apply(p, s, xb, training=False)[0]

            self._predict_fn = self._acquire_program(
                "predict", lambda: jax.jit(fwd), *self._eval_variant())
        if is_array:
            batches = [np.asarray(x)]
        else:
            batches = [b[0] if isinstance(b, tuple) else b for b in x]
        v = self.variables
        n_dev = len(self.strategy.mesh.local_devices)
        # One compiled program for the whole pass: every batch pads up to
        # the largest batch size rounded to a device multiple (a ragged
        # final batch or mixed sizes would otherwise retrace per distinct
        # length — the no-retrace discipline tpu_dist.serve buckets by).
        sizes = [int(np.asarray(b).shape[0]) for b in batches]
        if not sizes:
            return np.concatenate([], axis=0)
        target = max(sizes)
        target += (-target) % n_dev
        outs = []
        for xb in batches:
            xb = np.asarray(xb)
            n = xb.shape[0]
            pad = target - n
            if pad:
                xb = np.concatenate([xb, np.repeat(xb[-1:], pad, axis=0)])
            placed = self.strategy.distribute_batch(xb)
            out = np.asarray(self._predict_fn(v["params"], v["state"], placed))
            outs.append(out[:n])
        return np.concatenate(outs, axis=0)
