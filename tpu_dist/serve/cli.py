"""``python -m tpu_dist.serve`` — demo + seeded load generator.

Modes
-----
* default (demo): build the small causal LM, serve a handful of prompts
  through the continuous-batching engine, print the generations and the
  latency/throughput summary.
* ``--bench``: a seeded load-generator run — **closed-loop** (``--clients
  K``: K clients, each submits, waits for completion, immediately submits
  again) or **open-loop** (``--arrival-rate R``: exponential interarrivals
  at R req/s, submissions decoupled from completions). Prints a JSON
  report with p50/p95/p99 request latency, TTFT, throughput, and batch
  occupancy; exits 1 when the run is vacuous (no request completed).

Arrival times drive an *injected virtual clock* advanced by the load
generator, so a fixed ``--seed`` gives a reproducible request schedule
(real wall time still determines latency measurements — the decode steps
are real work).

Set ``$TPU_DIST_OBSERVE_DIR`` to also export the metrics snapshot as
schema-versioned JSONL + a Prometheus textfile, exactly like training
telemetry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

import numpy as np

from tpu_dist.observe import metrics
from tpu_dist.observe.telemetry import OBSERVE_DIR_ENV


def _quantile(vals, p: float) -> Optional[float]:
    """Shared report-quantile helper (bench summary + chaos storm gate):
    None on empty, else the :func:`tpu_dist.observe.metrics.quantile`
    linear-interpolation estimator — the same math the metrics snapshot
    quotes, so every serve report agrees on the estimator."""
    if not vals:
        return None
    return round(metrics.quantile(sorted(float(v) for v in vals), p), 6)


def _build_engine(args, *, policy: Optional[str] = None, **engine_kwargs):
    """Build the demo/bench engine; ``engine_kwargs`` forward the
    resilience knobs (journal, max_queue, stall watchdog, ...) straight to
    :class:`~tpu_dist.serve.engine.ServeEngine`."""
    from tpu_dist.models.transformer import build_transformer_lm
    from tpu_dist.serve.engine import ServeEngine

    paged_kwargs = {}
    if getattr(args, "paged", False):
        paged_kwargs = {"paged": True, "page_size": args.page_size,
                        "num_pages": args.num_pages}
        if getattr(args, "kv_dtype", None) is not None:
            paged_kwargs["kv_dtype"] = args.kv_dtype
        if getattr(args, "ragged", False):
            paged_kwargs["ragged"] = True
    if getattr(args, "budget_mb", None) is not None:
        paged_kwargs["budget_bytes"] = int(args.budget_mb * 2**20)
    if args.model_dir:
        return ServeEngine.from_saved(
            args.model_dir, max_batch=args.max_batch,
            policy=policy or args.policy, temperature=args.temperature,
            seed=args.seed, **paged_kwargs, **engine_kwargs)
    model = build_transformer_lm(args.vocab, args.max_len,
                                 d_model=args.d_model, depth=args.depth,
                                 num_heads=args.num_heads)
    return ServeEngine(model, max_batch=args.max_batch,
                       max_len=args.max_len,
                       policy=policy or args.policy,
                       temperature=args.temperature, seed=args.seed,
                       **paged_kwargs, **engine_kwargs)


def _workload(args) -> list[dict]:
    """Seeded synthetic request stream: ragged prompts, varied budgets."""
    rng = np.random.default_rng(args.seed)
    out = []
    for _ in range(args.requests):
        plen = int(rng.integers(2, max(3, args.max_len // 4)))
        out.append({
            "prompt": rng.integers(0, args.vocab, size=plen).tolist(),
            "max_new_tokens": int(rng.integers(args.min_new,
                                               args.max_new + 1)),
        })
    return out


def _summary(engine, *, wall_s: float) -> dict:
    from tpu_dist.serve.scheduler import DONE, EVICTED, SHED

    # Terminal states are mutually exclusive and exhaustive: every
    # finished request is exactly one of done / evicted / shed (a shed
    # request never held a slot, an evicted one never completed).
    done = [r for r in engine.finished if r.status == DONE]
    evicted = [r for r in engine.finished if r.status == EVICTED]
    shed = [r for r in engine.finished if r.status == SHED]
    assert len(done) + len(evicted) + len(shed) == len(engine.finished), \
        "finished request with a non-terminal status"
    tokens = sum(len(r.generated) for r in engine.finished)

    q = _quantile
    lat = [r.latency_s for r in done if r.latency_s is not None]
    ttft = [r.ttft_s for r in done if r.ttft_s is not None]
    snap = metrics.get_registry().snapshot() if metrics.enabled() else None
    occ = (snap["distributions"].get("serve.batch.occupancy")
           if snap else None)
    return {
        "completed": len(done),
        "evicted": len(evicted),
        "shed": len(shed),
        "tokens_generated": tokens,
        "wall_s": round(wall_s, 4),
        "throughput_tok_s": (round(tokens / wall_s, 2) if wall_s > 0
                             else None),
        "latency_s": {"p50": q(lat, 0.5), "p95": q(lat, 0.95),
                      "p99": q(lat, 0.99)},
        "ttft_s": {"p50": q(ttft, 0.5), "p95": q(ttft, 0.95),
                   "p99": q(ttft, 0.99)},
        "batch_occupancy": occ,
        "compiled_programs": engine.compiled_programs(),
    }


def run_load(engine, workload: list[dict], *, clients: int = 0,
             arrival_rate: float = 0.0, seed: int = 0,
             deadline_s: Optional[float] = None) -> dict:
    """Drive a request stream through the engine; returns the summary.

    ``clients > 0`` → closed-loop: at most ``clients`` requests in flight;
    the next request of the stream is submitted the moment one finishes.
    ``arrival_rate > 0`` → open-loop: request i arrives at the i-th
    seeded exponential arrival time, measured in *decode-loop* time (the
    generator advances submissions between engine steps). Both modes
    drain the full workload.
    """
    rng = np.random.default_rng(seed)
    pending = list(workload)
    t0 = time.monotonic()
    if arrival_rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / arrival_rate,
                                             size=len(pending)))
    else:
        arrivals = None
        width = max(1, clients or engine.max_batch)

    submitted = 0
    # Not a peer wait: every iteration either submits, steps the local
    # engine (which always makes decode progress), or naps until the next
    # seeded arrival — the workload is finite so the loop drains.
    while submitted < len(pending) or not engine.scheduler.idle():  # shardcheck: disable=SC502 -- local engine progress bounds the loop
        if arrivals is not None:
            elapsed = time.monotonic() - t0
            while (submitted < len(pending)
                   and arrivals[submitted] <= elapsed):
                w = pending[submitted]
                engine.submit(w["prompt"],
                              max_new_tokens=w["max_new_tokens"],
                              deadline_s=deadline_s)
                submitted += 1
        else:
            in_flight = (engine.scheduler.num_active
                         + engine.scheduler.queue_depth())
            while submitted < len(pending) and in_flight < width:
                w = pending[submitted]
                engine.submit(w["prompt"],
                              max_new_tokens=w["max_new_tokens"],
                              deadline_s=deadline_s)
                submitted += 1
                in_flight += 1
        if engine.scheduler.idle():
            if arrivals is None:
                continue  # closed loop refills immediately above
            # Open loop: idle until the next arrival is due.
            nxt = arrivals[submitted] - (time.monotonic() - t0)
            if nxt > 0:
                time.sleep(min(nxt, 0.05))
            continue
        engine.step()
    return _summary(engine, wall_s=time.monotonic() - t0)


def _export_observe(tag: str) -> Optional[str]:
    d = os.environ.get(OBSERVE_DIR_ENV)
    if not d:
        return None
    from tpu_dist.observe.exporters import (JsonlExporter,
                                            write_prometheus_textfile)

    os.makedirs(d, exist_ok=True)
    snap = metrics.get_registry().snapshot()
    with JsonlExporter(os.path.join(d, "serve.jsonl")) as ex:
        ex.write(snap, kind=tag)
    write_prometheus_textfile(snap, os.path.join(d, "serve.prom"))
    return d


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.serve",
        description="continuous-batching inference demo + load generator")
    p.add_argument("--bench", action="store_true",
                   help="seeded load-generator run, JSON report")
    p.add_argument("--model-dir", default=None,
                   help="serve a models.save_model directory instead of a "
                        "freshly initialized demo LM")
    p.add_argument("--policy", choices=("continuous", "static"),
                   default="continuous")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--clients", type=int, default=0,
                   help="closed-loop client count (0 = saturate the batch)")
    p.add_argument("--arrival-rate", type=float, default=0.0,
                   help="open-loop arrivals per second (0 = closed loop)")
    p.add_argument("--deadline-s", type=float, default=None)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--min-new", type=int, default=4)
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    # -- paged KV cache (README "Paged KV & prefix caching") ---------------
    p.add_argument("--paged", action="store_true",
                   help="paged KV cache + prefix reuse instead of the "
                        "contiguous per-slot preallocation")
    p.add_argument("--page-size", type=int, default=16,
                   help="positions per KV page (with --paged)")
    p.add_argument("--num-pages", type=int, default=None,
                   help="page-pool size (default: contiguous-capacity "
                        "parity, max_batch * ceil(max_len/page_size))")
    p.add_argument("--budget-mb", type=float, default=None,
                   help="KV memory budget in MiB — loud sizing error "
                        "(contiguous) or pool auto-sizing (--paged)")
    p.add_argument("--kv-dtype", choices=("fp32", "bf16", "int8"),
                   default=None,
                   help="paged-pool storage dtype (with --paged); int8 "
                        "quantizes K/V pages with per-position fp32 "
                        "scales — ~2x pages at a fixed --budget-mb")
    p.add_argument("--ragged", action="store_true",
                   help="one full-capacity decode program with per-slot "
                        "masking instead of pow2 buckets (with --paged)")
    # -- resilience / chaos (README "Serving resilience") -----------------
    p.add_argument("--worker", action="store_true",
                   help="supervised serve worker: journal + fault plan "
                        "from the environment, RESULT line on stdout")
    p.add_argument("--chaos", action="store_true",
                   help="serve chaos run: baseline, supervised faults, "
                        "gated JSON report")
    p.add_argument("--plan", default=None,
                   help="fault plan for --chaos (engine_crash@reqN / "
                        "decode_stall@reqN:Ss / request_storm@reqN)")
    p.add_argument("--journal-dir", default=None,
                   help="durable request journal directory (recovery "
                        "replays an existing journal)")
    p.add_argument("--max-queue", type=int, default=None,
                   help="bounded admission queue: shed past this depth")
    p.add_argument("--max-ttft-s", type=float, default=None,
                   help="shed when projected TTFT exceeds this bound")
    p.add_argument("--retry-budget", type=int, default=3,
                   help="max crash replays before a request is shed")
    p.add_argument("--stall-timeout-s", type=float, default=None,
                   help="decode-stall watchdog bound (None = disabled)")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--deadline", type=float, default=120.0, metavar="S",
                   help="per-attempt wall-clock deadline for --chaos")
    p.add_argument("--storm-requests", type=int, default=300)
    p.add_argument("--storm-burst", type=int, default=25,
                   help="storm submissions between decode rounds")
    p.add_argument("--virtual-step-s", type=float, default=0.05,
                   help="virtual decode-step seconds for the storm gate")
    p.add_argument("--p99-target-s", type=float, default=None,
                   help="storm p99 gate (default: BENCH_SERVE.json)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--report", default=None,
                   help="also write the chaos JSON report to this path")
    p.add_argument("--fleet", action="store_true",
                   help="multi-replica fleet run: sessioned workload "
                        "through the prefix-affinity router, token-parity "
                        "+ failover gates (serve/fleet.py)")
    p.add_argument("--fleet-replicas", type=int, default=2,
                   help="initial replica count for --fleet")
    p.add_argument("--fleet-sessions", type=int, default=4,
                   help="distinct shared-prefix sessions in the --fleet "
                        "workload (affinity anti-vacuity needs >= 1)")
    p.add_argument("--devices-per-replica", type=int, default=None,
                   help="lease a submesh of this many devices per replica "
                        "via the jobs runtime (default: no lease, engines "
                        "share the default strategy)")
    args = p.parse_args(argv)

    if args.fleet:
        from tpu_dist.serve.fleet import run_fleet

        return run_fleet(args)
    if args.worker:
        from tpu_dist.serve.chaos import run_worker

        return run_worker(args)
    if args.chaos:
        from tpu_dist.serve.chaos import run_chaos

        return run_chaos(args)

    metrics.get_registry().reset()
    metrics.enable()
    try:
        engine = _build_engine(args, journal=args.journal_dir,
                               max_queue=args.max_queue,
                               max_ttft_s=args.max_ttft_s,
                               retry_budget=args.retry_budget,
                               stall_timeout_s=args.stall_timeout_s)
        if args.bench:
            summary = run_load(engine, _workload(args),
                               clients=args.clients,
                               arrival_rate=args.arrival_rate,
                               seed=args.seed,
                               deadline_s=args.deadline_s)
            engine.close()
            mode = ("open-loop" if args.arrival_rate > 0 else "closed-loop")
            report = {
                "bench": "serve.load",
                "mode": mode,
                "policy": args.policy,
                "config": {"requests": args.requests,
                           "max_batch": args.max_batch,
                           "max_len": args.max_len,
                           "clients": args.clients,
                           "arrival_rate": args.arrival_rate,
                           "paged": bool(args.paged),
                           "page_size": args.page_size,
                           "kv_dtype": args.kv_dtype,
                           "ragged": bool(args.ragged),
                           "seed": args.seed},
                **summary,
            }
            # A run that completed nothing is vacuous — including the
            # degenerate case where overload protection shed EVERYTHING.
            report["ok"] = report["completed"] > 0
            obs = _export_observe("serve_bench")
            if obs:
                report["observe_dir"] = obs
            print(json.dumps(report, indent=2))
            if not report["ok"]:
                print(f"VACUOUS: no request completed "
                      f"({report['shed']} shed, {report['evicted']} "
                      f"evicted)", file=sys.stderr)
                return 1
            return 0

        # Demo: a few fixed prompts through the engine, verbose output.
        rng = np.random.default_rng(args.seed)
        reqs = [engine.submit(
                    rng.integers(0, args.vocab,
                                 size=int(rng.integers(2, 9))).tolist(),
                    max_new_tokens=int(rng.integers(4, 13)))
                for _ in range(min(args.requests, 6))]
        t0 = time.monotonic()
        engine.run_until_idle()
        engine.close()
        for r in reqs:
            print(f"req {r.rid}: prompt[{len(r.prompt)}] -> "
                  f"{r.generated} ({r.finish_reason}, "
                  f"{(r.latency_s or 0) * 1e3:.1f} ms)")
        print(json.dumps(_summary(engine,
                                  wall_s=time.monotonic() - t0), indent=2))
        _export_observe("serve_demo")
        return 0
    finally:
        metrics.disable()


if __name__ == "__main__":
    sys.exit(main())
