"""Preallocated KV cache + incremental decode for the transformer LM family.

Training runs the causal LM as one full-sequence forward pass; serving
cannot afford O(L^2) work per generated token. This module gives the
``models/transformer.py`` family an inference path that is numerically
identical to the training forward pass (tests pin allclose in fp32) while
doing O(L) work per new token:

* **prefill** — one full causal forward over the (padded) prompt, routed
  through the SAME attention dispatch training uses
  (``transformer._default_attention``: the fused flash kernel from
  ``ops/flash_attention.py`` on TPU for supported shapes, dense softmax
  elsewhere), capturing every layer's K/V projections into a
  preallocated per-layer cache as it goes. Emits the logits of the last
  *valid* prompt position — the first generated token, i.e. the
  time-to-first-token datum.
* **decode_step** — one token per active slot: Q/K/V are computed for the
  single new position, K/V appended to the cache at each slot's current
  length, and attention runs against the cached keys/values under a
  per-slot validity mask. Padding slots/positions beyond a slot's length
  are masked out, so cache rows left over from an evicted request are
  never read.

The cache is a plain pytree — ``{"k": [layers, slots, heads, max_len,
key_dim], "v": ...}`` — so engines can donate it into jitted programs
(in-place append, no per-step copy) and shardcheck can price its HBM
footprint like any other entry point.

Rather than re-deriving the transformer math, the interpreter is built
from a :func:`build_plan` walk over the ``Sequential``'s layer tree: the
frozen layer dataclasses ARE the architecture description, so the plan
reuses each layer's own ``apply`` (LayerNorm/Dense/Embedding are
position-wise) and ``MultiHeadAttention._heads`` projection — the decode
path shares weights *and code* with training, which is what makes the
equivalence test meaningful. Models outside the servable family
(pipelined stages, MoE blocks, custom ``attention_fn`` hooks, non-causal
attention) are rejected at plan-build time with a pointed error.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from tpu_dist.models.layers import (Block, Dense, Layer, Residual,
                                    _activation)
from tpu_dist.models.model import Sequential
from tpu_dist.models.transformer import (Embedding, LayerNormalization,
                                         MultiHeadAttention,
                                         PositionalEmbedding,
                                         _default_attention)

# -- plan: a flat, servable description of the Sequential ---------------------

#: Plan op tags. Ops are plain tuples so the plan stays hashable/static
#: under jit closures: ("embed"|"pos"|"point", layer, path),
#: ("attn", layer, path, cache_layer_index),
#: ("res_start",), ("res_end", activation_name).
_POINTWISE = (LayerNormalization, Dense)


@dataclasses.dataclass(frozen=True)
class DecodePlan:
    """Static decode description of one servable Sequential."""

    ops: tuple
    num_layers: int  #: attention layers == KV-cache depth
    num_heads: int
    key_dim: int
    max_position: int  #: PositionalEmbedding.max_len — hard cap on length
    vocab_size: int


def _unsupported(layer: Layer, why: str) -> TypeError:
    return TypeError(
        f"serve: {type(layer).__name__} is not servable ({why}); the KV-"
        "cache decode path covers the build_transformer_lm family — "
        "token/positional embeddings, pre-LN blocks with default causal "
        "attention, LayerNorm and Dense layers")


def build_plan(model: Sequential) -> DecodePlan:
    """Flatten a Sequential into decode ops, validating servability."""
    if not isinstance(model, Sequential):
        raise TypeError(
            f"serve supports Sequential models, got {type(model).__name__}")
    ops: list = []
    attn_layers: list[MultiHeadAttention] = []
    pos_layers: list[PositionalEmbedding] = []

    def walk(layers, names, path):
        for layer, name in zip(layers, names):
            p = path + (name,)
            if isinstance(layer, Embedding):
                ops.append(("embed", layer, p))
            elif isinstance(layer, PositionalEmbedding):
                pos_layers.append(layer)
                ops.append(("pos", layer, p))
            elif isinstance(layer, MultiHeadAttention):
                if not layer.causal:
                    raise _unsupported(
                        layer, "non-causal attention cannot decode "
                        "incrementally — future tokens would change past "
                        "activations")
                if layer.attention_fn is not None:
                    raise _unsupported(
                        layer, "custom attention_fn hooks (ring attention "
                        "etc.) have no cache-aware decode path")
                ops.append(("attn", layer, p, len(attn_layers)))
                attn_layers.append(layer)
            elif isinstance(layer, Residual):
                if layer.shortcut:
                    raise _unsupported(
                        layer, "projection shortcuts are a ResNet shape, "
                        "not a transformer residual")
                ops.append(("res_start",))
                walk(layer.main, layer._main_names, p + ("main",))
                ops.append(("res_end", layer.activation))
            elif isinstance(layer, Block):
                walk(layer.layers, layer._names, p)
            elif isinstance(layer, _POINTWISE):
                ops.append(("point", layer, p))
            else:
                raise _unsupported(layer, "no decode rule for this layer")

    walk(model.layers, model.layer_names, ())
    if not attn_layers:
        raise TypeError("serve: model has no attention layers to cache")
    heads = {(l.num_heads, l.key_dim) for l in attn_layers}
    if len(heads) > 1:
        raise TypeError(
            f"serve: attention layers disagree on (num_heads, key_dim) "
            f"({sorted(heads)}); a stacked KV cache needs uniform shapes")
    last = model.layers[-1]
    if not isinstance(last, Dense):
        raise TypeError(
            "serve: expected a Dense vocabulary head as the final layer, "
            f"got {type(last).__name__}")
    (num_heads, key_dim), = heads
    max_position = min((l.max_len for l in pos_layers),
                      default=2 ** 30)
    return DecodePlan(ops=tuple(ops), num_layers=len(attn_layers),
                      num_heads=num_heads, key_dim=key_dim,
                      max_position=max_position, vocab_size=last.units)


def init_cache(plan: DecodePlan, *, max_batch: int, max_len: int,
               dtype=jnp.float32, budget_bytes: Optional[int] = None) -> dict:
    """Zeros cache pytree: ``k``/``v`` of
    ``[num_layers, max_batch, num_heads, max_len, key_dim]``.

    ``budget_bytes`` turns the advisory :func:`cache_nbytes` math into a
    hard guard: when the cache would not fit, raise a loud error naming
    how many slots DO fit instead of letting XLA OOM at first prefill.
    """
    if jnp.dtype(dtype) == jnp.int8:
        raise ValueError(
            "serve: int8 KV is a paged-pool feature (the quantized pages "
            "carry per-page scale rows the contiguous cache has no layout "
            "for) — use ServeEngine(paged=True, kv_dtype='int8')")
    if max_len > plan.max_position:
        raise ValueError(
            f"max_len {max_len} exceeds the model's positional table "
            f"({plan.max_position})")
    if budget_bytes is not None:
        need = cache_nbytes(plan, max_batch=max_batch, max_len=max_len,
                            dtype=dtype)
        if need > budget_bytes:
            per_slot = need // max_batch
            fits = int(budget_bytes // per_slot)
            raise ValueError(
                f"serve: contiguous KV cache needs {need} B for "
                f"{max_batch} slots x {max_len} positions but "
                f"budget_bytes={budget_bytes} — at this max_len the "
                f"budget fits {fits} slot(s). Lower max_batch/max_len, "
                "raise the budget, or switch to the paged cache "
                "(ServeEngine(paged=True)), which allocates per page "
                "instead of max_len per slot.")
    shape = (plan.num_layers, max_batch, plan.num_heads, max_len,
             plan.key_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_nbytes(plan: DecodePlan, *, max_batch: int, max_len: int,
                 dtype=jnp.float32) -> int:
    """HBM the cache will pin, for capacity planning / logs."""
    n = (2 * plan.num_layers * max_batch * plan.num_heads * max_len
         * plan.key_dim)
    return n * jnp.dtype(dtype).itemsize


# -- shared layer helpers -----------------------------------------------------


def _params_at(params, path):
    node = params
    for key in path:
        node = node.get(key, {}) if isinstance(node, dict) else {}
    return node


def _qkv(layer: MultiHeadAttention, p, x):
    """The training projection, verbatim: [.., L, D] -> three
    [.., H, L, key_dim] head tensors."""
    b = (lambda n: p[n]) if layer.use_bias else (lambda n: None)
    return (layer._heads(x, p["wq"], b("bq")),
            layer._heads(x, p["wk"], b("bk")),
            layer._heads(x, p["wv"], b("bv")))


def _attn_out(layer: MultiHeadAttention, p, out):
    """[.., H, L, dk] attention output -> [.., L, D] through wo/bo."""
    out = jnp.moveaxis(out, -3, -2)
    *lead, ln, h, dk = out.shape
    out = out.reshape(*lead, ln, h * dk)
    y = out @ p["wo"].astype(out.dtype)
    if layer.use_bias:
        y = y + p["bo"].astype(y.dtype)
    return y


# -- prefill ------------------------------------------------------------------


def prefill(plan: DecodePlan, params, cache: dict, tokens, length, slot,
            *, attention_fn: Optional[Callable] = None):
    """Full causal forward over one padded prompt, filling cache slot
    ``slot``.

    Args:
      tokens: int32 ``[pad_len]`` prompt, padded past ``length`` with any
        token id (padded positions' K/V land in the cache but decode's
        validity mask never reads them before they are overwritten).
      length: scalar int32, number of valid prompt tokens (>= 1).
      slot: scalar int32 cache row to fill.
      attention_fn: override for the prefill attention inner loop
        (signature ``fn(q, k, v, causal=..., scale=...)``); defaults to
        the training dispatch — the fused flash kernel on TPU for
        supported shapes, dense softmax otherwise.

    Returns:
      ``(cache, last_logits)`` — logits ``[vocab]`` of position
      ``length - 1``, i.e. the distribution over the first generated
      token.
    """
    attend = attention_fn or _default_attention
    x = tokens[None]  # [1, pad_len]
    pad_len = tokens.shape[0]
    residuals: list = []
    for op in plan.ops:
        tag = op[0]
        if tag == "res_start":
            residuals.append(x)
        elif tag == "res_end":
            x = _activation(op[1])(residuals.pop() + x)
        elif tag == "attn":
            _, layer, path, idx = op
            p = _params_at(params, path)
            q, k, v = _qkv(layer, p, x)  # [1, H, pad_len, dk]
            scale = 1.0 / math.sqrt(layer.key_dim)
            out = attend(q, k, v, causal=True, scale=scale)
            dt = cache["k"].dtype
            for name, new in (("k", k), ("v", v)):
                cache[name] = jax.lax.dynamic_update_slice(
                    cache[name], new.astype(dt)[None],
                    (idx, slot, 0, 0, 0))
            x = _attn_out(layer, p, out)
        elif tag == "pos":
            _, layer, path = op
            table = _params_at(params, path)["table"]
            x = x + table[:pad_len].astype(x.dtype)
        else:  # "embed" / "point": the layer's own stateless apply
            _, layer, path = op
            x, _ = layer.apply(_params_at(params, path), {}, x)
    # x: [1, pad_len, vocab]; take the last VALID position's logits.
    last = jax.lax.dynamic_slice(
        x, (0, jnp.maximum(length - 1, 0), 0), (1, 1, plan.vocab_size))
    return cache, last[0, 0]


def prefill_chunk_step(plan: DecodePlan, params, cache: dict, tokens,
                       length, slot, start):
    """Causal forward over ONE chunk of a prompt — the contiguous-cache
    half of chunked prefill.

    The first ``start`` positions' K/V are already in cache slot
    ``slot`` (written by earlier chunks); this pass computes positions
    ``start .. length - 1``, writes their K/V at a traced window offset
    via ``dynamic_update_slice``, and attends each chunk query over the
    whole cached row under the absolute-position causal mask — exactly
    what a full prefill would compute for those positions, so chunked
    and whole-prompt prefill stay token-identical (the paged path gets
    the same semantics for free from :func:`paged_prefill`'s traced
    ``start``).

    Args:
      tokens: int32 ``[chunk_pad]`` — chunk tokens for absolute
        positions ``start .. length - 1``, padded past
        ``length - start``. Padded positions write garbage K/V at
        ``[length, start + chunk_pad)``; positions there are beyond
        every mask until a later chunk or decode append overwrites them
        (the same argument that covers whole-prompt prefill padding).
        The caller must guarantee ``start + chunk_pad <= max_len`` —
        ``dynamic_update_slice`` would otherwise clamp the window start
        and silently corrupt earlier positions.
      length: scalar int32 total valid positions through the end of
        this chunk (prefix + chunk).
      slot: scalar int32 cache row.
      start: scalar int32 already-cached positions (``< length``).

    Returns:
      ``(cache, last_logits)`` — logits ``[vocab]`` of position
      ``length - 1`` (the first-generated-token distribution when this
      is the final chunk; intermediate chunks' logits are discarded).
    """
    pad = tokens.shape[0]
    x = tokens[None]                       # [1, pad]
    valid = length - start
    pos = start + jnp.arange(pad)          # absolute positions [pad]
    max_len = cache["k"].shape[3]
    key_pos = jnp.arange(max_len)
    residuals: list = []
    for op in plan.ops:
        tag = op[0]
        if tag == "res_start":
            residuals.append(x)
        elif tag == "res_end":
            x = _activation(op[1])(residuals.pop() + x)
        elif tag == "pos":
            _, layer, path = op
            table = _params_at(params, path)["table"]
            at = jnp.minimum(pos, table.shape[0] - 1)
            x = x + table[at].astype(x.dtype)[None]
        elif tag == "attn":
            _, layer, path, idx = op
            p = _params_at(params, path)
            q, k, v = _qkv(layer, p, x)    # [1, H, pad, dk]
            dt = cache["k"].dtype
            # Window write at the traced chunk offset, then attend over
            # the whole row (earlier chunks' K/V plus this one's).
            for name, new in (("k", k), ("v", v)):
                cache[name] = jax.lax.dynamic_update_slice(
                    cache[name], new.astype(dt)[None],
                    (idx, slot, 0, start, 0))
            keys = jnp.take(cache["k"][idx], slot, axis=0)  # [H, S, dk]
            vals = jnp.take(cache["v"][idx], slot, axis=0)
            scale = 1.0 / math.sqrt(layer.key_dim)
            s = jnp.einsum("hqd,hkd->hqk", q[0].astype(jnp.float32),
                           keys.astype(jnp.float32)) * scale
            # Key j is position j: <= the query's own absolute position
            # covers causality and prefix validity in one mask.
            mask = key_pos[None, :] <= pos[:, None]         # [pad, S]
            s = jnp.where(mask[None], s, -jnp.inf)
            prob = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("hqk,hkd->hqd", prob,
                             vals.astype(jnp.float32))
            x = _attn_out(layer, p, out.astype(q.dtype)[None])
        else:  # "embed" / "point"
            _, layer, path = op
            x, _ = layer.apply(_params_at(params, path), {}, x)
    # x: [1, pad, vocab]; last valid chunk position is valid - 1.
    last = jax.lax.dynamic_slice(
        x, (0, jnp.maximum(valid - 1, 0), 0), (1, 1, plan.vocab_size))
    return cache, last[0, 0]


# -- incremental decode -------------------------------------------------------


def decode_step(plan: DecodePlan, params, cache: dict, tokens, lengths,
                *, bucket: int):
    """One generated token for the first ``bucket`` cache slots.

    Args:
      tokens: int32 ``[cap]`` — each slot's most recent token (prompt tail
        or last generated); only ``[:bucket]`` is read.
      lengths: int32 ``[cap]`` — tokens already cached per slot; the new
        token is written at this position. Only ``[:bucket]`` is read.
      bucket: static slot count this compiled program covers — the
        engine compiles one program per padded batch bucket so
        steady-state serving never retraces.

    Returns:
      ``(cache, logits)`` with logits ``[bucket, vocab]`` fp32.
    """
    x = tokens[:bucket][:, None]          # [b, 1]
    pos = lengths[:bucket]                # [b]
    rows = jnp.arange(bucket)
    max_len = cache["k"].shape[3]
    residuals: list = []
    for op in plan.ops:
        tag = op[0]
        if tag == "res_start":
            residuals.append(x)
        elif tag == "res_end":
            x = _activation(op[1])(residuals.pop() + x)
        elif tag == "pos":
            _, layer, path = op
            table = _params_at(params, path)["table"]
            x = x + table[pos].astype(x.dtype)[:, None, :]
        elif tag == "attn":
            _, layer, path, idx = op
            p = _params_at(params, path)
            q, k, v = _qkv(layer, p, x)   # [b, H, 1, dk]
            dt = cache["k"].dtype
            # Append this position's K/V at each slot's length (batched
            # scatter; advanced indices around the head slice put the
            # broadcast [b, H, dk] dims in front, matching the operand).
            cache["k"] = cache["k"].at[idx, rows, :, pos, :].set(
                k[:, :, 0, :].astype(dt))
            cache["v"] = cache["v"].at[idx, rows, :, pos, :].set(
                v[:, :, 0, :].astype(dt))
            keys = cache["k"][idx, :bucket]      # [b, H, S, dk]
            vals = cache["v"][idx, :bucket]
            scale = 1.0 / math.sqrt(layer.key_dim)
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           keys.astype(jnp.float32)) * scale
            # Valid keys: cached prefix plus the just-appended position.
            valid = jnp.arange(max_len)[None, :] <= pos[:, None]  # [b, S]
            s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
            prob = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", prob,
                             vals.astype(jnp.float32)).astype(q.dtype)
            x = _attn_out(layer, p, out)
        else:  # "embed" / "point"
            _, layer, path = op
            x, _ = layer.apply(_params_at(params, path), {}, x)
    return cache, x[:, 0, :].astype(jnp.float32)  # [b, vocab]


def swap_slots(cache: dict, i, j):
    """Exchange cache rows ``i`` and ``j`` (every layer, k and v) — the
    compaction move the scheduler uses to keep active slots a contiguous
    prefix so smaller buckets stay usable. ``i``/``j`` are traced
    scalars: one compiled program serves every swap."""
    out = {}
    for name, a in cache.items():
        ri = jnp.take(a, i, axis=1)
        rj = jnp.take(a, j, axis=1)
        out[name] = a.at[:, i].set(rj).at[:, j].set(ri)
    return out


# -- paged cache --------------------------------------------------------------
#
# The paged variant replaces the contiguous [layers, slots, heads, max_len,
# key_dim] preallocation with a pool of fixed-size pages — [layers,
# num_pages + 1, heads, page_size, key_dim] — addressed through a per-slot
# page table of page indices (host-managed by serve/paging.py). Row
# ``num_pages`` is a reserved scratch page: every index a program might
# compute for an invalid position (prompt padding, inactive decode slots
# whose stale page-table rows could otherwise alias pages reallocated to
# other requests) is routed there, so garbage writes land where nothing
# ever reads. A key at flattened gather position j of a slot's table is
# absolute sequence position j, so the contiguous validity mask
# ``arange <= pos`` carries over unchanged and the paged math stays
# allclose-equal to the contiguous path (tests pin it).
#
# int8 pool (``dtype=jnp.int8``): pages store K/V as int8 with fp32
# per-page scale ROWS — ``k_scale``/``v_scale`` of ``[num_layers,
# num_pages + 1, num_heads, page_size]``, one amax-derived symmetric
# scale per written position per head. Quantization happens at write
# time (prefill scatter, decode tail-append; ``copy_page`` clones the
# scale rows along with the int8 payload through the same generic loop)
# and dequantization is fused into the page gather, so the fp32
# attention math downstream is byte-for-byte the float path on the
# dequantized values. Scaling per POSITION rather than per whole page is
# what makes quantization write-order independent: a position's stored
# bytes depend only on its own K/V projection — never on what else
# landed in the page before or after — so journal replay (one big
# re-prefill) reproduces the exact pool bytes of the crashed run
# (prefill + many appends), and chunked prefill reproduces whole-prompt
# prefill, bit for bit. A per-page running-amax scale would break both:
# every amax bump re-rounds the page's older positions, making the
# bytes a function of write history.

#: Symmetric int8 range; scale = amax / _QMAX, values in [-127, 127].
_QMAX = 127.0


def _quantized(pool: dict) -> bool:
    """True for an int8 pool (fp32 scale planes present)."""
    return "k_scale" in pool


def _quant_rows(x):
    """Quantize ``[..., dk]`` fp rows to (int8 ``[..., dk]``, fp32 scale
    ``[...]``) — one symmetric amax scale per row. All-zero rows get
    scale 1 so they round-trip to exact zeros."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / _QMAX
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def page_nbytes(plan: DecodePlan, *, page_size: int,
                dtype=jnp.float32) -> int:
    """HBM one page pins across every layer, k and v. An int8 page also
    carries its fp32 scale rows (k and v, per head per position)."""
    n = 2 * plan.num_layers * plan.num_heads * page_size * plan.key_dim
    dt = jnp.dtype(dtype)
    if dt == jnp.int8:
        scales = 2 * plan.num_layers * plan.num_heads * page_size * 4
        return n * dt.itemsize + scales
    return n * dt.itemsize


def page_pool_nbytes(plan: DecodePlan, *, num_pages: int, page_size: int,
                     dtype=jnp.float32) -> int:
    """HBM the pool will pin, scratch page included."""
    return page_nbytes(plan, page_size=page_size, dtype=dtype) \
        * (num_pages + 1)


def pages_for_budget(plan: DecodePlan, *, page_size: int, budget_bytes: int,
                     dtype=jnp.float32) -> int:
    """Largest ``num_pages`` whose pool (plus scratch) fits the budget."""
    per = page_nbytes(plan, page_size=page_size, dtype=dtype)
    return max(int(budget_bytes // per) - 1, 0)


def init_page_pool(plan: DecodePlan, *, num_pages: int, page_size: int,
                   dtype=jnp.float32,
                   budget_bytes: Optional[int] = None) -> dict:
    """Zeros page pool pytree: ``k``/``v`` of
    ``[num_layers, num_pages + 1, num_heads, page_size, key_dim]`` —
    the extra row is the write-off scratch page.

    Like :func:`init_cache`, ``budget_bytes`` raises a loud sizing error
    (how many pages DO fit) instead of deferring to an XLA OOM.
    """
    if num_pages < 1:
        raise ValueError(f"num_pages must be >= 1, got {num_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if budget_bytes is not None:
        need = page_pool_nbytes(plan, num_pages=num_pages,
                                page_size=page_size, dtype=dtype)
        if need > budget_bytes:
            fits = pages_for_budget(plan, page_size=page_size,
                                    budget_bytes=budget_bytes, dtype=dtype)
            raise ValueError(
                f"serve: page pool needs {need} B for {num_pages} pages of "
                f"{page_size} positions (plus the scratch page) but "
                f"budget_bytes={budget_bytes} — the budget fits {fits} "
                "page(s). Lower num_pages/page_size or raise the budget.")
    shape = (plan.num_layers, num_pages + 1, plan.num_heads, page_size,
             plan.key_dim)
    pool = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        # fp32 scale rows, one per (layer, page, head, position). Zero
        # pages decode to exact zeros under any scale; real scales are
        # written alongside every K/V write.
        sshape = shape[:-1]
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return pool


def _gather_pages(pool_arr, layer_idx: int, page_rows):
    """Flatten one layer's pages into position order.

    ``pool_arr``: ``[L, P, H, ps, dk]``; ``page_rows``: int32
    ``[..., max_pages]`` page-table row(s). Returns
    ``[..., H, max_pages * ps, dk]`` where flattened index j holds
    absolute position j of that slot's sequence (table entries are
    position-ordered; unallocated entries point at scratch, whose
    garbage the caller's validity mask never admits).
    """
    g = pool_arr[layer_idx][page_rows]     # [..., max_pages, H, ps, dk]
    g = jnp.moveaxis(g, -3, -4)            # [..., H, max_pages, ps, dk]
    *lead, h, mp, ps, dk = g.shape
    return g.reshape(*lead, h, mp * ps, dk)


def _gather_kv(pool: dict, name: str, layer_idx: int, page_rows):
    """Position-ordered gather of ``pool[name]``, dequantized for int8
    pools (int8 payload × per-position fp32 scale row → fp32); float
    pools pass straight through :func:`_gather_pages`."""
    g = _gather_pages(pool[name], layer_idx, page_rows)
    if not _quantized(pool):
        return g
    s = pool[name + "_scale"][layer_idx][page_rows]  # [..., mp, H, ps]
    s = jnp.moveaxis(s, -2, -3)                      # [..., H, mp, ps]
    *lead, h, mp, ps = s.shape
    return g.astype(jnp.float32) * s.reshape(*lead, h, mp * ps)[..., None]


def paged_prefill(plan: DecodePlan, params, pool: dict, page_row, tokens,
                  length, start):
    """Causal forward over the UNCACHED suffix of one prompt, writing
    K/V through the page table.

    With a prefix-cache hit the first ``start`` positions' K/V already
    sit in (shared) pages referenced by ``page_row``; only the suffix is
    computed. The suffix queries attend over the gathered cached prefix
    plus their own causally-masked keys, so the result is numerically
    identical to a full prefill — cached K/V are exactly what the full
    forward would recompute. ``start=0`` is the cold path; one compiled
    program per padded suffix length serves both.

    Args:
      pool: page pool from :func:`init_page_pool`.
      page_row: int32 ``[max_pages]`` — this slot's page-table row.
        Entries covering ``[0, length)`` must be real pages (suffix
        pages writable, i.e. unshared); the rest point at scratch.
      tokens: int32 ``[pad]`` — suffix tokens for absolute positions
        ``start .. length - 1``, padded past ``length - start``.
      length: scalar int32 total valid positions (prefix + suffix).
      start: scalar int32 cached-prefix length (``< length``).

    Returns:
      ``(pool, last_logits)`` for float pools; int8 pools return
      ``(pool, last_logits, quant_error)`` where ``quant_error`` is the
      max-abs dequantization error over this call's valid suffix
      positions (fp32 scalar — the ``serve.kv.quant_error`` datum).
    """
    num_pages = pool["k"].shape[1] - 1     # last row is scratch
    ps = pool["k"].shape[3]
    max_pages = page_row.shape[0]
    pad = tokens.shape[0]
    x = tokens[None]                       # [1, pad]
    suffix = length - start
    pos = start + jnp.arange(pad)          # absolute positions [pad]
    valid_q = jnp.arange(pad) < suffix     # [pad]
    key_pos = jnp.arange(max_pages * ps)
    qerr = jnp.zeros((), jnp.float32)
    residuals: list = []
    for op in plan.ops:
        tag = op[0]
        if tag == "res_start":
            residuals.append(x)
        elif tag == "res_end":
            x = _activation(op[1])(residuals.pop() + x)
        elif tag == "pos":
            _, layer, path = op
            table = _params_at(params, path)["table"]
            at = jnp.minimum(pos, table.shape[0] - 1)
            x = x + table[at].astype(x.dtype)[None]
        elif tag == "attn":
            _, layer, path, idx = op
            p = _params_at(params, path)
            q, k, v = _qkv(layer, p, x)    # [1, H, pad, dk]
            # Scatter each suffix position into (its page, its offset);
            # padding positions are routed to the scratch page.
            pg = jnp.where(
                valid_q,
                page_row[jnp.minimum(pos // ps, max_pages - 1)],
                num_pages)                 # [pad]
            off = pos % ps
            if _quantized(pool):
                for name, new in (("k", k), ("v", v)):
                    rows = jnp.moveaxis(                     # [pad, H, dk]
                        new[0].astype(jnp.float32), 1, 0)
                    qv, sc = _quant_rows(rows)
                    pool[name] = pool[name].at[idx, pg, :, off, :].set(qv)
                    pool[name + "_scale"] = \
                        pool[name + "_scale"].at[idx, pg, :, off].set(sc)
                    err = jnp.max(jnp.abs(
                        rows - qv.astype(jnp.float32) * sc[..., None]),
                        axis=(1, 2))                         # [pad]
                    qerr = jnp.maximum(
                        qerr, jnp.max(jnp.where(valid_q, err, 0.0)))
            else:
                dt = pool["k"].dtype
                for name, new in (("k", k), ("v", v)):
                    pool[name] = pool[name].at[idx, pg, :, off, :].set(
                        jnp.moveaxis(new.astype(dt)[0], 1, 0))  # [pad, H, dk]
            keys = _gather_kv(pool, "k", idx, page_row)  # [H, S, dk]
            vals = _gather_kv(pool, "v", idx, page_row)
            scale = 1.0 / math.sqrt(layer.key_dim)
            s = jnp.einsum("hqd,hkd->hqk", q[0].astype(jnp.float32),
                           keys.astype(jnp.float32)) * scale
            # Key j is position j: <= the query's own absolute position
            # covers both causality and prefix validity in one mask.
            mask = key_pos[None, :] <= pos[:, None]         # [pad, S]
            s = jnp.where(mask[None], s, -jnp.inf)
            prob = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("hqk,hkd->hqd", prob,
                             vals.astype(jnp.float32))
            x = _attn_out(layer, p, out.astype(q.dtype)[None])
        else:  # "embed" / "point"
            _, layer, path = op
            x, _ = layer.apply(_params_at(params, path), {}, x)
    # x: [1, pad, vocab]; last valid suffix position is suffix - 1.
    last = jax.lax.dynamic_slice(
        x, (0, jnp.maximum(suffix - 1, 0), 0), (1, 1, plan.vocab_size))
    if _quantized(pool):
        return pool, last[0, 0], qerr
    return pool, last[0, 0]


def _paged_decode_core(plan: DecodePlan, params, pool: dict, tables,
                       tokens, pos, route):
    """Shared body of the bucketed and ragged paged decode programs.

    ``route(pg)`` maps each slot's computed tail page to its write
    destination — identity for the bucketed path (inactive slots there
    carry all-scratch table rows by host invariant), scratch-for-inactive
    for the ragged path (where mid-chunked-prefill slots hold REAL pages
    a stray decode write must not touch).
    """
    ps = pool["k"].shape[3]
    max_pages = tables.shape[1]
    b = tokens.shape[0]
    rows = jnp.arange(b)
    key_pos = jnp.arange(max_pages * ps)
    x = tokens[:, None]                    # [b, 1]
    residuals: list = []
    for op in plan.ops:
        tag = op[0]
        if tag == "res_start":
            residuals.append(x)
        elif tag == "res_end":
            x = _activation(op[1])(residuals.pop() + x)
        elif tag == "pos":
            _, layer, path = op
            table = _params_at(params, path)["table"]
            at = jnp.minimum(pos, table.shape[0] - 1)
            x = x + table[at].astype(x.dtype)[:, None, :]
        elif tag == "attn":
            _, layer, path, idx = op
            p = _params_at(params, path)
            q, k, v = _qkv(layer, p, x)    # [b, H, 1, dk]
            # Tail-page append: clamping the page-table column keeps the
            # gather in range; ``route`` decides where garbage writes go.
            pg = route(
                tables[rows, jnp.minimum(pos // ps, max_pages - 1)])  # [b]
            off = pos % ps
            if _quantized(pool):
                for name, new in (("k", k), ("v", v)):
                    qv, sc = _quant_rows(new[:, :, 0, :])  # [b, H, dk]
                    pool[name] = pool[name].at[idx, pg, :, off, :].set(qv)
                    pool[name + "_scale"] = \
                        pool[name + "_scale"].at[idx, pg, :, off].set(sc)
            else:
                dt = pool["k"].dtype
                pool["k"] = pool["k"].at[idx, pg, :, off, :].set(
                    k[:, :, 0, :].astype(dt))
                pool["v"] = pool["v"].at[idx, pg, :, off, :].set(
                    v[:, :, 0, :].astype(dt))
            keys = _gather_kv(pool, "k", idx, tables)  # [b, H, S, dk]
            vals = _gather_kv(pool, "v", idx, tables)
            scale = 1.0 / math.sqrt(layer.key_dim)
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           keys.astype(jnp.float32)) * scale
            valid = key_pos[None, :] <= pos[:, None]      # [b, S]
            s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
            prob = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", prob,
                             vals.astype(jnp.float32)).astype(q.dtype)
            x = _attn_out(layer, p, out)
        else:  # "embed" / "point"
            _, layer, path = op
            x, _ = layer.apply(_params_at(params, path), {}, x)
    return pool, x[:, 0, :].astype(jnp.float32)  # [b, vocab]


def paged_decode_step(plan: DecodePlan, params, pool: dict, page_tables,
                      tokens, lengths, *, bucket: int):
    """One generated token for the first ``bucket`` slots through the
    page tables.

    The new K/V land at offset ``length % page_size`` of the slot's tail
    page ``page_tables[slot, length // page_size]``; attention then runs
    over the gathered pages under the same ``arange <= pos`` validity
    mask as the contiguous path. Inactive slots inside the bucket must
    have all-scratch table rows so their garbage writes are absorbed.

    Args:
      page_tables: int32 ``[cap, max_pages]``; only ``[:bucket]`` read.
      tokens / lengths / bucket: as :func:`decode_step`.

    Returns:
      ``(pool, logits)`` with logits ``[bucket, vocab]`` fp32.
    """
    return _paged_decode_core(plan, params, pool, page_tables[:bucket],
                              tokens[:bucket], lengths[:bucket],
                              lambda pg: pg)


def paged_decode_ragged(plan: DecodePlan, params, pool: dict, page_tables,
                        tokens, lengths, active):
    """One generated token for every ACTIVE slot, full capacity in one
    program.

    The ragged replacement for the pow2-bucket program family: the page-
    table gather already erased contiguity, so batch size can be the
    engine's whole slot capacity with per-slot masking — ONE compiled
    decode program, zero steady-state retrace. Inactive rows (empty
    slots, slots mid-chunked-prefill whose table rows hold REAL pages)
    have their tail writes routed to the scratch page and their logits
    are garbage the host never reads; active rows compute exactly what
    :func:`paged_decode_step` computes for them, so ragged and bucketed
    streams are token-identical (tests pin it).

    Args:
      page_tables: int32 ``[cap, max_pages]``.
      tokens / lengths: int32 ``[cap]``, all rows read, inactive ignored.
      active: bool ``[cap]`` — which slots are really decoding.

    Returns:
      ``(pool, logits)`` with logits ``[cap, vocab]`` fp32.
    """
    num_pages = pool["k"].shape[1] - 1     # last row is scratch
    return _paged_decode_core(plan, params, pool, page_tables, tokens,
                              lengths,
                              lambda pg: jnp.where(active, pg, num_pages))


def copy_page(pool: dict, src, dst):
    """Copy page row ``src`` over ``dst`` (every layer, k and v — and,
    for int8 pools, the fp32 scale rows riding in the same pytree) — the
    device half of copy-on-write: the allocator clones a shared
    prefix-cache page into a private one the moment a request needs to
    write into it. ``src``/``dst`` are traced scalars: one compiled
    program serves every copy."""
    out = {}
    for name, a in pool.items():
        out[name] = a.at[:, dst].set(jnp.take(a, src, axis=1))
    return out
