"""ServeFleet: N supervised ServeEngine replicas behind one host router.

The single-engine serving stack (``serve/engine.py``) caps out at one
submesh of traffic.  This module scales the *same program* sideways —
PAPER.md's "millions of users" direction — by running N replica workers,
each a daemon thread that owns one :class:`ServeEngine` (optionally on a
leased submesh via the jobs runtime), fronted by a main-thread router:

* **Prefix-affinity routing** — the router keys every request by the
  chained digest of its *full-page* prompt prefix, computed with
  :meth:`PrefixCache.prompt_digest` (the exact key under which the
  paged KV prefix cache holds those pages warm), and routes same-prefix
  sessions to the replica whose pages are warm.  Unknown prefixes — and
  prompts shorter than one page, which have no reusable pages — fall
  back to the least-loaded replica (lowest outstanding count, lowest
  index on ties), and known prefixes stick there.
* **Journal-backed failover** — each replica journals to its own
  directory.  When a replica dies, the router joins its thread, loads
  the journal from disk (torn trailing lines are skipped by
  ``journal.load``, same as solo recovery), and re-adopts every still
  in-flight request onto a survivor via
  :meth:`ServeEngine.adopt_request` — which reserves a **fresh rid**
  through ``Scheduler.reserve_rid`` so two dead replicas' overlapping
  rid spaces can merge onto one survivor without collisions.  Tokens
  that reached the dead replica's journal are replayed (greedy
  re-prefill continues the stream token-identically); tokens lost in
  the unflushed tail are simply regenerated — greedy decode is
  batch-composition-independent, so the final stream is bit-identical
  either way.  Survivors are never restarted: blast radius zero.
* **Autoscaling** — :meth:`ServeFleet.autoscale_tick` applies a
  deterministic :class:`AutoscalePolicy` over router-side queue depth
  and the projected-TTFT signal (``owed / (replicas * max_batch) *
  step_ema``), spawning replicas up to ``max_replicas`` and retiring
  idle ones down to ``min_replicas``.

Fault grammar (``resilience/faults.py``): ``replica_kill@reqN:replicaR``
kills replica R in-process at its N-th completion — *before* the journal
flush, so the unflushed tail is genuinely lost, like a process death —
and ``router_storm@reqN:xM`` injects an M-request chaff burst through
the router at submission index N.  Both are armed only here; the solo
chaos driver rejects them.

Threading contract (shardcheck SC4xx/SC5xx): each engine is constructed
AND stepped only on its worker thread (thread-confined); the router
talks to workers through a command inbox and a shared event queue, and
reads the small shared worker state (rid map, stats) under the worker's
lock.  All router state (affinity map, outstanding counters, in-flight
tables) is main-thread-only.  After ``join()`` a worker's engine is
quiescent and safe to read directly (e.g. ``compiled_programs()``).

Observe: ``fleet.replicas`` gauge, ``fleet.route.affinity_hits`` /
``fleet.route.affinity_overridden`` / ``fleet.route.fallback`` /
``fleet.failover.replayed`` counters, ``fleet.autoscale.up`` /
``fleet.autoscale.down``.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import pathlib
import queue
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from tpu_dist.observe import metrics
from tpu_dist.resilience import events
from tpu_dist.resilience.faults import (FLEET_KINDS, FaultPlan, FaultSpec,
                                        describe as describe_faults)
from tpu_dist.serve import journal as journal_lib
from tpu_dist.serve.paging import PrefixCache
from tpu_dist.serve.scheduler import ACTIVE, DONE, QUEUED

logger = logging.getLogger("tpu_dist.serve.fleet")

__all__ = [
    "AutoscalePolicy",
    "FleetRequest",
    "ReplicaKilled",
    "ReplicaWorker",
    "ServeFleet",
    "run_fleet",
]


class ReplicaKilled(RuntimeError):
    """Raised inside a replica worker by an armed ``replica_kill`` fault.

    Raised from the engine's ``fault_injector.on_step_end`` hook, which
    runs *before* ``journal.flush()`` — so the step's journal records
    are lost with the replica, exactly like a process kill between a
    decode step and its fsync.
    """


class FleetFaultInjector:
    """Per-replica injector for fleet fault kinds (duck-typed on the
    engine's ``on_decode`` / ``on_step_end`` hook protocol).

    Only ``replica_kill`` specs addressed at this replica index are
    armed; everything else in the plan is the router's business.  The
    solo :class:`ServeFaultInjector` never arms fleet kinds
    (``ENGINE_KINDS`` is unchanged), so the two grammars cannot cross.
    """

    def __init__(self, replica: int, faults: Sequence[FaultSpec] = ()):
        self.replica = replica
        self.faults = [
            f for f in faults
            if f.kind == "replica_kill"
            and (0 if f.replica is None else f.replica) == replica
        ]
        self.fired: List[dict] = []
        for f in self.faults:
            events.maybe_log("fault_armed", kind=f.kind, req=f.req,
                             replica=replica)

    def on_decode(self) -> None:
        """No decode-time faults in the fleet grammar."""

    def on_step_end(self, done_count: int) -> None:
        for f in self.faults:
            if (f.due_at_req(done_count)
                    and not any(r["req"] == f.req for r in self.fired)):
                rec = {"kind": "replica_kill", "req": f.req,
                       "replica": self.replica, "done": done_count}
                self.fired.append(rec)
                events.maybe_log("fault_fired", **rec)
                raise ReplicaKilled(
                    f"replica {self.replica} killed at done_count="
                    f"{done_count} (replica_kill@req{f.req})")


@dataclasses.dataclass
class FleetRequest:
    """Router-side view of one request across its whole fleet lifetime
    (the engine-side :class:`Request` is per-replica and dies with it)."""

    frid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    deadline_s: Optional[float]
    #: full-page prefix-chain digest (the affinity key), or None when
    #: the prompt is shorter than one page (no reusable pages).
    digest: Optional[bytes]
    replica: int = -1
    route: Optional[str] = None      # affinity | overridden | fallback
    chaff: bool = False              # router_storm filler
    failovers: int = 0
    status: Optional[str] = None     # terminal engine status, or "rejected"
    finish_reason: Optional[str] = None
    shed_cause: Optional[str] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    rid: Optional[int] = None        # rid on the replica that finished it
    latency_s: Optional[float] = None


class ReplicaWorker:
    """One supervised replica: a daemon thread that owns one ServeEngine.

    The engine is built by ``factory(index, journal=..., fault_injector=
    ...)`` *on the worker thread* and never touched by another thread
    while the worker is alive.  Communication is one-way queues: the
    router posts ``("submit", fr)`` / ``("adopt", fr, generated,
    replays)`` commands into the inbox; the worker publishes ``("done",
    index, frid, req)``, ``("rejected", index, frid, why)``, ``("dead",
    index, why, killed)`` and ``("retired", index)`` events onto the
    fleet-shared event queue.  The rid→frid map and a small stats
    snapshot are shared under ``self._lock``.

    A ``replica_kill`` fault (or any unexpected exception) abandons the
    engine without flushing or closing its journal — the on-disk journal
    is missing the unflushed tail on purpose, so failover recovery has
    to work from durable state alone, like after a real process death.
    """

    def __init__(self, index: int, factory: Callable, *,
                 events_q: "queue.Queue", poll_s: float = 0.005,
                 faults: Sequence[FaultSpec] = (),
                 journal_dir: Optional[str] = None,
                 runtime=None, spec=None):
        self.index = index
        self._factory = factory
        self._events = events_q
        self._poll_s = float(poll_s)
        self.journal_dir = journal_dir
        self.injector = FleetFaultInjector(index, faults)
        self._runtime = runtime          # MeshRuntime, or None (no lease)
        self._spec = spec                # JobSpec for the lease, or None
        self._inbox: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-replica-{index}", daemon=True)
        # Shared worker state (written on the worker thread under _lock;
        # read by the router under _lock, or freely after join()).
        self.engine = None
        self.dead = False
        self.killed = False
        self.death: Optional[str] = None
        #: supervised-restart count — the chaos gate pins this at 0 for
        #: survivors (failover must not restart healthy replicas).
        self.restarts = 0
        self.stats: dict = {}
        self._rid_map: Dict[int, int] = {}   # engine rid -> frid
        self._published = 0                  # index into engine.finished

    # -- router-side API ------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def post(self, cmd: tuple) -> None:
        self._inbox.put(cmd)

    def stop(self) -> None:
        """Ask for graceful retirement: drain accepted work, then exit."""
        self._stop.set()

    def join(self, timeout_s: float = 10.0) -> bool:
        self._thread.join(timeout_s)
        return not self._thread.is_alive()

    def alive(self) -> bool:
        with self._lock:
            return not self.dead

    def rid_map(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._rid_map)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)

    # -- worker thread --------------------------------------------------------

    def _run(self) -> None:
        try:
            if self._runtime is not None and self._spec is not None:
                from tpu_dist.jobs.runtime import job_scope
                with job_scope(self._runtime, self._spec):
                    self._serve()
            else:
                self._serve()
        except ReplicaKilled as exc:
            self._die(str(exc), killed=True)
        except BaseException as exc:  # replica death is data, not a crash
            logger.exception("fleet: replica %d died", self.index)
            self._die(f"{type(exc).__name__}: {exc}", killed=False)

    def _serve(self) -> None:
        engine = self._factory(self.index, journal=self.journal_dir,
                               fault_injector=self.injector)
        with self._lock:
            self.engine = engine
        while not self._stop.is_set():
            moved = self._drain_inbox(engine)
            if engine.scheduler.idle():
                if not moved:
                    try:
                        # Park until the next command; bounded so the
                        # stop flag is re-checked every poll interval.
                        cmd = self._inbox.get(True, self._poll_s)
                    except queue.Empty:
                        continue
                    self._apply(engine, cmd)
                self._publish(engine)
                continue
            engine.step()
            self._publish(engine)
        # Graceful retirement: finish everything already accepted.
        while not engine.scheduler.idle():
            engine.step()
            self._publish(engine)
        engine.close()
        self._publish(engine)
        with self._lock:
            self.dead = True
            self.death = "retired"
        self._events.put(("retired", self.index))

    def _die(self, why: str, *, killed: bool) -> None:
        # The engine is abandoned un-flushed and un-closed on purpose:
        # an injected kill must look like a process death, so the
        # on-disk journal is missing the unflushed tail and failover
        # has to recover from durable state alone.
        with self._lock:
            self.dead = True
            self.killed = killed
            self.death = why
        self._events.put(("dead", self.index, why, killed))

    def _drain_inbox(self, engine) -> bool:
        moved = False
        while True:
            try:
                cmd = self._inbox.get_nowait()
            except queue.Empty:
                return moved
            self._apply(engine, cmd)
            moved = True

    def _apply(self, engine, cmd: tuple) -> None:
        op = cmd[0]
        if op == "submit":
            fr = cmd[1]
            try:
                req = engine.submit(fr.prompt,
                                    max_new_tokens=fr.max_new_tokens,
                                    eos_id=fr.eos_id,
                                    deadline_s=fr.deadline_s)
            except ValueError as exc:
                self._events.put(("rejected", self.index, fr.frid, str(exc)))
                return
        elif op == "adopt":
            fr, generated, replays = cmd[1], cmd[2], cmd[3]
            try:
                req = engine.adopt_request(fr.prompt, generated=generated,
                                           max_new_tokens=fr.max_new_tokens,
                                           eos_id=fr.eos_id,
                                           deadline_s=fr.deadline_s,
                                           replays=replays)
            except ValueError as exc:
                self._events.put(("rejected", self.index, fr.frid, str(exc)))
                return
        else:
            raise RuntimeError(f"fleet: unknown worker command {op!r}")
        with self._lock:
            self._rid_map[req.rid] = fr.frid
        # Shed-on-submit and adopt-to-done are terminal immediately
        # (already in engine.finished) — surface them without waiting
        # for the next step.
        if req.status not in (QUEUED, ACTIVE):
            self._publish(engine)

    def _publish(self, engine) -> None:
        new = engine.finished[self._published:]
        self._published = len(engine.finished)
        with self._lock:
            self.stats = {
                "done": len(engine.finished),
                "step_ema_s": engine._step_ema_s,
                "queue_depth": engine.scheduler.queue_depth(),
                "active": engine.scheduler.num_active,
                "max_batch": engine.max_batch,
            }
            frids = [self._rid_map.get(req.rid) for req in new]
        for req, frid in zip(new, frids):
            self._events.put(("done", self.index, frid, req))


@dataclasses.dataclass
class AutoscalePolicy:
    """Deterministic scale decisions from router-side signals.

    Scale **up** when every live replica's outstanding count reaches
    ``scale_up_outstanding`` (backlog nowhere to shed to), or when the
    projected TTFT — ``sum(outstanding) / (replicas * max_batch) *
    step_ema`` , the fleet-level analog of the engine's admission
    signal — exceeds ``ttft_target_s``.  Scale **down** when a replica
    has been idle (zero outstanding) for ``idle_ticks_down``
    consecutive ticks AND no other replica holds more than
    ``scale_down_max_load`` — retiring idle capacity while the rest of
    the fleet is backlogged would just re-trigger scale-up (thrash).
    Bounded by ``min_replicas``/``max_replicas``.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_outstanding: int = 8
    ttft_target_s: Optional[float] = None
    idle_ticks_down: int = 50
    scale_down_max_load: int = 0

    def decide(self, *, outstanding: Dict[int, int],
               idle_ticks: Dict[int, int],
               step_ema_s: Optional[float],
               max_batch: int) -> tuple:
        """Return ``(action, target, why)`` with action in
        ``{"up", "down", "hold"}``; target is the replica index to
        retire for ``"down"``, else ``None``."""
        n = len(outstanding)
        if n < self.max_replicas and n > 0:
            if min(outstanding.values()) >= self.scale_up_outstanding:
                return ("up", None,
                        f"backlog >= {self.scale_up_outstanding} on every "
                        f"replica")
            if self.ttft_target_s is not None and step_ema_s:
                owed = sum(outstanding.values())
                projected = (owed / max(n * max_batch, 1)) * step_ema_s
                if projected > self.ttft_target_s:
                    return ("up", None,
                            f"projected TTFT {projected:.4f}s > "
                            f"{self.ttft_target_s}s")
        if n > self.min_replicas:
            idle = [i for i in sorted(outstanding)
                    if idle_ticks.get(i, 0) >= self.idle_ticks_down]
            if idle:
                # Retire the highest index: lowest indices hold the
                # oldest prefix affinities.
                cand = idle[-1]
                others = [v for i, v in outstanding.items() if i != cand]
                if not others or max(others) <= self.scale_down_max_load:
                    return ("down", cand,
                            f"idle for {self.idle_ticks_down} ticks")
        return ("hold", None, "")


class ServeFleet:
    """Main-thread router over :class:`ReplicaWorker` replicas.

    All router state lives on the calling thread; the only cross-thread
    traffic is the per-worker command inbox and the shared event queue.
    Typical use::

        fleet = ServeFleet(factory, replicas=2)
        fleet.start()
        frs = [fleet.submit(p) for p in prompts]
        fleet.drain()
        fleet.close()
        programs = fleet.compiled_programs()   # safe: threads joined

    ``factory(replica_index, *, journal, fault_injector)`` must build a
    fresh ServeEngine; it runs on the worker thread.
    """

    def __init__(self, factory: Callable, *, replicas: int = 2,
                 page_size: int = 16,
                 journal_root: Optional[str] = None,
                 plan: Optional[FaultPlan] = None,
                 autoscale: Optional[AutoscalePolicy] = None,
                 devices_per_replica: Optional[int] = None,
                 runtime=None, storm_vocab: int = 128,
                 storm_seed: int = 0, poll_s: float = 0.005,
                 affinity_load_slack: Optional[int] = 8):
        if replicas < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {replicas}")
        self._factory = factory
        self._page_size = int(page_size)
        self._poll_s = float(poll_s)
        self._autoscale = autoscale
        self._devices_per_replica = devices_per_replica
        self._runtime = runtime
        self._storm_vocab = int(storm_vocab)
        self._storm_seed = int(storm_seed)
        if journal_root is None:
            journal_root = tempfile.mkdtemp(prefix="tpu-dist-fleet-")
        self._journal_root = pathlib.Path(journal_root)
        plan = plan or FaultPlan()
        self._kill_faults = [f for f in plan.faults
                             if f.kind == "replica_kill"]
        self._storm_faults = [f for f in plan.faults
                              if f.kind == "router_storm"]
        foreign = [f for f in plan.faults if f.kind not in FLEET_KINDS]
        if foreign:
            raise ValueError(
                f"fleet plan contains non-fleet fault kinds "
                f"{sorted({f.kind for f in foreign})}; run those through "
                f"--chaos against a solo engine")
        self._storm_fired: List[dict] = []
        self._workers: Dict[int, ReplicaWorker] = {}
        self._retiring: set = set()
        self._events: "queue.Queue" = queue.Queue()
        self._affinity: Dict[bytes, int] = {}
        self._outstanding: Dict[int, int] = {}
        self._inflight: Dict[int, Dict[int, FleetRequest]] = {}
        self._idle_ticks: Dict[int, int] = {}
        self._frid = itertools.count()
        self._submit_index = 0
        self._initial = int(replicas)
        self._next_index = int(replicas)
        self.requests: Dict[int, FleetRequest] = {}
        # Hot-prefix load shed: affinity stops being a hard pin once the
        # pinned replica is this many outstanding requests ahead of the
        # least-loaded one (None disables the override entirely).
        self._affinity_load_slack = (None if affinity_load_slack is None
                                     else max(0, int(affinity_load_slack)))
        self.route_counts = {"affinity": 0, "fallback": 0,
                             "affinity_overridden": 0}
        self.failover_replayed = 0
        self.deaths: List[dict] = []
        self.autoscale_events: List[dict] = []

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        for i in range(self._initial):
            self._spawn(i)

    def _spawn(self, index: int) -> ReplicaWorker:
        jdir = self._journal_root / f"replica-{index}"
        jdir.mkdir(parents=True, exist_ok=True)
        runtime = None
        spec = None
        if self._devices_per_replica:
            runtime = self._ensure_runtime()
            from tpu_dist.jobs.spec import JobSpec
            spec = JobSpec(name=f"fleet-r{index}", kind="serve",
                           devices=int(self._devices_per_replica))
        w = ReplicaWorker(index, self._factory, events_q=self._events,
                          poll_s=self._poll_s, faults=self._kill_faults,
                          journal_dir=str(jdir), runtime=runtime, spec=spec)
        self._workers[index] = w
        self._outstanding[index] = 0
        self._inflight[index] = {}
        self._idle_ticks[index] = 0
        w.start()
        metrics.set_gauge("fleet.replicas", float(len(self.alive_indices())))
        return w

    def _ensure_runtime(self):
        if self._runtime is None:
            from tpu_dist.jobs.runtime import MeshRuntime
            self._runtime = MeshRuntime()
        return self._runtime

    def close(self, *, timeout_s: float = 30.0) -> None:
        """Gracefully retire every replica: drain accepted work, flush
        journals, join threads.  After this the fleet is quiescent."""
        for w in self._workers.values():
            w.stop()
        stuck = [w.index for w in self._workers.values()
                 if not w.join(timeout_s)]
        if stuck:
            raise TimeoutError(
                f"fleet: replica thread(s) {stuck} did not exit within "
                f"{timeout_s}s")
        metrics.set_gauge("fleet.replicas", 0.0)

    def alive_indices(self) -> List[int]:
        return sorted(i for i, w in self._workers.items()
                      if i not in self._retiring and w.alive())

    # -- routing --------------------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               chaff: bool = False) -> FleetRequest:
        """Route one request: prefix-affinity first, least-loaded
        fallback.  Returns the router-side :class:`FleetRequest`;
        terminal state lands on it during :meth:`drain`."""
        if not chaff:
            self._maybe_storm()
        # Affinity keys on the *full-page* prefix chain — exactly the
        # pages the prefix cache can hold warm across requests. The
        # ragged tail never lands in a reusable full page, so it does
        # not contribute to warmth; prompts shorter than one page have
        # no reusable pages at all and route stateless (least-loaded).
        k_full = len(prompt) // self._page_size
        digest = (PrefixCache.prompt_digest(
            list(prompt)[:k_full * self._page_size], self._page_size)
            if k_full else None)
        fr = FleetRequest(frid=next(self._frid),
                          prompt=[int(t) for t in prompt],
                          max_new_tokens=int(max_new_tokens),
                          eos_id=eos_id, deadline_s=deadline_s,
                          digest=digest, chaff=chaff)
        self.requests[fr.frid] = fr
        self._submit_index += 1
        self._route(fr)
        return fr

    def _route(self, fr: FleetRequest) -> None:
        alive = self.alive_indices()
        if not alive:
            self._reap(block=True)
            alive = self.alive_indices()
            if not alive:
                raise RuntimeError("fleet: no live replicas to route to")
        target = (self._affinity.get(fr.digest)
                  if fr.digest is not None else None)
        if target is not None and target in alive:
            coldest = min(alive, key=lambda i: (self._outstanding[i], i))
            if (self._affinity_load_slack is not None
                    and self._outstanding[target]
                    - self._outstanding[coldest]
                    > self._affinity_load_slack):
                # Hot-prefix load shed: warmth is not worth queueing this
                # far behind the coldest replica. Route there for THIS
                # request only — the affinity pin stays on the hot
                # replica, so routing snaps back once its queue drains
                # instead of migrating the prefix on a transient spike.
                target = coldest
                fr.route = "overridden"
                self.route_counts["affinity_overridden"] += 1
                metrics.inc("fleet.route.affinity_overridden")
            else:
                fr.route = "affinity"
                self.route_counts["affinity"] += 1
                metrics.inc("fleet.route.affinity_hits")
        else:
            target = min(alive, key=lambda i: (self._outstanding[i], i))
            fr.route = "fallback"
            self.route_counts["fallback"] += 1
            metrics.inc("fleet.route.fallback")
            if fr.digest is not None:
                self._affinity[fr.digest] = target
        fr.replica = target
        self._outstanding[target] += 1
        self._inflight[target][fr.frid] = fr
        self._workers[target].post(("submit", fr))

    def _maybe_storm(self) -> None:
        for f in self._storm_faults:
            if (f.due_at_req(self._submit_index)
                    and not any(r["req"] == f.req
                                for r in self._storm_fired)):
                rec = {"kind": "router_storm", "req": f.req,
                       "count": f.count, "at_index": self._submit_index}
                self._storm_fired.append(rec)
                events.maybe_log("fault_fired", **rec)
                metrics.inc("fleet.router_storm.injected", f.count)
                # Seeded chaff: short prompts, tiny budgets — load, not
                # output. Deterministic per (seed, storm index).
                import numpy as np
                rng = np.random.default_rng(
                    self._storm_seed + 7919 * f.req)
                for _ in range(f.count):
                    plen = int(rng.integers(1, self._page_size + 1))
                    self.submit(
                        rng.integers(0, self._storm_vocab,
                                     size=plen).tolist(),
                        max_new_tokens=int(rng.integers(1, 5)),
                        chaff=True)

    # -- event pump / failover ------------------------------------------------

    def pending(self) -> int:
        return sum(len(m) for m in self._inflight.values())

    def drain(self, *, timeout_s: float = 120.0) -> None:
        """Pump events until every routed request is terminal.  Runs
        autoscale ticks opportunistically when a policy is configured."""
        deadline = time.monotonic() + timeout_s
        while self.pending() > 0:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet: {self.pending()} request(s) still in flight "
                    f"after {timeout_s}s; deaths={self.deaths}")
            self._pump(0.05)
            if self._autoscale is not None:
                self.autoscale_tick()

    def _pump(self, timeout_s: float) -> bool:
        try:
            ev = self._events.get(True, timeout_s)
        except queue.Empty:
            return False
        self._handle(ev)
        while True:
            try:
                ev = self._events.get_nowait()
            except queue.Empty:
                return True
            self._handle(ev)

    def _reap(self, *, block: bool = False) -> None:
        """Drain pending events (used before routing when no replica
        looks alive — a death event may simply not be handled yet)."""
        self._pump(1.0 if block else 0.0)

    def _handle(self, ev: tuple) -> None:
        kind = ev[0]
        if kind == "done":
            _, idx, frid, req = ev
            fr = self._inflight.get(idx, {}).pop(frid, None)
            if fr is None:
                return  # finished during failover handoff; already settled
            self._outstanding[idx] = max(self._outstanding[idx] - 1, 0)
            fr.status = req.status
            fr.finish_reason = req.finish_reason
            fr.shed_cause = req.shed_cause
            fr.tokens = list(req.generated)
            fr.rid = req.rid
            fr.latency_s = req.latency_s
        elif kind == "rejected":
            _, idx, frid, why = ev
            fr = self._inflight.get(idx, {}).pop(frid, None)
            if fr is not None:
                self._outstanding[idx] = max(self._outstanding[idx] - 1, 0)
                fr.status = "rejected"
                fr.finish_reason = why
        elif kind == "dead":
            _, idx, why, killed = ev
            self._failover(idx, why=why, killed=killed)
        elif kind == "retired":
            _, idx = ev
            self._retiring.discard(idx)
            metrics.set_gauge("fleet.replicas",
                              float(len(self.alive_indices())))

    def _failover(self, idx: int, *, why: str, killed: bool) -> None:
        """Replay a dead replica's in-flight requests onto survivors.

        The worker thread is joined first, so its journal file is stable
        and its rid map is safe to read.  Requests whose submit never
        reached the journal (lost in the unflushed tail, or still queued
        in the inbox) replay from the router's own copy with zero
        generated tokens — greedy decode regenerates the identical
        stream.  Requests with journaled tokens resume mid-stream via
        ``adopt_request``, which reserves a fresh rid on the survivor
        (the collision guard when two dead replicas' rid spaces merge).
        """
        w = self._workers[idx]
        w.join(10.0)
        self.deaths.append({"replica": idx, "why": why, "killed": killed,
                            "fired": list(w.injector.fired)})
        metrics.set_gauge("fleet.replicas", float(len(self.alive_indices())))
        logger.warning("fleet: replica %d dead (%s); failing over %d "
                       "request(s)", idx, why, len(self._inflight[idx]))
        orphans = sorted(self._inflight[idx].values(), key=lambda f: f.frid)
        self._inflight[idx] = {}
        self._outstanding[idx] = 0
        # Torn trailing lines (a kill can land mid-append) are skipped
        # by journal.load — same tolerance as solo recovery.
        state = journal_lib.load(
            pathlib.Path(w.journal_dir) / journal_lib.JOURNAL_NAME)
        by_frid: Dict[int, journal_lib.JournaledRequest] = {}
        for rid, frid in w.rid_map().items():
            jr = state.requests.get(rid)
            if jr is not None:
                by_frid[frid] = jr
        for fr in orphans:
            jr = by_frid.get(fr.frid)
            generated = list(jr.tokens) if jr is not None else []
            self._adopt(fr, generated=generated)

    def _adopt(self, fr: FleetRequest, *, generated: List[int]) -> None:
        survivors = self.alive_indices()
        if not survivors:
            raise RuntimeError(
                f"fleet: request frid={fr.frid} orphaned with no "
                f"surviving replicas")
        target = min(survivors, key=lambda i: (self._outstanding[i], i))
        fr.failovers += 1
        fr.replica = target
        # The session's warm pages died with the replica; future
        # same-prefix requests should follow the adopted work.
        if fr.digest is not None:
            self._affinity[fr.digest] = target
        self._outstanding[target] += 1
        self._inflight[target][fr.frid] = fr
        self.failover_replayed += 1
        metrics.inc("fleet.failover.replayed")
        self._workers[target].post(
            ("adopt", fr, list(generated), fr.failovers - 1))

    # -- autoscaling ----------------------------------------------------------

    def autoscale_tick(self) -> Optional[str]:
        """Apply one deterministic autoscale decision; returns the
        action taken (``"up"``/``"down"``) or None."""
        if self._autoscale is None:
            return None
        alive = self.alive_indices()
        if not alive:
            return None
        for i in alive:
            if self._outstanding[i] == 0:
                self._idle_ticks[i] += 1
            else:
                self._idle_ticks[i] = 0
        outstanding = {i: self._outstanding[i] for i in alive}
        emas = [s.get("step_ema_s") for s in
                (self._workers[i].snapshot() for i in alive)]
        emas = [e for e in emas if e]
        batches = [self._workers[i].snapshot().get("max_batch") or 0
                   for i in alive]
        action, target, why = self._autoscale.decide(
            outstanding=outstanding,
            idle_ticks={i: self._idle_ticks[i] for i in alive},
            step_ema_s=(sum(emas) / len(emas)) if emas else None,
            max_batch=max(batches) if any(batches) else 1)
        if action == "up":
            index = self._next_index
            self._next_index += 1
            self._spawn(index)
            metrics.inc("fleet.autoscale.up")
            self.autoscale_events.append(
                {"action": "up", "replica": index, "why": why})
            logger.info("fleet: autoscale up -> replica %d (%s)", index, why)
            return "up"
        if action == "down":
            # Only retire a truly idle replica; routing excludes it from
            # this tick on, so no command can land after stop().
            if self._outstanding.get(target, 0) == 0:
                self._retiring.add(target)
                self._workers[target].stop()
                metrics.inc("fleet.autoscale.down")
                self.autoscale_events.append(
                    {"action": "down", "replica": target, "why": why})
                logger.info("fleet: autoscale down -> retire replica %d "
                            "(%s)", target, why)
                return "down"
        return None

    # -- post-quiescence inspection ------------------------------------------

    def compiled_programs(self) -> Dict[int, dict]:
        """Per-replica ``ServeEngine.compiled_programs()``.  Call only
        after :meth:`close` (or after a replica died and was joined) —
        engines are thread-confined while their worker runs."""
        out: Dict[int, dict] = {}
        for i, w in sorted(self._workers.items()):
            if w.alive():
                raise RuntimeError(
                    f"fleet: replica {i} still running; close() first")
            if w.engine is not None:
                out[i] = w.engine.compiled_programs()
        return out

    def report(self) -> dict:
        frs = sorted(self.requests.values(), key=lambda f: f.frid)
        real = [f for f in frs if not f.chaff]
        chaff = [f for f in frs if f.chaff]
        lats = sorted(f.latency_s for f in real
                      if f.status == DONE and f.latency_s is not None)
        p99 = lats[min(len(lats) - 1,
                       int(0.99 * len(lats)))] if lats else None
        return {
            "replicas_started": len(self._workers),
            "replicas": {
                i: {"dead": not w.alive(), "killed": w.killed,
                    "death": w.death, "restarts": w.restarts,
                    "stats": w.snapshot()}
                for i, w in sorted(self._workers.items())
            },
            "requests": len(real),
            "chaff": len(chaff),
            "done": sum(1 for f in real if f.status == DONE),
            "shed": sum(1 for f in real
                        if f.status is not None and f.status != DONE),
            "route": dict(self.route_counts),
            "failover_replayed": self.failover_replayed,
            "deaths": list(self.deaths),
            "storm_fired": list(self._storm_fired),
            "autoscale": list(self.autoscale_events),
            "p99_latency_s": p99,
        }


# -- CLI driver ---------------------------------------------------------------


def _fleet_workload(args, *, sessions: int, page_size: int) -> list:
    """Sessioned synthetic stream: ``sessions`` distinct full-page
    prefixes, each request is its session's prefix plus a ragged seeded
    suffix — so repeat visits to a session are affinity hits and first
    visits are fallbacks (the bench's anti-vacuity gates).

    Suffix lengths and token budgets follow one seeded *per-visit*
    schedule shared by every session, so sessions are work-identical by
    construction: any session-granular routing split carries the same
    decode load, and the throughput-scaling gate measures routing, not
    workload luck.  Token contents stay per-request random.
    """
    import numpy as np
    rng = np.random.default_rng(args.seed)
    prefixes = [rng.integers(0, args.vocab, size=page_size).tolist()
                for _ in range(sessions)]
    max_suffix = max(2, args.max_len // 8)
    visits = -(-args.requests // sessions)  # ceil
    suffix_lens = [int(rng.integers(1, max_suffix)) for _ in range(visits)]
    budgets = [int(rng.integers(args.min_new, args.max_new + 1))
               for _ in range(visits)]
    out = []
    for i in range(args.requests):
        s, v = i % sessions, i // sessions
        suffix = rng.integers(0, args.vocab, size=suffix_lens[v]).tolist()
        out.append({
            "session": s,
            "prompt": prefixes[s] + suffix,
            "max_new_tokens": budgets[v],
        })
    return out


def run_fleet(args) -> int:
    """``python -m tpu_dist.serve --fleet``: run the sessioned workload
    through a fleet, compare every token stream against an uninterrupted
    solo baseline, and gate on routing/failover/pinning invariants."""
    from tpu_dist.serve.cli import _build_engine

    metrics.get_registry().reset()
    metrics.enable()
    plan = (FaultPlan.parse(args.plan)
            if getattr(args, "plan", None) else FaultPlan())
    foreign = sorted({f.kind for f in plan.faults
                      if f.kind not in FLEET_KINDS})
    if foreign:
        print(f"error: fault kind(s) {foreign} target a solo engine; "
              f"run them through --chaos, not --fleet", file=sys.stderr)
        return 2
    page_size = args.page_size
    sessions = max(1, int(args.fleet_sessions))
    workload = _fleet_workload(args, sessions=sessions, page_size=page_size)

    def factory(replica, *, journal, fault_injector):
        del replica
        return _build_engine(args, journal=journal,
                             fault_injector=fault_injector,
                             max_queue=args.max_queue,
                             retry_budget=args.retry_budget)

    # Uninterrupted solo baseline: greedy decode is batch-composition
    # independent, so per-request streams are the fleet's ground truth.
    print(f"fleet: baseline — solo engine, {len(workload)} requests")
    solo = _build_engine(args)
    solo_reqs = [solo.submit(w["prompt"],
                             max_new_tokens=w["max_new_tokens"])
                 for w in workload]
    solo.run_until_idle()
    baseline = [list(r.generated) for r in solo_reqs]
    solo_programs = solo.compiled_programs()
    solo_buckets = tuple(solo.scheduler.buckets)
    solo.close()

    workdir = getattr(args, "workdir", None)
    journal_root = os.path.join(workdir, "fleet-journals") if workdir else None
    fleet = ServeFleet(factory, replicas=args.fleet_replicas,
                       page_size=page_size, journal_root=journal_root,
                       plan=plan,
                       devices_per_replica=args.devices_per_replica,
                       storm_vocab=args.vocab, storm_seed=args.seed)
    print(f"fleet: {args.fleet_replicas} replica(s), {sessions} session(s), "
          f"plan={'; '.join(describe_faults(plan)) if plan.faults else 'none'}")
    fleet.start()
    frs = [fleet.submit(w["prompt"], max_new_tokens=w["max_new_tokens"])
           for w in workload]
    fleet.drain(timeout_s=args.deadline)
    fleet.close()
    report = fleet.report()
    programs = fleet.compiled_programs()

    gates = {}
    # Every admitted (non-chaff) request reaches DONE.
    gates["all_done"] = all(fr.status == DONE for fr in frs)
    # Token parity with the uninterrupted solo baseline, bit-identical.
    gates["token_parity"] = all(
        fr.tokens == base for fr, base in zip(frs, baseline))
    # Survivors never restarted: blast radius zero.
    gates["survivors_zero_restarts"] = all(
        w.restarts == 0 for w in fleet._workers.values() if not w.killed)
    # Steady-state router adds no device programs.  With one healthy
    # replica the pin is exact: same workload, same order, so the
    # program dict must be bit-identical to the solo engine's.  With
    # N > 1 each replica sees a different concurrency profile (decode
    # buckets track active count), so the pin is containment in the
    # engine's *static* program universe — the configured bucket ladder
    # and the pow2 prompt-pad ladder — i.e. routing/failover never
    # introduces a program shape a solo engine could not compile.
    if args.fleet_replicas == 1 and not fleet._kill_faults:
        gates["no_new_programs"] = all(p == solo_programs
                                       for p in programs.values())
    else:
        universe = _program_universe(solo_buckets, args.max_len)
        gates["no_new_programs"] = all(
            _program_keys(p) <= universe for p in programs.values())
    if fleet._kill_faults:
        gates["kill_fired"] = any(d["killed"] for d in report["deaths"])
        gates["failover_replayed"] = report["failover_replayed"] >= 1
    if fleet._storm_faults:
        gates["storm_fired"] = bool(report["storm_fired"])
        chaff = [f for f in fleet.requests.values() if f.chaff]
        gates["storm_settled"] = bool(chaff) and all(
            f.status is not None for f in chaff)

    report["gates"] = gates
    report["programs"] = {str(i): _program_summary(p)
                          for i, p in programs.items()}
    report["solo_programs"] = _program_summary(solo_programs)
    ok = all(gates.values())
    report["ok"] = ok
    out = json.dumps(report, indent=2, default=str)
    print(out)
    if getattr(args, "report", None):
        with open(args.report, "w", encoding="utf-8") as fh:
            fh.write(out + "\n")
    print(f"fleet: {'OK' if ok else 'FAILED'} — "
          + ", ".join(f"{k}={'pass' if v else 'FAIL'}"
                      for k, v in gates.items()))
    return 0 if ok else 1


def _program_keys(programs: dict) -> set:
    """Flatten ``compiled_programs()``'s ``{kind: [keys...]}`` dict into
    a comparable set of ``(kind, key)`` pairs."""
    return {(kind, k) for kind, entries in programs.items()
            for k in entries}


def _program_universe(buckets: Sequence[int], max_len: int) -> set:
    """Every program shape a solo engine of this configuration could
    compile: decode programs per configured bucket, prefill programs per
    reachable pow2 prompt pad."""
    from tpu_dist.serve.engine import _pad_to_pow2
    pads = {_pad_to_pow2(n, hi=max_len) for n in range(1, max_len + 1)}
    universe = set()
    for kind in ("decode", "paged_decode"):
        universe |= {(kind, b) for b in buckets}
    for kind in ("prefill", "paged_prefill", "prefill_chunk"):
        universe |= {(kind, p) for p in pads}
    return universe


def _program_summary(programs: dict) -> dict:
    return {kind: list(entries) for kind, entries in programs.items()}
