"""ServeSupervisor: keep a serving engine alive across crashes.

A thin serving-shaped specialization of
:class:`tpu_dist.resilience.supervisor.Supervisor` (same BackoffPolicy /
GracePolicy / exit classification / per-attempt deadline / EventLog):

* the gang is always ONE worker — a serve engine is a single process on
  its mesh; there is nothing to gang-restart;
* every attempt gets ``$TPU_DIST_SERVE_JOURNAL`` pointing at the shared
  journal directory, so attempt N+1 *recovers* attempt N's queued and
  in-flight requests (``serve/journal.py``) instead of starting empty;
* ``no_restart_exits`` is EMPTY: unlike training's ``integrity_abort``
  (restart replays into the same wall),
  :data:`~tpu_dist.resilience.faults.EXIT_SERVE_ABORT` — a wedged decode
  runtime caught by the stall watchdog — is exactly the failure a fresh
  process cures, so every nonzero exit restarts within the budget;
* the restart count lands on the ``serve.engine.restarts`` counter and
  the final journal is the source of truth for what was served
  (:meth:`ServeSupervisor.journal_state`).

The worker argv is typically ``python -m tpu_dist.serve --worker ...``
(see ``serve/cli.py``); its last ``RESULT:{...}`` stdout line — the same
protocol the training chaos harness uses — is read back with
:meth:`ServeSupervisor.final_result`.
"""

from __future__ import annotations

import logging
import pathlib
from typing import Optional, Sequence

from tpu_dist.observe import metrics
from tpu_dist.resilience.supervisor import (BackoffPolicy, GracePolicy,
                                            Supervisor, SupervisorReport)
from tpu_dist.serve import journal as journal_lib

logger = logging.getLogger(__name__)


class ServeSupervisor(Supervisor):
    """Supervise one serve worker process against a shared journal.

    Args:
      cmd: worker argv (e.g. ``[sys.executable, "-m", "tpu_dist.serve",
        "--worker", ...]``); rerun unchanged every attempt.
      journal_dir: the durable journal directory every attempt shares —
        exported to the worker as ``$TPU_DIST_SERVE_JOURNAL``.
      Everything else is forwarded to :class:`Supervisor` (single worker,
      empty ``no_restart_exits``).
    """

    def __init__(self, cmd: Sequence[str], *,
                 journal_dir: str | pathlib.Path,
                 max_restarts: int = 3,
                 attempt_deadline_s: Optional[float] = None,
                 backoff: BackoffPolicy = BackoffPolicy(initial_s=0.1,
                                                        max_s=2.0),
                 grace: GracePolicy = GracePolicy(),
                 env: Optional[dict] = None,
                 log_dir: str | pathlib.Path = "serve-logs",
                 event_log=None):
        self.journal_dir = pathlib.Path(journal_dir)
        env = dict(env or {})
        env[journal_lib.JOURNAL_DIR_ENV] = str(self.journal_dir)
        super().__init__(cmd, num_workers=1, max_restarts=max_restarts,
                         attempt_deadline_s=attempt_deadline_s,
                         backoff=backoff, grace=grace, env=env,
                         log_dir=log_dir, event_log=event_log,
                         no_restart_exits=())

    def run(self) -> SupervisorReport:
        report = super().run()
        if report.restarts:
            metrics.inc("serve.engine.restarts", report.restarts)
        return report

    # -- post-run introspection ----------------------------------------------

    def final_result(self, report: SupervisorReport) -> Optional[dict]:
        """The last ``RESULT:{...}`` line of the FINAL attempt's worker
        log, or None when the worker never printed one (died too early)."""
        from tpu_dist.resilience.cli import parse_result_line

        log = self.worker_log(report.attempts - 1, 0)
        try:
            return parse_result_line(log.read_text())
        except OSError:
            return None

    def journal_state(self) -> journal_lib.JournalState:
        """Replay the shared journal — the source of truth for what was
        served across every attempt (per-request token streams included)."""
        return journal_lib.load(self.journal_dir / journal_lib.JOURNAL_NAME)
