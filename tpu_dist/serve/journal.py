"""Durable request journal: the serve engine's crash-recovery log.

An append-only JSONL file recording every request's lifecycle — ``submit``
(prompt + generation knobs), ``token`` (each emitted token), ``finish``
(terminal status + reason) and ``replay`` (one marker per crash recovery,
naming the requests that were in flight) — so a supervised engine restart
can reconstruct exactly where serving stood:

* requests journaled ``submit`` but never ``finish`` and with no tokens
  are **queued**: re-admitted in arrival order;
* requests with tokens but no ``finish`` were **active** mid-decode:
  re-prefilled with ``prompt + tokens_emitted_so_far``, which makes the
  greedy continuation token-identical to an uninterrupted run (the
  incremental-decode ≡ full-forward equivalence ``test_serve.py`` pins);
* requests whose journaled tokens already satisfy their stop condition
  (EOS flushed, length reached) finish **during replay** — their terminal
  record was lost in the crash, not their work.

Durability model: records are **buffered in memory and flushed once per
decode step** — a single ``write`` of the whole batch followed by an
``fsync`` (the same durability discipline as ``training/checkpoint.py``:
atomicity is not durability; data sitting in the page cache is lost to a
crash). One fsync per decode step keeps the journal off the per-token hot
path; everything since the last flush is regenerated deterministically on
replay, so the flush granularity bounds *recomputation*, never
*correctness*. A writer killed mid-flush leaves at most one torn trailing
line, which :func:`load` skips exactly like the resilience event log does.

The journal is host-side and jax-free on purpose: it records scheduling
truth, never touches device buffers, and adds zero bytes to the compiled
``serve.decode_step`` program (pinned by the analysis cost baseline).
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Optional

#: Environment variable a supervised serve worker reads its journal
#: directory from (set by the serve chaos driver / ServeSupervisor).
JOURNAL_DIR_ENV = "TPU_DIST_SERVE_JOURNAL"

#: Environment variable bounding the journal file size (bytes): past it,
#: the next flush compacts the file (:meth:`RequestJournal.flush`).
#: Unset/empty/0 = never rotate (the historical behavior).
JOURNAL_MAX_BYTES_ENV = "TPU_DIST_SERVE_JOURNAL_MAX_BYTES"

#: Journal file name inside the journal directory.
JOURNAL_NAME = "journal.jsonl"


class RequestJournal:
    """Buffered, fsync'd append-only journal for one serving process.

    Args:
      directory: journal directory (created if missing); the JSONL lives at
        ``<directory>/journal.jsonl``. An existing journal is APPENDED to —
        recovery reads it first (:func:`load`), then the recovered engine
        keeps writing to the same file, so the full request history
        survives any number of restarts.
      fsync: set False to skip the per-flush fsync (tests on tmpfs; a
        production engine keeps it on — a journal that loses its tail to
        the page cache silently re-queues shed work).
      max_bytes: rotate (compact) the journal when a flush leaves the
        file larger than this. Compaction drops finished requests'
        records — their rids survive in the rotation marker, so
        idempotent resubmission and rid allocation are unchanged — and
        rewrites unfinished requests' submit+token trails verbatim, so a
        crash after any number of rotations replays exactly like one
        before the first. None (or a false-y value) = never rotate; a
        long-lived engine's journal then grows with every token served.
    """

    def __init__(self, directory: str | os.PathLike, *, fsync: bool = True,
                 max_bytes: Optional[int] = None):
        self.directory = pathlib.Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.path = self.directory / JOURNAL_NAME
        self.fsync = bool(fsync)
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.rotations = 0
        self._buf: list[str] = []
        self._closed = False

    # -- record builders (buffered) ------------------------------------------

    def _put(self, rec: dict) -> None:
        if self._closed:
            raise RuntimeError(f"journal {self.path} is closed")
        self._buf.append(json.dumps(rec))

    def record_submit(self, req) -> None:
        # ts fields below are operator telemetry ONLY: load()/replay never
        # read them, rotate() strips them, and no gate compares them.
        self._put({"rec": "submit", "rid": int(req.rid),  # shardcheck: disable=SC601 -- ts is write-only telemetry, ignored by load()/replay
                   "prompt": [int(t) for t in req.prompt],
                   "max_new_tokens": int(req.max_new_tokens),
                   "eos_id": (None if req.eos_id is None
                              else int(req.eos_id)),
                   "deadline_s": req.deadline_s,
                   "ts": round(time.time(), 6)})

    def record_token(self, rid: int, token: int) -> None:
        self._put({"rec": "token", "rid": int(rid), "t": int(token)})

    def record_finish(self, req) -> None:
        self._put({"rec": "finish", "rid": int(req.rid),  # shardcheck: disable=SC601 -- ts is write-only telemetry, ignored by load()/replay
                   "status": req.status, "reason": req.finish_reason,
                   "ts": round(time.time(), 6)})

    def record_replay(self, *, attempt: int, queued: list, active: list,
                      completed: list, replay_s: float) -> None:
        """One marker per crash recovery. ``active`` is what counts against
        each request's retry budget: those are the requests that were being
        decoded when the engine died (the poison-pill suspects)."""
        self._put({"rec": "replay", "attempt": int(attempt),  # shardcheck: disable=SC601 -- ts is write-only telemetry, ignored by load()/replay
                   "queued": [int(r) for r in queued],
                   "active": [int(r) for r in active],
                   "completed": [int(r) for r in completed],
                   "replay_s": round(float(replay_s), 6),
                   "ts": round(time.time(), 6)})
        self.flush()

    # -- durability ----------------------------------------------------------

    def flush(self) -> int:
        """Write every buffered record as ONE append + fsync; returns the
        number of records flushed. Called by the engine between decode
        steps — the batched-fsync contract in the module docstring."""
        if not self._buf:
            return 0
        n = len(self._buf)
        data = "\n".join(self._buf) + "\n"
        self._buf = []
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(data)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        if (self.max_bytes is not None
                and self.path.stat().st_size > self.max_bytes):
            self.rotate()
        return n

    def rotate(self) -> dict:
        """Compact the journal in place: drop finished requests' records,
        keep replay-marker history and every unfinished request's full
        submit+token trail, and lead with ONE cumulative ``rotate`` marker
        carrying the dropped rids (so ``known_rids``/``next_rid`` read
        back exactly as before compaction). Atomic and durable the
        checkpoint way — temp file, fsync, rename, fsync(dir) — so a
        crash mid-rotation leaves either the old journal or the new one,
        never a blend. Returns the rotation marker."""
        state = load(self.path)
        finished = sorted(state.compacted_rids
                          | {r.rid for r in state.requests.values()
                             if r.finished})
        self.rotations = state.rotations + 1
        marker = {"rec": "rotate", "rotations": self.rotations,
                  "finished_rids": finished,
                  "ts": round(time.time(), 6)}
        lines = [json.dumps(marker)]
        lines += [json.dumps(m) for m in state.replay_markers]
        unfinished = sorted((r for r in state.requests.values()
                             if not r.finished), key=lambda r: r.order)
        for r in unfinished:
            lines.append(json.dumps(
                {"rec": "submit", "rid": r.rid, "prompt": r.prompt,
                 "max_new_tokens": r.max_new_tokens, "eos_id": r.eos_id,
                 "deadline_s": r.deadline_s}))
            lines += [json.dumps({"rec": "token", "rid": r.rid, "t": t})
                      for t in r.tokens]
        tmp = self.path.with_name(JOURNAL_NAME + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")  # shardcheck: disable=SC601 -- rotate marker ts is write-only telemetry; replay ignores it
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        if self.fsync:
            fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        return marker

    def close(self) -> None:
        if not self._closed:
            self.flush()
            self._closed = True

    def __enter__(self) -> "RequestJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class JournaledRequest:
    """Replay-side view of one journaled request."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_id", "deadline_s",
                 "tokens", "status", "finish_reason", "order", "replays")

    def __init__(self, rid: int, *, prompt: list, max_new_tokens: int,
                 eos_id: Optional[int], deadline_s: Optional[float],
                 order: int):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.deadline_s = deadline_s
        self.tokens: list[int] = []
        self.status: Optional[str] = None      # terminal status, if finished
        self.finish_reason: Optional[str] = None
        self.order = order                     # arrival order (submit index)
        self.replays = 0                       # times caught ACTIVE in a crash

    @property
    def finished(self) -> bool:
        return self.status is not None

    def stop_satisfied(self) -> bool:
        """True when the journaled tokens already meet the request's stop
        condition — the terminal record was lost, not the work."""
        if self.eos_id is not None and self.eos_id in self.tokens:
            return True
        return len(self.tokens) >= self.max_new_tokens

    def implied_finish_reason(self) -> str:
        if self.eos_id is not None and self.eos_id in self.tokens:
            return "eos"
        return "length"


class JournalState:
    """Everything :func:`load` reconstructs from a journal file."""

    def __init__(self):
        self.requests: dict[int, JournaledRequest] = {}
        self.replay_markers: list[dict] = []
        self.records = 0
        #: Finished rids whose records a rotation dropped — still "known"
        #: (resubmission idempotency, rid allocation), just not replayable.
        self.compacted_rids: set = set()
        self.rotations = 0

    @property
    def known_rids(self) -> set:
        return set(self.requests) | self.compacted_rids

    @property
    def next_rid(self) -> int:
        return max(max(self.requests, default=-1),
                   max(self.compacted_rids, default=-1)) + 1

    def pending(self) -> tuple[list, list]:
        """``(active, queued)`` in arrival order: active = unfinished with
        tokens (were mid-decode), queued = unfinished without tokens."""
        unfinished = sorted((r for r in self.requests.values()
                             if not r.finished), key=lambda r: r.order)
        active = [r for r in unfinished if r.tokens]
        queued = [r for r in unfinished if not r.tokens]
        return active, queued


def load(path: str | os.PathLike) -> JournalState:
    """Replay a journal file into a :class:`JournalState`. Unparseable
    (torn) lines are skipped — crash recovery reads journals whose writer
    died mid-append, by design. A missing file is an empty state."""
    state = JournalState()
    try:
        fh = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return state
    with fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("rec")
            state.records += 1
            if kind == "submit":
                rid = int(rec["rid"])
                state.requests[rid] = JournaledRequest(
                    rid, prompt=list(rec.get("prompt", [])),
                    max_new_tokens=int(rec.get("max_new_tokens", 0)),
                    eos_id=rec.get("eos_id"),
                    deadline_s=rec.get("deadline_s"),
                    order=len(state.requests))
            elif kind == "token":
                jr = state.requests.get(int(rec.get("rid", -1)))
                if jr is not None:
                    jr.tokens.append(int(rec["t"]))
            elif kind == "finish":
                jr = state.requests.get(int(rec.get("rid", -1)))
                if jr is not None:
                    jr.status = rec.get("status")
                    jr.finish_reason = rec.get("reason")
            elif kind == "replay":
                state.replay_markers.append(rec)
                for rid in rec.get("active", []):
                    jr = state.requests.get(int(rid))
                    if jr is not None:
                        jr.replays += 1
            elif kind == "rotate":
                state.compacted_rids |= {int(r) for r in
                                         rec.get("finished_rids", [])}
                state.rotations = max(state.rotations,
                                      int(rec.get("rotations", 0)))
    return state


def journal_dir_from_env() -> Optional[str]:
    """The journal directory named by ``$TPU_DIST_SERVE_JOURNAL``, or None
    when this process serves without crash recovery."""
    d = os.environ.get(JOURNAL_DIR_ENV)
    return d if d else None


def journal_max_bytes_from_env() -> Optional[int]:
    """The rotation threshold from ``$TPU_DIST_SERVE_JOURNAL_MAX_BYTES``,
    or None (never rotate) when unset, empty, zero, or unparseable."""
    raw = os.environ.get(JOURNAL_MAX_BYTES_ENV)
    if not raw or not raw.strip():
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n > 0 else None
