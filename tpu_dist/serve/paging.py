"""Host-side page management for the paged KV cache.

``kv_cache.init_page_pool`` carves HBM into fixed-size pages; this module
owns everything about which page holds what:

* :class:`PageAllocator` — the free list, per-page refcounts, and the
  per-slot page table ``[slots, max_pages]`` of pool indices (scratch-
  filled for unallocated entries). Reclaim is compaction-free: finishing
  a request just drops its refcounts, and any page that hits zero goes
  straight back on the free list — no copying, no defragmentation.
  Exhaustion raises a loud :class:`PageExhaustedError` naming the exact
  accounting instead of letting a device scatter corrupt another
  request's pages. A *reservation* ledger makes admission deadlock-free:
  a request is only admitted once ``ceil(total_tokens / page_size)``
  pages are set aside for its worst case (zero sharing), so every later
  incremental allocation — decode appends, copy-on-write clones — is
  guaranteed to succeed.
* :class:`PrefixCache` — chain-hashes page-aligned prompt chunks
  (blake2b over parent digest + chunk tokens) and maps them to
  refcounted read-only pages, so a repeated system prompt resolves to
  already-computed K/V and prefill runs only over the suffix. Partial
  tail chunks are cached too (registered when a request finishes, keyed
  under the parent full-page digest), and a write into any shared page
  triggers copy-on-write: the allocator hands out a private clone and
  the device runs one ``kv_cache.copy_page`` program. Eviction is
  leaf-first LRU and only ever drops the *cache's* reference — pages
  still used by active requests stay resident until those finish.
* :class:`PagedKVState` — the engine-facing facade tying both together:
  admission headroom checks, prefix lookup + page-table construction at
  prefill, tail-page writability for decode appends, registration +
  release at finish, and the pointer-swap that replaces the contiguous
  engine's ``swap_slots`` device program.

The invariant everything hangs on: **a page is writable by a slot iff
its refcount is exactly 1** (the slot's own reference). The prefix cache
holds its own +1 on every page it indexes, so cached pages are read-only
by construction and sharing can never alias a write.

Device state never leaves this module's hands as anything but *indices*
— journal replay rebuilds every page table from prompt tokens alone, so
no page state needs to be persisted.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_dist.observe import metrics

__all__ = [
    "PageAllocator",
    "PageExhaustedError",
    "PagedKVState",
    "PrefixCache",
    "PrefillSetup",
]

#: Chain-hash root for the empty prefix.
_ROOT = b"tpu_dist.serve.prefix-root"


class PageExhaustedError(RuntimeError):
    """The pool has no page to give — raised loudly instead of letting a
    scatter land on a page another request owns."""


def _digest(parent: bytes, chunk: Tuple[int, ...]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.asarray(chunk, np.int64).tobytes())
    return h.digest()


class PageAllocator:
    """Free list + refcounts + per-slot page tables over a fixed pool.

    Page index ``num_pages`` is the device pool's scratch row: it never
    enters the free list, unallocated table entries point at it, and
    kernels route invalid-position writes to it.
    """

    def __init__(self, *, num_pages: int, page_size: int, slots: int,
                 max_pages: int) -> None:
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.slots = slots
        self.max_pages = max_pages
        self.scratch = num_pages
        self._free: deque = deque(range(num_pages))
        self.refcount = np.zeros(num_pages, np.int64)
        #: int32 [slots, max_pages]; position-ordered page indices.
        self.table = np.full((slots, max_pages), self.scratch, np.int32)
        #: allocated (position-ordered) entries per slot.
        self.count = np.zeros(slots, np.int64)
        #: outstanding worst-case future allocations per slot.
        self.reserved = np.zeros(slots, np.int64)
        #: reservations made at admission, not yet bound to a slot.
        self.pending_reserved = 0

    # -- accounting -----------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def headroom(self) -> int:
        """Pages available beyond every outstanding reservation."""
        return (len(self._free) - int(self.reserved.sum())
                - self.pending_reserved)

    def pages_needed(self, total_tokens: int) -> int:
        return -(-total_tokens // self.page_size)

    def _exhausted(self, what: str) -> PageExhaustedError:
        return PageExhaustedError(
            f"serve: page pool exhausted while {what} — "
            f"{self.pages_in_use}/{self.num_pages} pages in use, "
            f"{self.free_pages} free, "
            f"{int(self.reserved.sum()) + self.pending_reserved} reserved "
            "for admitted requests. Raise num_pages/budget_bytes, lower "
            "max_new_tokens, or let active requests drain.")

    # -- reservation (admission) ----------------------------------------------

    def reserve_pending(self, n: int) -> None:
        """Set aside ``n`` pages for a request admitted this round but
        not yet bound to a slot."""
        if n > self.headroom():
            raise self._exhausted(f"reserving {n} page(s) at admission")
        self.pending_reserved += n

    def bind_reservation(self, slot: int, n: int) -> None:
        """Move an admission reservation onto the slot that got it."""
        self.pending_reserved -= min(n, self.pending_reserved)
        self.reserved[slot] += n

    # -- page lifecycle -------------------------------------------------------

    def alloc(self, slot: int) -> int:
        """Append one fresh private page to ``slot``'s table. Draws from
        the slot's reservation, which guarantees the free list is
        non-empty for every covered allocation."""
        if not self._free:
            raise self._exhausted(f"allocating a page for slot {slot}")
        if self.count[slot] >= self.max_pages:
            raise PageExhaustedError(
                f"serve: slot {slot} already holds max_pages="
                f"{self.max_pages} pages — the request outgrew "
                "max_len // page_size, which submit() should have caught")
        pg = self._free.popleft()
        self.refcount[pg] = 1
        self.table[slot, self.count[slot]] = pg
        self.count[slot] += 1
        self.reserved[slot] = max(self.reserved[slot] - 1, 0)
        return pg

    def attach(self, slot: int, pages: List[int], *,
               full: bool = True) -> None:
        """Append shared (prefix-cache) pages to ``slot``'s table,
        bumping refcounts. ``full`` pages retire one unit of the slot's
        reservation each — they will never need a private replacement;
        a partial page keeps its unit, which the follow-up copy-on-write
        clone consumes."""
        for pg in pages:
            if self.count[slot] >= self.max_pages:
                raise PageExhaustedError(
                    f"serve: slot {slot} page table overflow attaching "
                    "shared pages")
            self.refcount[pg] += 1
            self.table[slot, self.count[slot]] = pg
            self.count[slot] += 1
            if full:
                self.reserved[slot] = max(self.reserved[slot] - 1, 0)

    def retain(self, pg: int) -> None:
        """Add an owner (the prefix cache) to an allocated page."""
        self.refcount[pg] += 1

    def release_page(self, pg: int) -> None:
        self.refcount[pg] -= 1
        if self.refcount[pg] < 0:
            raise AssertionError(f"page {pg} refcount went negative")
        if self.refcount[pg] == 0:
            self._free.append(pg)

    def writable(self, pg: int) -> bool:
        """A slot may write a page iff it is the sole owner."""
        return pg != self.scratch and self.refcount[pg] == 1

    def cow(self, slot: int, idx: int) -> Tuple[int, int]:
        """Clone table entry ``idx`` (a shared page) into a private page
        and repoint the slot at it. Returns ``(src, dst)`` for the
        device-side ``copy_page`` the caller must run before writing."""
        src = int(self.table[slot, idx])
        if not self._free:
            raise self._exhausted(
                f"copy-on-write for slot {slot} page {idx}")
        dst = self._free.popleft()
        self.refcount[dst] = 1
        self.table[slot, idx] = dst
        self.reserved[slot] = max(self.reserved[slot] - 1, 0)
        self.release_page(src)
        return src, dst

    def release_slot(self, slot: int) -> None:
        """Compaction-free reclaim: drop the slot's references (pages the
        prefix cache still indexes stay resident) and return any unused
        reservation."""
        for i in range(int(self.count[slot])):
            self.release_page(int(self.table[slot, i]))
        self.table[slot, :] = self.scratch
        self.count[slot] = 0
        self.reserved[slot] = 0

    def swap_slots(self, i: int, j: int) -> None:
        """The paged analogue of the contiguous engine's device-side
        ``swap_slots`` program: a host pointer swap."""
        self.table[[i, j]] = self.table[[j, i]]
        self.count[[i, j]] = self.count[[j, i]]
        self.reserved[[i, j]] = self.reserved[[j, i]]

    def check(self) -> None:
        """Internal-consistency audit (tests): every table reference is
        counted, every free page has refcount 0."""
        refs = np.zeros(self.num_pages, np.int64)
        for s in range(self.slots):
            for i in range(int(self.count[s])):
                pg = int(self.table[s, i])
                assert pg != self.scratch, (s, i)
                refs[pg] += 1
        assert np.all(self.refcount >= refs), (self.refcount, refs)
        for pg in self._free:
            assert self.refcount[pg] == 0, pg
        held = set(int(p) for p in self._free)
        assert len(held) == len(self._free), "free list has duplicates"


@dataclasses.dataclass
class _Node:
    """One cached chunk: a page plus its place in the chain."""

    page: int
    parent: Optional[bytes]  #: parent FULL-chunk digest (None for root)
    tokens: Optional[Tuple[int, ...]]  #: partial chunks only
    children: int = 0
    tick: int = 0


class PrefixCache:
    """Chain-hashed page-aligned prompt chunks -> refcounted pages.

    Full ``page_size`` chunks are indexed by the digest chain
    ``d_i = H(d_{i-1}, chunk_i)`` and registered right after prefill
    (full prompt pages are complete and never rewritten, so concurrent
    requests can share immediately). A partial tail chunk is registered
    when its request *finishes* — its page keeps being written during
    decode — keyed by ``(parent digest, tail tokens)``; a later prompt
    extending past a cached partial copy-on-writes the clone at its
    first divergent/extending position.
    """

    def __init__(self, allocator: PageAllocator) -> None:
        self._alloc = allocator
        self._full: Dict[bytes, _Node] = {}
        self._partial: Dict[Tuple[bytes, Tuple[int, ...]], _Node] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0

    @property
    def pages_held(self) -> int:
        return len(self._full) + len(self._partial)

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.tick = self._tick

    def _chunks(self, prompt) -> List[Tuple[int, ...]]:
        ps = self._alloc.page_size
        return [tuple(int(t) for t in prompt[i:i + ps])
                for i in range(0, len(prompt), ps)]

    @staticmethod
    def prompt_digest(tokens, page_size: int) -> bytes:
        """Chained prefix digest of a prompt, computable without an
        engine or allocator — the fleet router's affinity key.

        Walks the same chain :meth:`lookup`/:meth:`register_full` walk:
        ``d_i = H(d_{i-1}, chunk_i)`` over the full ``page_size`` chunks
        (so the result for a page-aligned prompt IS the ``_full`` cache
        key of its last page), then folds a partial tail chunk in with
        one more ``H(parent, tail)`` step — the hashed form of the
        ``(parent digest, tail tokens)`` key ``_partial`` uses. Two
        prompts share a digest iff the cache would key them identically,
        which is exactly the warm-replica question the router asks.
        """
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        toks = [int(t) for t in tokens]
        digest = _ROOT
        k_full = len(toks) // page_size
        for i in range(k_full):
            digest = _digest(
                digest, tuple(toks[i * page_size:(i + 1) * page_size]))
        tail = tuple(toks[k_full * page_size:])
        if tail:
            digest = _digest(digest, tail)
        return digest

    def lookup(self, prompt) -> Tuple[List[int], int, bool]:
        """Longest cached prefix of ``prompt``.

        Returns ``(pages, matched_tokens, tail_is_partial)`` —
        position-ordered pages covering ``matched_tokens``; when
        ``tail_is_partial`` the last page is a partially-filled cached
        tail (its clone must be copy-on-written before any write).
        """
        ps = self._alloc.page_size
        pages: List[int] = []
        matched = 0
        digest = _ROOT
        for chunk in self._chunks(prompt):
            if len(chunk) < ps:
                break
            nxt = _digest(digest, chunk)
            node = self._full.get(nxt)
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            matched += ps
            digest = nxt
        if matched < len(prompt):
            remainder = tuple(int(t) for t in prompt[matched:])
            best: Optional[_Node] = None
            best_len = 0
            for (parent, toks), node in self._partial.items():
                if parent != digest or len(toks) <= best_len:
                    continue
                if remainder[:len(toks)] == toks:
                    best, best_len = node, len(toks)
            if best is not None:
                self._touch(best)
                pages.append(best.page)
                matched += best_len
                return pages, matched, True
        return pages, matched, False

    def register_full(self, prompt, table_row, *, upto: int) -> None:
        """Index the full ``page_size`` chunks of ``prompt[:upto]``
        against the slot's (already written) pages, taking a cache
        reference on each newly indexed page."""
        ps = self._alloc.page_size
        digest = _ROOT
        for i in range(int(upto) // ps):
            chunk = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            nxt = _digest(digest, chunk)
            node = self._full.get(nxt)
            if node is None:
                pg = int(table_row[i])
                if pg == self._alloc.scratch:
                    break
                self._alloc.retain(pg)
                node = _Node(page=pg, parent=None if digest is _ROOT
                             else digest, tokens=None)
                self._full[nxt] = node
                if node.parent is not None:
                    self._full[node.parent].children += 1
            self._touch(node)
            digest = nxt

    def register_partial(self, prompt, table_row) -> None:
        """Index the prompt's partial tail chunk (if any) under its
        parent digest. Called at request finish — by then the tail page
        is private and stable for the cached positions."""
        ps = self._alloc.page_size
        k_full = len(prompt) // ps
        tail = tuple(int(t) for t in prompt[k_full * ps:])
        if not tail:
            return
        digest = _ROOT
        for i in range(k_full):
            chunk = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            nxt = _digest(digest, chunk)
            if nxt not in self._full:
                return  # full chain not cached; don't dangle a partial
            digest = nxt
        key = (digest, tail)
        if key in self._partial:
            self._touch(self._partial[key])
            return
        pg = int(table_row[k_full])
        if pg == self._alloc.scratch:
            return
        self._alloc.retain(pg)
        node = _Node(page=pg, parent=None if digest is _ROOT else digest,
                     tokens=tail)
        self._partial[key] = node
        if node.parent is not None:
            self._full[node.parent].children += 1
        self._touch(node)

    def evict(self, need: int) -> int:
        """Leaf-first LRU: drop cache references until ``need`` pages
        came free (or nothing evictable remains). Only pages no active
        slot shares actually return to the free list."""
        freed = 0
        while freed < need:
            candidates: List[Tuple[int, object, _Node]] = []
            for key, node in self._partial.items():
                candidates.append((node.tick, key, node))
            for key, node in self._full.items():
                if node.children == 0:
                    candidates.append((node.tick, key, node))
            if not candidates:
                break
            _, key, node = min(candidates, key=lambda c: c[0])
            if isinstance(key, tuple):
                del self._partial[key]
            else:
                del self._full[key]
            if node.parent is not None:
                self._full[node.parent].children -= 1
            if self._alloc.refcount[node.page] == 1:
                freed += 1
            self._alloc.release_page(node.page)
        return freed

    def clear(self) -> None:
        """Drop every cache reference (tests / shutdown)."""
        for node in list(self._partial.values()):
            self._alloc.release_page(node.page)
        for node in list(self._full.values()):
            self._alloc.release_page(node.page)
        self._partial.clear()
        self._full.clear()


@dataclasses.dataclass
class PrefillSetup:
    """What the engine must do before running ``paged_prefill``."""

    start: int  #: cached-prefix length; prefill covers [start, len(seq))
    copies: List[Tuple[int, int]]  #: copy_page (src, dst) pairs, in order


class PagedKVState:
    """Engine-facing facade: allocator + prefix cache + metrics.

    Pure host state. The engine owns the device pool and the compiled
    ``copy_page`` program; this class only ever returns *indices* and
    ``(src, dst)`` copy instructions.
    """

    def __init__(self, *, num_pages: int, page_size: int, slots: int,
                 max_pages: int, bytes_per_token: int,
                 prefix_caching: bool = True) -> None:
        self.allocator = PageAllocator(
            num_pages=num_pages, page_size=page_size, slots=slots,
            max_pages=max_pages)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator) if prefix_caching else None)
        self._bytes_per_token = bytes_per_token

    # -- admission ------------------------------------------------------------

    def pages_needed(self, total_tokens: int) -> int:
        return self.allocator.pages_needed(total_tokens)

    def check_fits(self, total_tokens: int) -> None:
        """submit()-time guard: reject requests that could never fit
        even into an empty pool, loudly."""
        need = self.pages_needed(total_tokens)
        if need > self.allocator.num_pages:
            raise ValueError(
                f"serve: request needs {need} pages "
                f"({total_tokens} tokens at page_size="
                f"{self.allocator.page_size}) but the pool only has "
                f"{self.allocator.num_pages} — raise num_pages/"
                "budget_bytes or lower max_new_tokens")

    def try_admit(self, total_tokens: int) -> bool:
        """Admission gate: reserve worst-case pages for one request,
        evicting cold prefix-cache pages if that is what it takes.
        Returns False (leave it queued) when headroom is short."""
        need = self.pages_needed(total_tokens)
        short = need - self.allocator.headroom()
        if short > 0 and self.prefix is not None:
            self.prefix.evict(short)
        if need > self.allocator.headroom():
            return False
        self.allocator.reserve_pending(need)
        return True

    # -- prefill --------------------------------------------------------------

    def begin(self, slot: int, seq, total_tokens: int, *,
              chunk: int = 0) -> PrefillSetup:
        """Build ``slot``'s page table for prefilling ``seq``: bind the
        admission reservation, attach any cached prefix (copy-on-write
        on a partial tail), and allocate private pages for the suffix.

        ``chunk > 0`` switches to chunk-granular allocation: only the
        pages the FIRST chunk (positions ``[start, start + chunk)``)
        writes are allocated now; :meth:`extend_prefill` draws the rest
        from the admission reservation one chunk at a time, so a
        half-prefilled long prompt pins pages proportional to its
        progress, not its full length. Prefix-cache hits skip whole
        cached chunks — the suffix starts at ``start``.
        """
        alloc = self.allocator
        ps = alloc.page_size
        need = self.pages_needed(total_tokens)
        alloc.bind_reservation(slot, need)
        copies: List[Tuple[int, int]] = []
        start = 0
        if self.prefix is not None:
            pages, matched, partial = self.prefix.lookup(seq)
            # Always leave >= 1 token to prefill: the suffix pass is
            # what produces the first generated token's logits.
            matched = min(matched, len(seq) - 1)
            k_full = matched // ps
            rem = matched % ps
            alloc.attach(slot, pages[:k_full], full=True)
            if rem:
                # Partially-used hit page: attach then immediately make
                # it private — positions >= rem get overwritten.
                alloc.attach(slot, [pages[k_full]], full=False)
                copies.append(alloc.cow(slot, k_full))
            start = matched
            if matched:
                self.prefix.hits += 1
                metrics.inc("serve.prefix.hits")
                metrics.inc("serve.prefix.bytes_saved",
                            matched * self._bytes_per_token)
            else:
                self.prefix.misses += 1
                metrics.inc("serve.prefix.misses")
            metrics.observe_value("serve.prefill.skipped_tokens",
                                  float(matched))
        # Private pages for every position the suffix will write — the
        # whole suffix up front, or just the first chunk's worth.
        upto = len(seq) if chunk <= 0 else min(start + chunk, len(seq))
        last_page = (upto - 1) // ps
        while alloc.count[slot] <= last_page:
            alloc.alloc(slot)
        return PrefillSetup(start=start, copies=copies)

    def extend_prefill(self, slot: int, upto: int) -> None:
        """Chunk-granular growth: allocate pages so positions
        ``[0, upto)`` all have a table entry. Draws from the admission
        reservation bound in :meth:`begin`, so it cannot deadlock
        against other requests."""
        alloc = self.allocator
        last_page = (int(upto) - 1) // alloc.page_size
        while alloc.count[slot] <= last_page:
            alloc.alloc(slot)

    def register_prefill(self, slot: int, prompt) -> None:
        """Index the prompt's full pages right after prefill wrote them,
        so requests admitted later this round already share."""
        if self.prefix is not None:
            self.prefix.register_full(prompt, self.allocator.table[slot],
                                      upto=len(prompt))

    # -- decode ---------------------------------------------------------------

    def prepare_append(self, slot: int, length: int) -> List[Tuple[int, int]]:
        """Make the write target for position ``length`` writable:
        allocate the next page at a boundary, copy-on-write a shared
        tail. Returns ``copy_page`` (src, dst) pairs to run first."""
        alloc = self.allocator
        idx = length // alloc.page_size
        if idx >= alloc.count[slot]:
            alloc.alloc(slot)
            return []
        if not alloc.writable(int(alloc.table[slot, idx])):
            return [alloc.cow(slot, idx)]
        return []

    # -- finish / swap --------------------------------------------------------

    def finish(self, slot: int, prompt, *,
               upto: Optional[int] = None) -> None:
        """Release the slot's pages; first index the prompt's tail chunk
        (and any full chunks a recovery prefill skipped registering) so
        the next identical prompt hits.

        ``upto`` bounds registration to prompt positions whose K/V were
        actually WRITTEN — a request evicted mid-chunked-prefill may
        hold allocated-but-unwritten pages, and registering those would
        poison the prefix cache with garbage K/V. The partial tail is
        only indexed when the whole prompt landed."""
        if self.prefix is not None:
            n = len(prompt) if upto is None else min(int(upto), len(prompt))
            self.prefix.register_full(prompt, self.allocator.table[slot],
                                      upto=n)
            if n == len(prompt):
                self.prefix.register_partial(prompt,
                                             self.allocator.table[slot])
        self.allocator.release_slot(slot)

    def swap_slots(self, i: int, j: int) -> None:
        self.allocator.swap_slots(i, j)

    # -- telemetry ------------------------------------------------------------

    def note_usage(self) -> None:
        metrics.set_gauge("serve.pages.in_use",
                          float(self.allocator.pages_in_use))
        metrics.set_gauge("serve.pages.free",
                          float(self.allocator.free_pages))
        # Pool bytes actually held per occupied slot (page-table
        # references x page bytes — shared prefix pages count once per
        # referencing slot on purpose: this is the capacity-planning
        # "what does one more request cost" number, and with an int8
        # pool it is roughly half the float figure at equal lengths).
        held = int(self.allocator.count.sum())
        occupied = int(np.count_nonzero(self.allocator.count))
        if occupied:
            per_page = self.allocator.page_size * self._bytes_per_token
            metrics.set_gauge("serve.pages.bytes_per_slot",
                              held * per_page / occupied)
