"""Continuous-batching request scheduler: slot assignment between steps.

The engine's compiled programs are keyed by *bucket* (padded batch size),
so all the scheduler has to do — and all it does — is keep the set of
active cache slots a compact prefix and decide, between decode steps,
which queued requests enter and which active ones leave:

* **FIFO admission** into the lowest free slot. ``continuous`` policy
  admits whenever a slot is free (requests join mid-flight next step);
  ``static`` policy only admits into an EMPTY batch and runs that cohort
  to completion AT THE COHORT'S BUCKET — a request finishing early stops
  consuming tokens but its padded slot keeps paying decode compute until
  the whole cohort drains, which is exactly the head-of-line blocking
  the serve benchmark measures continuous batching against.
* **Completion/eviction between steps**: a request leaves when it emits
  EOS, reaches its ``max_new_tokens``, or blows its deadline. Freed
  slots are compacted by swapping the last active slot down (the engine
  mirrors each swap in the KV cache via ``kv_cache.swap_slots``), so the
  active count maps to the smallest padded bucket.
* **No starvation**: admission is strictly arrival-ordered and every
  active request makes one token of progress per decode step (there is
  no preemption and no reordering), so under a full batch a queued
  request waits only for the bounded completion of earlier requests —
  ``test_serve.py`` pins this.

Host-side and jax-free on purpose: scheduling decisions happen between
compiled steps, never inside them.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

#: Request lifecycle states. SHED is terminal like DONE/EVICTED but
#: mutually exclusive with both: a shed request was REJECTED at admission
#: (queue bound, projected-TTFT/deadline infeasibility, or retry-budget
#: exhaustion on journal replay) and never occupied a slot.
QUEUED, ACTIVE, DONE, EVICTED, SHED = ("queued", "active", "done",
                                       "evicted", "shed")


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record."""

    prompt: list  #: int token ids, len >= 1
    max_new_tokens: int = 32
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None  #: wall seconds from submit
    rid: int = -1
    status: str = QUEUED
    generated: list = dataclasses.field(default_factory=list)
    slot: int = -1
    submit_s: float = 0.0
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    finish_reason: Optional[str] = None  #: eos | length | deadline | shed
    #: Why a SHED request was rejected: queue_full | projected_ttft |
    #: deadline_unmeetable | retry_budget (finish_reason stays "shed").
    shed_cause: Optional[str] = None
    #: Crash-recovery replays this request has survived (journal replay
    #: counts it each time the request was ACTIVE when the engine died).
    replays: int = 0
    #: The slot this request occupied when :meth:`Scheduler.finish`
    #: released it (``slot`` itself is cleared to -1 there). The paged
    #: engine reads this to free the right page-table row; None until
    #: the request has held — and left — a slot.
    released_slot: Optional[int] = None
    #: Chunked-prefill cursor: sequence positions whose K/V already sit
    #: in the cache (prefix-cache hits included). While the request is
    #: on the prefill queue this trails the prompt length and the slot
    #: is excluded from decode; the whole-prompt path sets it to the
    #: full prefilled length in one go. The engine also reads it at
    #: release time to bound prefix-cache registration to pages that
    #: were actually written.
    prefill_pos: int = 0

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_s is None:
            return None
        return self.finish_s - self.submit_s

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.submit_s


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Powers of two up to ``max_batch`` (always including it): one
    compiled decode program per bucket, log2(cap) programs total."""
    bs = [b for b in itertools.takewhile(lambda b: b < max_batch,
                                         (1 << i for i in range(31)))]
    return tuple(bs) + (max_batch,)


class Scheduler:
    """Slot-based continuous (or static) batching over ``max_batch`` KV
    slots. The engine drives it: ``admit()`` before each decode step,
    ``finish()``/``evict_deadline()`` after, ``bucket()`` to pick the
    compiled program."""

    def __init__(self, max_batch: int, *,
                 buckets: Optional[tuple[int, ...]] = None,
                 policy: str = "continuous",
                 max_queue: Optional[int] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if policy not in ("continuous", "static"):
            raise ValueError(f"unknown policy {policy!r}")
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_batch = max_batch
        self.policy = policy
        #: Bounded admission queue: ``submit`` of a request the engine did
        #: not shed still raises past this depth (belt and braces — the
        #: engine's shed path is the polite rejection). None = unbounded.
        self.max_queue = max_queue
        self.buckets = tuple(sorted(set(buckets or
                                        default_buckets(max_batch))))
        if self.buckets[-1] != max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} != max_batch "
                f"{max_batch}")
        self.queue: list[Request] = []  #: FIFO, arrival order
        #: active requests by slot; slots [0, num_active) are occupied.
        self.slots: list[Optional[Request]] = [None] * max_batch
        #: Chunked-prefill queue, arrival order: ACTIVE requests whose
        #: prompts are still being prefilled chunk-by-chunk. The engine
        #: drains the HEAD first (at most ``prefill_interleave`` chunks
        #: between decode steps), so chunk draining is arrival-ordered
        #: and starvation-free — a later long prompt cannot delay an
        #: earlier one's first token. Empty unless the engine runs with
        #: ``prefill_chunk > 0``.
        self.prefilling: list[Request] = []
        self.num_active = 0
        self._cohort = 0  #: static policy: admitted cohort size, sticky
        self._next_rid = 0

    # -- intake ---------------------------------------------------------------

    def submit(self, req: Request, *, now: float,
               rid: Optional[int] = None) -> Request:
        """Queue a request. ``rid`` pins a journal-recovered request to its
        original id (the rid counter jumps past it); fresh submissions get
        the next sequential id."""
        if not req.prompt:
            raise ValueError("empty prompt")
        if self.full():
            raise RuntimeError(
                f"admission queue full ({len(self.queue)} >= "
                f"{self.max_queue}); shed before submitting")
        if rid is None:
            rid = self._next_rid
        req.rid = rid
        self._next_rid = max(self._next_rid, rid + 1)
        req.submit_s = now
        req.status = QUEUED
        self.queue.append(req)
        return req

    def reserve_rid(self) -> int:
        """Consume the next request id without queueing anything — shed
        requests still need a stable id for the journal and the report."""
        rid = self._next_rid
        self._next_rid += 1
        return rid

    def full(self) -> bool:
        """True when the bounded admission queue is at capacity."""
        return (self.max_queue is not None
                and len(self.queue) >= self.max_queue)

    # -- admission ------------------------------------------------------------

    def admit(self, *, gate=None) -> list[Request]:
        """Move queued requests into free slots (FIFO); returns the newly
        admitted requests, each with ``slot`` assigned — the engine owes
        each one a prefill before the next decode step.

        ``gate`` (optional ``fn(req) -> bool``) is consulted before each
        admission and stops the round on the first False — the paged
        engine's free-page-headroom check, which replaces "is a slot
        free" as the real capacity question. FIFO order is preserved:
        a gated-out head request blocks those behind it (no reordering,
        no starvation inversion)."""
        if self.policy == "static" and self.num_active > 0:
            return []  # static cohorts run to completion before refilling
        admitted = []
        while self.queue and self.num_active < self.max_batch:
            if gate is not None and not gate(self.queue[0]):
                break
            req = self.queue.pop(0)
            req.slot = self.num_active
            req.status = ACTIVE
            self.slots[req.slot] = req
            self.num_active += 1
            admitted.append(req)
        if self.policy == "static" and admitted:
            self._cohort = self.num_active
        return admitted

    # -- chunked prefill queue ------------------------------------------------

    def enqueue_prefill(self, req: Request) -> None:
        """Put an admitted request on the chunk queue: its prompt will be
        prefilled ``prefill_chunk`` positions at a time, interleaved with
        decode steps, and its slot stays out of decode until the final
        chunk lands."""
        self.prefilling.append(req)

    def peek_prefill(self) -> Optional[Request]:
        """Arrival-order head of the chunk queue (None when empty)."""
        return self.prefilling[0] if self.prefilling else None

    def dequeue_prefill(self, req: Request) -> None:
        """Drop a request from the chunk queue — its final chunk landed,
        or it was evicted mid-prefill."""
        self.prefilling = [r for r in self.prefilling if r is not req]

    def is_prefilling(self, req: Request) -> bool:
        return any(r is req for r in self.prefilling)

    def ready(self) -> list[Request]:
        """Active requests eligible for decode: everyone whose prefill is
        complete. Identical to :meth:`active` when chunked prefill is
        off (the queue is empty)."""
        if not self.prefilling:
            return self.active()
        return [r for r in self.slots[:self.num_active]
                if not self.is_prefilling(r)]

    # -- step accounting ------------------------------------------------------

    def active(self) -> list[Request]:
        return [r for r in self.slots[:self.num_active]]

    def bucket(self) -> int:
        """Smallest configured bucket holding every active slot — or, under
        the static policy, the whole admitted cohort: drained slots keep
        paying padded-batch compute until the cohort completes (the cost
        continuous batching exists to reclaim)."""
        n = max(self.num_active, 1)
        if self.policy == "static":
            n = max(n, self._cohort)
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch  # unreachable: buckets[-1] == max_batch

    def record_token(self, req: Request, token: int, *, now: float) -> bool:
        """Append a generated token; returns True when the request is now
        complete (EOS or length). The caller still owns the slot until it
        calls :meth:`finish`."""
        if req.first_token_s is None:
            req.first_token_s = now
        req.generated.append(int(token))
        if req.eos_id is not None and int(token) == req.eos_id:
            req.finish_reason = "eos"
            return True
        if len(req.generated) >= req.max_new_tokens:
            req.finish_reason = "length"
            return True
        return False

    # -- release + compaction -------------------------------------------------

    def finish(self, req: Request, *, now: float,
               status: str = DONE) -> Optional[tuple[int, int]]:
        """Release a request's slot. Returns ``(freed, last)`` when the
        engine must mirror a cache-row swap (last active slot moved down
        into the freed slot), or None when the freed slot was already
        last. Call with descending slot numbers when releasing several at
        once, so earlier swaps don't invalidate later slot indices."""
        slot = req.slot
        if not (0 <= slot < self.num_active and self.slots[slot] is req):
            raise ValueError(f"request {req.rid} does not own slot {slot}")
        if self.prefilling:  # evicted mid-prefill: off the chunk queue too
            self.dequeue_prefill(req)
        req.status = status
        req.finish_s = now
        req.released_slot = slot
        req.slot = -1
        last = self.num_active - 1
        swap = None
        if slot != last:
            mover = self.slots[last]
            mover.slot = slot
            self.slots[slot] = mover
            swap = (slot, last)
        self.slots[last] = None
        self.num_active -= 1
        if self.num_active == 0:
            self._cohort = 0
        return swap

    def evict_deadline(self, *, now: float) -> list[tuple[Request,
                                                          Optional[tuple]]]:
        """Evict active requests past their deadline. Returns
        ``[(request, swap_or_None), ...]``; swaps are produced
        high-slot-first so the engine can apply them in order."""
        out = []
        stale = sorted(
            (r for r in self.slots[:self.num_active]
             if r.deadline_s is not None
             and now - r.submit_s > r.deadline_s),
            key=lambda r: r.slot, reverse=True)
        for req in stale:
            req.finish_reason = "deadline"
            out.append((req, self.finish(req, now=now, status=EVICTED)))
        # Expire queued requests too — they can't meet a blown deadline.
        still = []
        for req in self.queue:
            if (req.deadline_s is not None
                    and now - req.submit_s > req.deadline_s):
                req.status = EVICTED
                req.finish_s = now
                req.finish_reason = "deadline"
                out.append((req, None))
            else:
                still.append(req)
        self.queue = still
        return out

    # -- introspection --------------------------------------------------------

    def queue_depth(self) -> int:
        return len(self.queue)

    def idle(self) -> bool:
        return self.num_active == 0 and not self.queue
