"""Serve-path chaos: crash/stall/storm faults with anti-vacuity gates.

The serving counterpart of ``tpu_dist.resilience.cli`` (the training chaos
runner), reached through ``python -m tpu_dist.serve --chaos --plan ...``.
Three fault kinds, same FaultPlan grammar, same report discipline:

* ``engine_crash@reqN`` / ``decode_stall@reqN[:Ss]`` run END-TO-END: an
  uninterrupted in-process **baseline** records every request's greedy
  token stream; then a :class:`~tpu_dist.serve.supervisor.ServeSupervisor`
  runs the same workload as a ``--worker`` subprocess with the plan armed,
  the engine dies mid-decode (injected ``os._exit``, or the stall watchdog
  exiting :data:`~tpu_dist.resilience.faults.EXIT_SERVE_ABORT`), restarts,
  and REPLAYS the shared journal. Gates: the fault must actually fire
  (vacuous otherwise), the engine must actually restart, recovery must go
  through a journal replay (a restart that serves from an empty journal is
  a silent data-loss bug, not recovery), and the final per-request token
  streams read back from the journal must be **bit-identical** to the
  baseline.
* ``request_storm@reqN`` runs in process on a :class:`VirtualClock`: the
  engine's ``virtual_step_s`` advances the clock per decode step, so
  queueing delay is measured in deterministic virtual seconds (host speed
  cancels out). A **shedding** run (bounded queue + projected-TTFT bound)
  must keep admitted-request p99 latency within the ``BENCH_SERVE.json``
  target while a **control** run with shedding disabled must blow it —
  the overload protection has to be both load-bearing and non-vacuous.

The report is JSON on stdout; exit 0 iff every gate passes.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile
import time
from typing import Optional

import numpy as np

from tpu_dist.observe import metrics
from tpu_dist.resilience import events
from tpu_dist.resilience.faults import (FAULT_PLAN_ENV, FLEET_KINDS,
                                        FaultPlan, SERVE_KINDS, describe)
from tpu_dist.serve.scheduler import DONE, EVICTED, SHED

#: Default p99 latency target (virtual seconds) for the storm gate when
#: ``BENCH_SERVE.json`` is not found next to the repo root.
DEFAULT_P99_TARGET_S = 15.0


class VirtualClock:
    """A monotonic clock that only moves when told to. The storm gate
    injects it as the engine clock with ``virtual_step_s > 0``, making
    every submit/first-token/finish timestamp a deterministic function of
    the schedule rather than of host speed."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def bench_p99_target_s() -> float:
    """The serving p99 target from ``BENCH_SERVE.json`` (repo root), or
    the default when the file is missing/unparseable."""
    path = pathlib.Path(__file__).resolve().parents[2] / "BENCH_SERVE.json"
    try:
        cfg = json.loads(path.read_text()).get("config", {})
        return float(cfg.get("p99_target_s", DEFAULT_P99_TARGET_S))
    except (OSError, ValueError):
        return DEFAULT_P99_TARGET_S


# -- the supervised worker (one attempt of the chaos run) ---------------------


def run_worker(args) -> int:
    """``--worker`` mode: serve the seeded workload once, under whatever
    journal/fault-plan environment the supervisor armed.

    Resubmission is idempotent: workload index == request id (requests are
    submitted in order before any shedding), so any index already in the
    recovered journal — finished, replayed, or shed — is skipped; the
    journal replay, not resubmission, owns those requests."""
    from tpu_dist.resilience.injector import maybe_serve_injector_from_env
    from tpu_dist.serve import journal as journal_lib
    from tpu_dist.serve.cli import _build_engine, _workload

    metrics.get_registry().reset()
    metrics.enable()
    jdir = journal_lib.journal_dir_from_env() or args.journal_dir
    engine = _build_engine(
        args, journal=jdir, max_queue=args.max_queue,
        max_ttft_s=args.max_ttft_s, retry_budget=args.retry_budget,
        stall_timeout_s=args.stall_timeout_s,
        fault_injector=maybe_serve_injector_from_env())
    workload = _workload(args)
    skipped = 0
    for i, w in enumerate(workload):
        if i in engine.known_rids:
            skipped += 1
            continue
        engine.submit(w["prompt"], max_new_tokens=w["max_new_tokens"],
                      deadline_s=args.deadline_s)
    engine.run_until_idle()
    engine.close()
    metrics.disable()
    by_status = {s: [r for r in engine.finished if r.status == s]
                 for s in (DONE, EVICTED, SHED)}
    result = {
        "attempt": events.current_attempt(),
        "completed": len(by_status[DONE]),
        "evicted": len(by_status[EVICTED]),
        "shed": len(by_status[SHED]),
        "resubmit_skipped": skipped,
        "replay": engine.last_replay,
    }
    print("RESULT:" + json.dumps(result))
    return 0 if result["completed"] > 0 else 1


# -- baseline (uninterrupted, in process) -------------------------------------


def baseline_token_streams(args) -> dict:
    """Serve the whole workload in process with no journal and no faults;
    returns ``{rid: [tokens...]}`` — the parity reference. Per-request
    greedy decode is independent of batch composition (pinned in
    ``test_serve.py``), so this is THE answer regardless of how recovery
    reshuffles scheduling."""
    from tpu_dist.serve.cli import _build_engine, _workload

    engine = _build_engine(args)
    reqs = [engine.submit(w["prompt"], max_new_tokens=w["max_new_tokens"])
            for w in _workload(args)]
    engine.run_until_idle()
    return {r.rid: list(r.generated) for r in reqs}


# -- the storm gate (in process, virtual time) --------------------------------


def run_storm(args, *, shedding: bool, target_s: float) -> dict:
    """One storm run: ``--storm-requests`` chaff requests submitted in
    bursts between decode rounds, latency measured on the virtual clock.
    ``shedding`` arms the bounded queue + projected-TTFT bound; the
    control run takes the full storm and eats the queueing delay."""
    from tpu_dist.serve.cli import _build_engine, _quantile

    clock = VirtualClock()
    max_queue = (args.max_queue if args.max_queue is not None
                 else 2 * args.max_batch) if shedding else None
    max_ttft = (args.max_ttft_s if args.max_ttft_s is not None
                else target_s / 2.0) if shedding else None
    engine = _build_engine(args, clock=clock,
                           virtual_step_s=args.virtual_step_s,
                           max_queue=max_queue, max_ttft_s=max_ttft)
    rng = np.random.default_rng(args.seed)
    n = args.storm_requests
    submitted = 0
    rounds = 0
    while submitted < n or not engine.scheduler.idle():
        burst = min(args.storm_burst, n - submitted)
        for _ in range(burst):
            plen = int(rng.integers(2, max(3, args.max_len // 4)))
            engine.submit(
                rng.integers(0, args.vocab, size=plen).tolist(),
                max_new_tokens=int(rng.integers(args.min_new,
                                                args.max_new + 1)))
            submitted += 1
        engine.step()
        rounds += 1
        if rounds > 100 * n:
            raise RuntimeError("storm run failed to drain")
    done = [r for r in engine.finished if r.status == DONE]
    shed = [r for r in engine.finished if r.status == SHED]
    lat = [r.latency_s for r in done if r.latency_s is not None]
    p99 = _quantile(lat, 0.99)
    return {
        "mode": "shedding" if shedding else "control",
        "requests": n,
        "completed": len(done),
        "shed": len(shed),
        "shed_causes": sorted({r.shed_cause for r in shed
                               if r.shed_cause is not None}),
        "p99_latency_virtual_s": p99,
        "virtual_makespan_s": round(clock.t, 6),
        "decode_rounds": rounds,
    }


# -- the chaos driver ---------------------------------------------------------


def _worker_cmd(args, *, stall_timeout_s: Optional[float]) -> list:
    cmd = [sys.executable, "-m", "tpu_dist.serve", "--worker",
           "--requests", str(args.requests),
           "--max-batch", str(args.max_batch),
           "--max-len", str(args.max_len),
           "--min-new", str(args.min_new),
           "--max-new", str(args.max_new),
           "--vocab", str(args.vocab),
           "--d-model", str(args.d_model),
           "--depth", str(args.depth),
           "--num-heads", str(args.num_heads),
           "--seed", str(args.seed)]
    if args.model_dir:
        cmd += ["--model-dir", args.model_dir]
    if stall_timeout_s is not None:
        cmd += ["--stall-timeout-s", str(stall_timeout_s)]
    if args.retry_budget is not None:
        cmd += ["--retry-budget", str(args.retry_budget)]
    return cmd


def _clean_env(extra: dict) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in (FAULT_PLAN_ENV, events.EVENT_LOG_ENV,
                        events.ATTEMPT_ENV)
           and not k.startswith("TPU_DIST_SERVE")}
    env.update(extra)
    return env


def run_chaos(args) -> int:
    """``--chaos`` mode: run the plan's serve faults, print the gated
    JSON report, exit 0 iff every gate holds."""
    from tpu_dist.serve import journal as journal_lib
    from tpu_dist.serve.supervisor import ServeSupervisor

    if args.temperature != 0.0:
        print("error: --chaos requires greedy decoding (--temperature 0); "
              "the token-parity gate is a greedy guarantee", file=sys.stderr)
        return 2
    plan = FaultPlan.parse(args.plan) if args.plan else None
    fleet_faults = ([f for f in plan.faults if f.kind in FLEET_KINDS]
                    if plan else [])
    if fleet_faults:
        print(f"error: fault kind(s) "
              f"{sorted({f.kind for f in fleet_faults})} target the fleet "
              f"router; run them through --fleet, not --chaos",
              file=sys.stderr)
        return 2
    serve_faults = ([f for f in plan.faults if f.kind in SERVE_KINDS]
                    if plan else [])
    if not serve_faults:
        print("error: --chaos needs --plan with at least one serve fault "
              "(engine_crash@reqN / decode_stall@reqN / request_storm@reqN)",
              file=sys.stderr)
        return 2
    engine_faults = [f for f in serve_faults if f.kind != "request_storm"]
    storm_faults = [f for f in serve_faults if f.kind == "request_storm"]
    workdir = pathlib.Path(args.workdir or tempfile.mkdtemp(
        prefix="tpu-dist-serve-chaos-"))
    workdir.mkdir(parents=True, exist_ok=True)
    print(f"serve chaos workdir: {workdir}", file=sys.stderr)
    for line in describe(plan):
        print(f"fault: {line}", file=sys.stderr)

    report: dict = {"plan": plan.to_json(), "workdir": str(workdir)}
    ok = True

    if engine_faults:
        # Arm the stall watchdog whenever the plan stalls a decode step —
        # the watchdog, not the injector, is what converts the hang into a
        # classified restartable exit.
        stall_to = args.stall_timeout_s
        if stall_to is None and any(f.kind == "decode_stall"
                                    for f in engine_faults):
            stall_to = 1.0

        print("running baseline (uninterrupted, in process)...",
              file=sys.stderr)
        baseline = baseline_token_streams(args)

        print("running supervised chaos serve...", file=sys.stderr)
        event_path = workdir / "events.jsonl"
        sup = ServeSupervisor(
            _worker_cmd(args, stall_timeout_s=stall_to),
            journal_dir=workdir / "journal",
            max_restarts=args.max_restarts,
            attempt_deadline_s=args.deadline,
            env=_clean_env({FAULT_PLAN_ENV: plan.dumps(),
                            events.EVENT_LOG_ENV: str(event_path)}),
            log_dir=workdir / "logs",
            event_log=events.EventLog(event_path, role="supervisor"))
        t0 = time.monotonic()
        sup_report = sup.run()
        final = sup.final_result(sup_report)
        state = sup.journal_state()
        fired = events.read_events(event_path, "fault_fired")
        sup_json = sup_report.to_json()

        mismatches = []
        for rid, want in sorted(baseline.items()):
            jr = state.requests.get(rid)
            got = list(jr.tokens) if jr is not None else None
            if jr is None or not jr.finished or got != want:
                mismatches.append({
                    "rid": rid, "expected": want, "got": got,
                    "finished": bool(jr is not None and jr.finished)})
        replays = state.replay_markers
        report["engine"] = {
            "success": sup_report.success,
            "attempts": sup_report.attempts,
            "restarts": sup_report.restarts,
            "exit_codes": [o.exit_codes for o in sup_report.outcomes],
            "exit_kinds": sup_json["exit_kinds"],
            "wall_time_s": round(time.monotonic() - t0, 3),
            "faults_fired": [
                {k: r.get(k) for k in ("kind", "req", "done", "seconds")
                 if r.get(k) is not None} for r in fired],
            "journal_records": state.records,
            "journal_replays": [
                {k: m.get(k) for k in ("attempt", "active", "queued",
                                       "completed", "replay_s")}
                for m in replays],
            "final_result": final,
            "baseline_requests": len(baseline),
            "token_mismatches": mismatches,
        }
        if not sup_report.success:
            ok = False
            report["failure"] = "supervised serve run did not succeed"
        elif not fired:
            ok = False
            report["failure"] = "no fault fired — vacuous chaos run"
        elif sup_report.restarts < 1:
            ok = False
            report["failure"] = ("engine fault plan but the engine never "
                                 "restarted — vacuous chaos run")
        elif not replays:
            ok = False
            report["failure"] = (
                "engine restarted without a journal replay — the restart "
                "served from scratch (silent request loss, not recovery)")
        elif mismatches:
            ok = False
            report["failure"] = (
                f"token parity violated for {len(mismatches)} request(s)")
        else:
            report["engine"]["parity_ok"] = True

    if storm_faults:
        target = (args.p99_target_s if args.p99_target_s is not None
                  else bench_p99_target_s())
        print(f"running request storm (shedding vs control, p99 target "
              f"{target}s virtual)...", file=sys.stderr)
        shed_run = run_storm(args, shedding=True, target_s=target)
        control = run_storm(args, shedding=False, target_s=target)
        report["storm"] = {"p99_target_s": target,
                           "shedding": shed_run, "control": control}
        sp99, cp99 = (shed_run["p99_latency_virtual_s"],
                      control["p99_latency_virtual_s"])
        if shed_run["shed"] <= 0:
            ok = False
            report["failure"] = ("storm run shed nothing — overload "
                                 "protection never engaged (vacuous)")
        elif sp99 is None or sp99 > target:
            ok = False
            report["failure"] = (
                f"admitted-request p99 {sp99}s blew the {target}s target "
                f"despite shedding")
        elif cp99 is not None and cp99 <= target:
            ok = False
            report["failure"] = (
                f"no-shedding control p99 {cp99}s met the target anyway — "
                f"the storm is too small to prove shedding matters")
        else:
            report["storm"]["ok"] = True

    report["ok"] = ok
    out = json.dumps(report, indent=2)
    print(out)
    if args.report:
        pathlib.Path(args.report).write_text(out + "\n")
    return 0 if ok else 1
