import sys

from tpu_dist.serve.cli import main

sys.exit(main())
