"""ServeEngine: compiled-program inference runtime on the training mesh.

The engine owns the three compiled surfaces serving needs and nothing
else — scheduling stays host-side in ``scheduler.py``, math stays in
``kv_cache.py``:

* one **prefill** program per padded prompt length (prompts pad up to a
  power of two, so a stream of ragged prompts compiles O(log max_len)
  programs, not O(distinct lengths));
* one **decode** program per padded batch *bucket* (``scheduler.
  default_buckets``): requests come and go between steps, the active
  count maps to the smallest covering bucket, and steady-state serving
  never retraces — the same no-retrace discipline ``Trainer.predict``
  now follows;
* one **slot-swap** program (traced slot indices) mirroring the
  scheduler's compaction moves into the KV cache.

Weights come from a live model's materialized variables or a
``models/serialize.py`` saved-model directory (:meth:`ServeEngine.
from_saved`), and are placed on the active ``Strategy``'s mesh via
``strategy.replicate`` — the same placement training uses, so a model
can go fit() → save → serve without leaving the mesh.

Every step emits host-side observe metrics (never inside jit —
shardcheck SC103 guards this): ``serve.request.latency_s`` /
``serve.request.ttft_s`` / ``serve.batch.occupancy`` distributions (the
registry's reservoir quantiles give p50/p95/p99 directly),
``serve.queue.depth`` gauge, and ``serve.{requests.*,tokens.generated,
decode.steps,prefills}`` counters. Arm ``$TPU_DIST_OBSERVE_DIR`` (or
call ``metrics.enable()``) to record; disabled is free.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.models.model import Sequential
from tpu_dist.observe import metrics
from tpu_dist.parallel.strategy import get_strategy
from tpu_dist.serve import kv_cache
from tpu_dist.serve.scheduler import DONE, Request, Scheduler

logger = logging.getLogger(__name__)

_MIN_PROMPT_PAD = 8


def _pad_to_pow2(n: int, *, lo: int = _MIN_PROMPT_PAD, hi: int) -> int:
    p = lo
    while p < n:
        p <<= 1
    return min(p, hi)


class ServeEngine:
    """Continuous-batching decode loop over a fixed pool of KV slots.

    Args:
      model: a ``Sequential`` from the servable family (see
        ``kv_cache.build_plan``). Weights are taken from the model's live
        variables when materialized, else freshly initialized from
        ``seed`` (the demo path).
      max_batch: KV slots == maximum concurrent requests.
      max_len: per-slot cache capacity (prompt + generated tokens);
        defaults to the model's positional-table length.
      buckets / policy: forwarded to :class:`Scheduler`.
      temperature: 0 = greedy argmax; > 0 samples from the tempered
        softmax with a host-side seeded generator (deterministic runs).
      clock: injectable monotonic clock (tests pin deadlines with it).
    """

    def __init__(self, model: Sequential, *, max_batch: int = 8,
                 max_len: Optional[int] = None,
                 buckets: Optional[tuple[int, ...]] = None,
                 policy: str = "continuous", temperature: float = 0.0,
                 seed: int = 0, cache_dtype=jnp.float32, clock=None):
        self.model = model
        self.plan = kv_cache.build_plan(model)
        self.max_len = int(max_len or self.plan.max_position)
        if self.max_len > self.plan.max_position:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's positional "
                f"table ({self.plan.max_position})")
        self.max_batch = int(max_batch)
        self.temperature = float(temperature)
        self.clock = clock or time.monotonic
        self._rng = np.random.default_rng(seed)
        self.strategy = model.strategy or get_strategy()

        variables = model.variables
        params = (variables["params"] if variables is not None
                  else model.init(seed)["params"])
        # Same mesh placement training uses; on the default single-device
        # strategy this is a no-op device_put.
        self.params = self.strategy.replicate(params)
        self.cache = self.strategy.replicate(kv_cache.init_cache(
            self.plan, max_batch=self.max_batch, max_len=self.max_len,
            dtype=cache_dtype))
        logger.info(
            "serve: %d slots x %d positions, KV cache %.1f MiB, "
            "buckets %s", self.max_batch, self.max_len,
            kv_cache.cache_nbytes(self.plan, max_batch=self.max_batch,
                                  max_len=self.max_len,
                                  dtype=cache_dtype) / 2**20,
            buckets or "pow2")

        self.scheduler = Scheduler(self.max_batch, buckets=buckets,
                                   policy=policy)
        # Host mirrors of per-slot decode state (compacted with the
        # scheduler's slot moves).
        self._tokens = np.zeros(self.max_batch, np.int32)
        self._lengths = np.zeros(self.max_batch, np.int32)
        self.finished: list[Request] = []

        # CPU XLA has no buffer donation — donating there only logs
        # warnings; on TPU the cache updates in place (no per-step copy).
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._decode_fns: dict[int, callable] = {}
        self._prefill_fns: dict[int, callable] = {}
        self._donate = donate
        self._swap_fn = jax.jit(kv_cache.swap_slots,
                                donate_argnums=(0,) if donate else ())

    @classmethod
    def from_saved(cls, directory, **kwargs) -> "ServeEngine":
        """Load a ``save_model`` directory (weights restored, no training
        compile) and serve it."""
        from tpu_dist.models import serialize

        model = serialize.load_model(directory, compile=False)
        return cls(model, **kwargs)

    # -- compiled-program cache ----------------------------------------------

    def _decode_fn(self, bucket: int):
        fn = self._decode_fns.get(bucket)
        if fn is None:
            fn = jax.jit(functools.partial(kv_cache.decode_step, self.plan,
                                           bucket=bucket),
                         donate_argnums=self._donate)
            self._decode_fns[bucket] = fn
        return fn

    def _prefill_fn(self, pad_len: int):
        fn = self._prefill_fns.get(pad_len)
        if fn is None:
            fn = jax.jit(functools.partial(kv_cache.prefill, self.plan),
                         donate_argnums=self._donate)
            self._prefill_fns[pad_len] = fn
        return fn

    def compiled_programs(self) -> dict:
        """{'decode': [buckets...], 'prefill': [pad_lens...]} — tests pin
        the no-retrace property on this."""
        return {"decode": sorted(self._decode_fns),
                "prefill": sorted(self._prefill_fns)}

    # -- request intake -------------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        prompt = [int(t) for t in prompt]
        if len(prompt) > self.max_len - 1:
            raise ValueError(
                f"prompt of {len(prompt)} tokens does not fit a "
                f"{self.max_len}-position cache slot (need >= 1 free)")
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_id=eos_id, deadline_s=deadline_s)
        self.scheduler.submit(req, now=self.clock())
        metrics.inc("serve.requests.submitted")
        return req

    # -- sampling (host-side) -------------------------------------------------

    def _pick(self, logits: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        return int(self._rng.choice(logits.shape[-1], p=p / p.sum()))

    # -- the serving loop -----------------------------------------------------

    def _apply_swap(self, swap: Optional[tuple[int, int]]) -> None:
        if swap is None:
            return
        i, j = swap
        self.cache = self._swap_fn(self.cache, jnp.int32(i), jnp.int32(j))
        self._tokens[[i, j]] = self._tokens[[j, i]]
        self._lengths[[i, j]] = self._lengths[[j, i]]

    def _retire(self, req: Request, *, now: float, status: str) -> None:
        swap = self.scheduler.finish(req, now=now, status=status)
        self._apply_swap(swap)
        self.finished.append(req)
        if status == DONE:
            metrics.inc("serve.requests.completed")
            if req.latency_s is not None:
                metrics.observe_value("serve.request.latency_s",
                                      req.latency_s)
            if req.ttft_s is not None:
                metrics.observe_value("serve.request.ttft_s", req.ttft_s)
        else:
            metrics.inc("serve.requests.evicted")

    def _prefill(self, req: Request) -> None:
        plen = len(req.prompt)
        pad = _pad_to_pow2(plen, hi=self.max_len)
        tokens = np.zeros(pad, np.int32)
        tokens[:plen] = req.prompt
        fn = self._prefill_fn(pad)
        self.cache, logits = fn(self.params, self.cache,
                                jnp.asarray(tokens), jnp.int32(plen),
                                jnp.int32(req.slot))
        metrics.inc("serve.prefills")
        now = self.clock()
        token = self._pick(np.asarray(logits))
        done = self.scheduler.record_token(req, token, now=now)
        metrics.inc("serve.tokens.generated")
        self._tokens[req.slot] = token
        self._lengths[req.slot] = plen
        if done or plen >= self.max_len:
            self._retire(req, now=now, status=DONE)

    def step(self) -> int:
        """One scheduling round: deadline evictions → admissions (each
        pays its prefill and emits its first token) → one decode step for
        the active bucket. Returns the number of still-active requests."""
        now = self.clock()
        for req, swap in self.scheduler.evict_deadline(now=now):
            self._apply_swap(swap)
            self.finished.append(req)
            metrics.inc("serve.requests.evicted")

        for req in self.scheduler.admit():
            self._prefill(req)
        metrics.set_gauge("serve.queue.depth", self.scheduler.queue_depth())

        n = self.scheduler.num_active
        if n == 0:
            return 0
        bucket = self.scheduler.bucket()
        metrics.observe_value("serve.batch.occupancy", n / bucket)
        self.cache, logits = self._decode_fn(bucket)(
            self.params, self.cache, jnp.asarray(self._tokens),
            jnp.asarray(self._lengths))
        metrics.inc("serve.decode.steps")
        logits = np.asarray(logits)
        now = self.clock()
        completed = []
        for req in self.scheduler.active():
            token = self._pick(logits[req.slot])
            self._lengths[req.slot] += 1
            self._tokens[req.slot] = token
            done = self.scheduler.record_token(req, token, now=now)
            metrics.inc("serve.tokens.generated")
            if done or self._lengths[req.slot] >= self.max_len:
                completed.append(req)
        # Highest slot first: each swap moves the (untouched) last slot.
        for req in sorted(completed, key=lambda r: r.slot, reverse=True):
            self._retire(req, now=now, status=DONE)
        return self.scheduler.num_active

    def run_until_idle(self, *, max_steps: int = 100_000) -> list[Request]:
        """Drive :meth:`step` until queue and batch drain; returns all
        requests finished so far (done + evicted, completion order)."""
        steps = 0
        while not self.scheduler.idle():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serve loop still busy after {max_steps} steps "
                    f"({self.scheduler.num_active} active, "
                    f"{self.scheduler.queue_depth()} queued)")
        return self.finished

    def generate(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None) -> list[int]:
        """Single-request convenience: submit, drain, return the tokens."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          eos_id=eos_id)
        self.run_until_idle()
        return req.generated
