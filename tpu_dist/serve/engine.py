"""ServeEngine: compiled-program inference runtime on the training mesh.

The engine owns the three compiled surfaces serving needs and nothing
else — scheduling stays host-side in ``scheduler.py``, math stays in
``kv_cache.py``:

* one **prefill** program per padded prompt length (prompts pad up to a
  power of two, so a stream of ragged prompts compiles O(log max_len)
  programs, not O(distinct lengths));
* one **decode** program per padded batch *bucket* (``scheduler.
  default_buckets``): requests come and go between steps, the active
  count maps to the smallest covering bucket, and steady-state serving
  never retraces — the same no-retrace discipline ``Trainer.predict``
  now follows. ``ragged=True`` (paged only) collapses the family to a
  single full-capacity program with a per-slot active mask;
* one **slot-swap** program (traced slot indices) mirroring the
  scheduler's compaction moves into the KV cache.

Weights come from a live model's materialized variables or a
``models/serialize.py`` saved-model directory (:meth:`ServeEngine.
from_saved`), and are placed on the active ``Strategy``'s mesh via
``strategy.replicate`` — the same placement training uses, so a model
can go fit() → save → serve without leaving the mesh.

Every step emits host-side observe metrics (never inside jit —
shardcheck SC103 guards this): ``serve.request.latency_s`` /
``serve.request.ttft_s`` / ``serve.batch.occupancy`` distributions (the
registry's reservoir quantiles give p50/p95/p99 directly),
``serve.queue.depth`` / ``serve.ready`` gauges, and
``serve.{requests.*,tokens.generated,decode.steps,prefills}`` counters.
Arm ``$TPU_DIST_OBSERVE_DIR`` (or call ``metrics.enable()``) to record;
disabled is free.

Resilience (see ``serve/journal.py`` and README "Serving resilience"):
an optional durable request journal makes a supervised restart replay
queued and in-flight requests with token-identical greedy continuations;
a bounded admission queue + projected-TTFT/deadline feasibility checks
shed load the engine cannot serve (``finish_reason="shed"``); a decode-
stall watchdog converts a hung decode step into a classified fault
(:data:`~tpu_dist.resilience.faults.EXIT_SERVE_ABORT`) instead of
blocking the serving loop forever.
"""

from __future__ import annotations

import functools
import itertools
import logging
import sys
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist.models.model import Sequential
from tpu_dist.observe import metrics
from tpu_dist.parallel.strategy import get_strategy
from tpu_dist.serve import kv_cache, paging
from tpu_dist.serve import journal as journal_lib
from tpu_dist.serve.scheduler import DONE, SHED, Request, Scheduler

logger = logging.getLogger(__name__)

_MIN_PROMPT_PAD = 8

#: EMA smoothing for the decode-step wall-time estimate behind the
#: projected-TTFT admission check.
_EMA_ALPHA = 0.3


def _default_stall_action(info: dict) -> None:
    """What a production engine does about a hung decode step: classify it
    as a fault and die with the registered serve exit code — the
    ServeSupervisor restarts the engine and the journal replays the work.
    ``os._exit`` on purpose: the main thread is wedged inside the runtime,
    so no Python-level unwind can run."""
    import os as _os

    from tpu_dist.resilience import events
    from tpu_dist.resilience.faults import EXIT_SERVE_ABORT

    logger.error("serve: decode step stalled > %.3fs (bucket %s) — "
                 "exiting %d (serve_abort) for supervised restart",
                 info.get("timeout_s", -1.0), info.get("bucket"),
                 EXIT_SERVE_ABORT)
    events.maybe_log("serve_stall", **info)
    _os._exit(EXIT_SERVE_ABORT)


def _pad_to_pow2(n: int, *, lo: int = _MIN_PROMPT_PAD, hi: int) -> int:
    p = lo
    while p < n:
        p <<= 1
    return min(p, hi)


def _current_job():
    """The active multi-tenant job scope — probed through sys.modules so
    a solo engine that never imports :mod:`tpu_dist.jobs` pays nothing,
    not even the import (the jobs runtime's solo no-op contract)."""
    mod = sys.modules.get("tpu_dist.jobs.runtime")
    return mod.current_job() if mod is not None else None


#: Monotonic engine generation counter — keys pool-cached decode/prefill
#: programs to one engine instance (its plan, donation mode, and KV-cache
#: shapes are baked into the traced closures).
_ENGINE_SERIALS = itertools.count()


class ServeEngine:
    """Continuous-batching decode loop over a fixed pool of KV slots.

    Args:
      model: a ``Sequential`` from the servable family (see
        ``kv_cache.build_plan``). Weights are taken from the model's live
        variables when materialized, else freshly initialized from
        ``seed`` (the demo path).
      max_batch: KV slots == maximum concurrent requests.
      max_len: per-slot cache capacity (prompt + generated tokens);
        defaults to the model's positional-table length.
      buckets / policy: forwarded to :class:`Scheduler`.
      temperature: 0 = greedy argmax; > 0 samples from the tempered
        softmax with a host-side seeded generator (deterministic runs).
      clock: injectable monotonic clock (tests pin deadlines with it).
      journal: a :class:`~tpu_dist.serve.journal.RequestJournal`, or a
        directory path to open one in. When the directory already holds a
        journal, the engine RECOVERS before serving: journaled-but-
        unfinished requests are re-admitted in arrival order, formerly
        active ones re-prefilled with ``prompt + tokens_emitted_so_far``
        (token-identical greedy continuation).
      max_queue: bounded admission queue — submissions past this depth are
        shed (``finish_reason="shed"``, cause ``queue_full``).
      max_ttft_s: shed a submission whose projected time-to-first-token
        (queue + active work ahead of it, at the EMA decode-step time)
        exceeds this bound (cause ``projected_ttft``).
      retry_budget: a journal-replayed request found ACTIVE in more than
        this many crashes is shed instead of re-admitted (cause
        ``retry_budget``) — poison-pill protection.
      stall_timeout_s: decode-stall watchdog — a decode step (dispatch
        through host materialization) exceeding this wall bound triggers
        ``stall_action`` (default: exit ``EXIT_SERVE_ABORT`` for a
        supervised restart). None disables the watchdog (no per-step cost).
      stall_action: injectable watchdog action (tests record instead of
        exiting); receives an info dict.
      fault_injector: serve chaos seam — an object with ``on_decode`` /
        ``on_step_end`` hooks (see
        :class:`~tpu_dist.resilience.injector.ServeFaultInjector`).
      virtual_step_s: when > 0 and ``clock`` has an ``advance`` method,
        the engine advances the injected clock by this much per decode
        step — a deterministic stand-in for a production-sized model's
        step time, used by the request-storm chaos gate so queueing-delay
        measurements don't depend on host speed.
      paged: select the paged KV-cache subsystem (``serve/paging.py``):
        HBM is carved into fixed-size pages addressed through per-slot
        page tables, admission consults free-page headroom instead of
        slot count alone, repeated prompt prefixes resolve to shared
        read-only pages (prefill runs only over the suffix), and slot
        compaction becomes a host pointer swap. Greedy token streams are
        bit-identical to the contiguous default (tests + serve-bench pin
        it). Default False: the contiguous path and its compiled
        programs are untouched.
      page_size: positions per page (paged mode). Small pages waste less
        HBM on short requests and share prefixes at finer grain; large
        pages mean fewer gather indices per attention step.
      num_pages: pool size (paged mode). Defaults to
        ``max_batch * ceil(max_len / page_size)`` — contiguous-capacity
        parity; pass fewer (or a ``budget_bytes``) to overcommit slots
        against actual request lengths.
      budget_bytes: hard KV-memory bound. Contiguous mode: raise a loud
        sizing error (how many slots fit) instead of an XLA OOM. Paged
        mode: sizes ``num_pages`` to the budget when ``num_pages`` is
        not given, else guards the explicit pool the same way.
      prefix_caching: paged mode only — disable to keep paging without
        cross-request prefix sharing (parity baselines use this).
      prefill_chunk: when > 0, split each admitted prompt's prefill into
        chunks of this many positions (power of two >= 8) and interleave
        them with decode steps, so one long prompt no longer stalls
        every in-flight decode stream for a whole-prompt causal pass.
        Each chunk attends over all prior cached positions — attention
        is never reordered — so greedy streams stay token-identical to
        whole-prompt prefill (tests + serve-bench pin it). A slot being
        chunk-prefilled is excluded from decode (cursor on the request)
        until its final chunk lands; the final chunk emits the first
        token. Ragged final chunks pad to a power of two, so the chunk
        program cache holds at most log2(prefill_chunk / 8) + 1
        programs. Default 0: whole-prompt prefill, compiled programs and
        scheduling byte-identical to previous behavior. Tune it to
        roughly the per-step decode token budget: smaller chunks give
        flatter inter-token latency, larger chunks finish long prompts
        in fewer (cheaper-per-token) passes.
      prefill_interleave: max prefill chunks run between consecutive
        decode steps (default 1 — the flattest-latency policy). Chunks
        drain arrival-ordered (the head request finishes before a later
        one starts), so chunked prefill cannot starve anyone.
      kv_dtype: paged-pool storage dtype — ``"fp32"``/``"bf16"``/
        ``"int8"`` (or the jnp dtypes). ``"int8"`` stores K/V pages as
        int8 with per-position fp32 scale rows: a fixed ``budget_bytes``
        buys ~2x the pages (gate: >= 1.8x concurrent slots in
        serve-bench), greedy streams stay token-identical on short
        horizons and logit drift stays bounded on long ones
        (quantization is write-order independent, so chunked prefill,
        COW, and journal replay all reproduce exact pool bytes). Paged
        mode only — the contiguous cache keeps ``cache_dtype``. Default
        None: the pool dtype is ``cache_dtype``, programs byte-unchanged.
      ragged: paged mode only — decode ALL slots in one full-capacity
        program with a per-slot active mask instead of the pow2-bucket
        program family. The page-table gather already erased contiguity,
        so bucketing is pure retrace surface: ragged engines compile
        exactly ONE decode program and stream token-identically to
        bucketed ones (tests + serve-bench pin both). Default False:
        the bucketed family remains (it is the contiguous engine's only
        mode and the bench's A/B control).
    """

    def __init__(self, model: Sequential, *, max_batch: int = 8,
                 max_len: Optional[int] = None,
                 buckets: Optional[tuple[int, ...]] = None,
                 policy: str = "continuous", temperature: float = 0.0,
                 seed: int = 0, cache_dtype=jnp.float32, clock=None,
                 journal=None, max_queue: Optional[int] = None,
                 max_ttft_s: Optional[float] = None, retry_budget: int = 3,
                 stall_timeout_s: Optional[float] = None,
                 stall_action=None, fault_injector=None,
                 virtual_step_s: float = 0.0, paged: bool = False,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 budget_bytes: Optional[int] = None,
                 prefix_caching: bool = True, prefill_chunk: int = 0,
                 prefill_interleave: int = 1, kv_dtype=None,
                 ragged: bool = False):
        self.model = model
        self.plan = kv_cache.build_plan(model)
        self.max_len = int(max_len or self.plan.max_position)
        if self.max_len > self.plan.max_position:
            raise ValueError(
                f"max_len {self.max_len} exceeds the model's positional "
                f"table ({self.plan.max_position})")
        self.prefill_chunk = int(prefill_chunk)
        self.prefill_interleave = int(prefill_interleave)
        if self.prefill_chunk:
            if (self.prefill_chunk < _MIN_PROMPT_PAD
                    or self.prefill_chunk & (self.prefill_chunk - 1)):
                raise ValueError(
                    f"prefill_chunk must be a power of two >= "
                    f"{_MIN_PROMPT_PAD}, got {prefill_chunk}")
            if not paged and self.max_len % self.prefill_chunk:
                raise ValueError(
                    f"prefill_chunk {self.prefill_chunk} must divide "
                    f"max_len {self.max_len} on the contiguous path — "
                    "chunk K/V writes are dynamic_update_slice windows "
                    "that must never run past the cache row")
        if self.prefill_interleave < 1:
            raise ValueError(
                f"prefill_interleave must be >= 1, got {prefill_interleave}")
        self.max_batch = int(max_batch)
        self.temperature = float(temperature)
        self.clock = clock or time.monotonic
        self._rng = np.random.default_rng(seed)
        # Mesh acquisition goes through the job runtime when a job scope
        # is active: the engine serves on its job's leased submesh slice
        # and its decode/prefill programs land in the pool-owned cache.
        self._job = _current_job()
        self._serial = next(_ENGINE_SERIALS)
        if self._job is not None:
            self.strategy = model.strategy or self._job.strategy
        else:
            self.strategy = model.strategy or get_strategy()

        variables = model.variables
        params = (variables["params"] if variables is not None
                  else model.init(seed)["params"])
        # Same mesh placement training uses; on the default single-device
        # strategy this is a no-op device_put.
        self.params = self.strategy.replicate(params)
        self.paged = bool(paged)
        self.page_size = int(page_size)
        self.ragged = bool(ragged)
        if self.ragged and not self.paged:
            raise ValueError(
                "serve: ragged decode rides the page tables (one full-"
                "capacity program, per-slot masking) — pass paged=True")
        if kv_dtype is not None:
            if not self.paged:
                raise ValueError(
                    "serve: kv_dtype is a paged-pool knob — pass "
                    "paged=True (the contiguous cache keeps cache_dtype)")
            aliases = {"fp32": jnp.float32, "bf16": jnp.bfloat16}
            resolved = (aliases.get(kv_dtype, kv_dtype)
                        if isinstance(kv_dtype, str) else kv_dtype)
            dt = jnp.dtype(resolved)
            if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                          jnp.dtype(jnp.int8)):
                raise ValueError(
                    f"serve: kv_dtype must be one of fp32/bf16/int8, "
                    f"got {kv_dtype!r}")
            cache_dtype = resolved
        self._kv_quant = (self.paged
                          and jnp.dtype(cache_dtype) == jnp.int8)
        if self.paged:
            max_pages = -(-self.max_len // self.page_size)
            if num_pages is None and budget_bytes is not None:
                num_pages = kv_cache.pages_for_budget(
                    self.plan, page_size=self.page_size,
                    budget_bytes=budget_bytes, dtype=cache_dtype)
                if num_pages < 1:
                    raise ValueError(
                        f"serve: budget_bytes={budget_bytes} does not fit "
                        "even one page (plus scratch) at page_size="
                        f"{self.page_size}")
            if num_pages is None:
                num_pages = self.max_batch * max_pages
            self.num_pages = int(num_pages)
            self.cache = self.strategy.replicate(kv_cache.init_page_pool(
                self.plan, num_pages=self.num_pages,
                page_size=self.page_size, dtype=cache_dtype,
                budget_bytes=budget_bytes))
            # Per-position pool bytes, derived from the page layout so
            # int8's fp32 scale rows are priced in (for float dtypes this
            # is exactly 2 * L * H * dk * itemsize).
            per_token = kv_cache.page_nbytes(
                self.plan, page_size=self.page_size,
                dtype=cache_dtype) // self.page_size
            self._paging = paging.PagedKVState(
                num_pages=self.num_pages, page_size=self.page_size,
                slots=self.max_batch, max_pages=max_pages,
                bytes_per_token=per_token, prefix_caching=prefix_caching)
            logger.info(
                "serve: paged — %d slots, %d pages x %d positions "
                "(+scratch), pool %.1f MiB (%s), prefix caching %s, "
                "decode %s",
                self.max_batch, self.num_pages, self.page_size,
                kv_cache.page_pool_nbytes(
                    self.plan, num_pages=self.num_pages,
                    page_size=self.page_size, dtype=cache_dtype) / 2**20,
                jnp.dtype(cache_dtype).name,
                "on" if prefix_caching else "off",
                "ragged" if self.ragged else
                f"buckets {buckets or 'pow2'}")
        else:
            self._paging = None
            self.cache = self.strategy.replicate(kv_cache.init_cache(
                self.plan, max_batch=self.max_batch, max_len=self.max_len,
                dtype=cache_dtype, budget_bytes=budget_bytes))
            logger.info(
                "serve: %d slots x %d positions, KV cache %.1f MiB, "
                "buckets %s", self.max_batch, self.max_len,
                kv_cache.cache_nbytes(self.plan, max_batch=self.max_batch,
                                      max_len=self.max_len,
                                      dtype=cache_dtype) / 2**20,
                buckets or "pow2")

        self.scheduler = Scheduler(self.max_batch, buckets=buckets,
                                   policy=policy, max_queue=max_queue)
        # Host mirrors of per-slot decode state (compacted with the
        # scheduler's slot moves).
        self._tokens = np.zeros(self.max_batch, np.int32)
        self._lengths = np.zeros(self.max_batch, np.int32)
        self.finished: list[Request] = []

        # CPU XLA has no buffer donation — donating there only logs
        # warnings; on TPU the cache updates in place (no per-step copy).
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._decode_fns: dict[int, callable] = {}
        self._prefill_fns: dict[int, callable] = {}
        self._donate = donate
        self._swap_fn = jax.jit(kv_cache.swap_slots,
                                donate_argnums=(0,) if donate else ())
        self._paged_decode_fns: dict[int, callable] = {}
        self._paged_prefill_fns: dict[int, callable] = {}
        #: Contiguous chunked-prefill programs, one per pow2 chunk pad.
        #: (The paged chunked path reuses _paged_prefill_fns — the paged
        #: prefill kernel already takes a traced window start.)
        self._chunk_fns: dict[int, callable] = {}
        self._copy_fn = jax.jit(kv_cache.copy_page,
                                donate_argnums=(0,) if donate else ())

        # -- resilience state --------------------------------------------
        self.max_ttft_s = None if max_ttft_s is None else float(max_ttft_s)
        self.retry_budget = int(retry_budget)
        self.stall_timeout_s = (None if stall_timeout_s is None
                                else float(stall_timeout_s))
        self.stall_action = stall_action or _default_stall_action
        self.fault_injector = fault_injector
        self.virtual_step_s = float(virtual_step_s)
        self._step_ema_s: Optional[float] = None
        self._done_count = 0
        self._closed = False
        self.last_replay: Optional[dict] = None
        self.known_rids: set = set()
        if journal is None:
            self.journal: Optional[journal_lib.RequestJournal] = None
        elif isinstance(journal, journal_lib.RequestJournal):
            self.journal = journal
        else:
            # Directory path: the rotation threshold rides in from the
            # environment (the supervised-worker configuration channel).
            self.journal = journal_lib.RequestJournal(
                journal, max_bytes=journal_lib.journal_max_bytes_from_env())
        if self.journal is not None:
            self._recover_from_journal()
        metrics.set_gauge("serve.ready", 1.0)

    # -- crash recovery -------------------------------------------------------

    def _recover_from_journal(self) -> None:
        """Replay an existing journal into the scheduler: formerly active
        requests first (arrival order, re-prefilled with their journaled
        tokens for a token-identical greedy continuation), then the queued
        ones; requests whose journaled tokens already satisfy their stop
        condition finish here; actives past the retry budget are shed."""
        t0 = time.monotonic()
        state = journal_lib.load(self.journal.path)
        self.known_rids = state.known_rids
        # Seed rid allocation from the full rid space — including rids a
        # rotation compacted away, which have no request record left to
        # bump the counter below. A fresh submit must never reuse one.
        self.scheduler._next_rid = max(self.scheduler._next_rid,
                                       state.next_rid)
        if not state.requests:
            return
        active, queued = state.pending()
        completed, replayed, shed = [], [], []
        for jr in active + queued:
            req = Request(prompt=list(jr.prompt),
                          max_new_tokens=jr.max_new_tokens,
                          eos_id=jr.eos_id, deadline_s=jr.deadline_s,
                          generated=list(jr.tokens), replays=jr.replays)
            if jr.stop_satisfied():
                # The work survived the crash; only its terminal record
                # was lost. Finish it now, never re-admit.
                req.rid = jr.rid
                req.status = DONE
                req.finish_reason = jr.implied_finish_reason()
                self.scheduler._next_rid = max(self.scheduler._next_rid,
                                               jr.rid + 1)
                self.finished.append(req)
                self.journal.record_finish(req)
                self._done_count += 1
                metrics.inc("serve.requests.completed")
                completed.append(jr.rid)
                continue
            if jr.tokens and jr.replays + 1 > self.retry_budget:
                req.rid = jr.rid
                self.scheduler._next_rid = max(self.scheduler._next_rid,
                                               jr.rid + 1)
                self._shed(req, "retry_budget", journaled=True)
                shed.append(jr.rid)
                continue
            # Deadlines re-arm relative to re-submission: the original
            # submit wall-clock is from a dead process.
            self.scheduler.submit(req, now=self.clock(), rid=jr.rid)
            replayed.append(jr.rid)
        replay_s = time.monotonic() - t0
        attempt = len(state.replay_markers) + 1
        self.last_replay = {
            "attempt": attempt,
            "active": [r.rid for r in active],
            "queued": [r.rid for r in queued],
            "replayed": replayed, "completed": completed, "shed": shed,
            "replay_s": replay_s,
        }
        self.journal.record_replay(
            attempt=attempt, queued=[r.rid for r in queued],
            active=[r.rid for r in active], completed=completed,
            replay_s=replay_s)
        metrics.observe_value("serve.journal.replay_s", replay_s)
        from tpu_dist.resilience import events
        events.maybe_log("serve_replay", attempt=attempt,
                         replayed=len(replayed), completed=len(completed),
                         shed=len(shed), replay_s=round(replay_s, 6))
        logger.info(
            "serve: journal replay #%d — %d re-admitted (%d were active), "
            "%d finished from journaled tokens, %d shed, %.3fs",
            attempt, len(replayed), len(active), len(completed),
            len(shed), replay_s)

    @classmethod
    def from_saved(cls, directory, **kwargs) -> "ServeEngine":
        """Load a ``save_model`` directory (weights restored, no training
        compile) and serve it."""
        from tpu_dist.models import serialize

        model = serialize.load_model(directory, compile=False)
        return cls(model, **kwargs)

    # -- compiled-program cache ----------------------------------------------

    def _acquire_program(self, kind: str, key, builder):
        """Build — or acquire — one compiled program. Solo engines build
        directly (the exact pre-jobs path); under an active job scope the
        program lives in the pool's MeshRuntime cache, keyed by job,
        model, and engine generation."""
        if self._job is None:
            return builder()
        return self._job.runtime.cached(
            self._job.program_key(self.model.name, self._serial, kind, key),
            builder)

    def _decode_fn(self, bucket: int):
        fn = self._decode_fns.get(bucket)
        if fn is None:
            fn = self._acquire_program(
                "decode", bucket,
                lambda: jax.jit(
                    functools.partial(kv_cache.decode_step, self.plan,
                                      bucket=bucket),
                    donate_argnums=self._donate))
            self._decode_fns[bucket] = fn
        return fn

    def _prefill_fn(self, pad_len: int):
        fn = self._prefill_fns.get(pad_len)
        if fn is None:
            fn = self._acquire_program(
                "prefill", pad_len,
                lambda: jax.jit(
                    functools.partial(kv_cache.prefill, self.plan),
                    donate_argnums=self._donate))
            self._prefill_fns[pad_len] = fn
        return fn

    def _paged_decode_fn(self, bucket: int):
        fn = self._paged_decode_fns.get(bucket)
        if fn is None:
            if self.ragged:
                # One full-capacity program; ``bucket`` is always
                # max_batch here, kept as the cache key so
                # compiled_programs() reports the surface uniformly.
                fn = self._acquire_program(
                    "paged_decode_ragged", bucket,
                    lambda: jax.jit(
                        functools.partial(kv_cache.paged_decode_ragged,
                                          self.plan),
                        donate_argnums=self._donate))
            else:
                fn = self._acquire_program(
                    "paged_decode", bucket,
                    lambda: jax.jit(
                        functools.partial(kv_cache.paged_decode_step,
                                          self.plan, bucket=bucket),
                        donate_argnums=self._donate))
            self._paged_decode_fns[bucket] = fn
        return fn

    def _paged_prefill_fn(self, pad_len: int):
        fn = self._paged_prefill_fns.get(pad_len)
        if fn is None:
            fn = self._acquire_program(
                "paged_prefill", pad_len,
                lambda: jax.jit(
                    functools.partial(kv_cache.paged_prefill, self.plan),
                    donate_argnums=self._donate))
            self._paged_prefill_fns[pad_len] = fn
        return fn

    def _chunk_fn(self, pad_len: int):
        fn = self._chunk_fns.get(pad_len)
        if fn is None:
            fn = self._acquire_program(
                "prefill_chunk", pad_len,
                lambda: jax.jit(
                    functools.partial(kv_cache.prefill_chunk_step,
                                      self.plan),
                    donate_argnums=self._donate))
            self._chunk_fns[pad_len] = fn
        return fn

    def compiled_programs(self) -> dict:
        """{'decode': [buckets...], 'prefill': [pad_lens...]} — tests pin
        the no-retrace property on this. Paged engines report their
        ``paged_decode``/``paged_prefill`` surfaces too (a suffix prefill
        after a prefix hit pads to a smaller power of two, so warm and
        cold prefills land in different — but both steady — programs).
        Contiguous chunked engines add ``prefill_chunk``: one program per
        pow2 chunk pad (paged chunked engines run chunks through the
        ``paged_prefill`` surface — same traced-start programs). The
        default ``prefill_chunk=0`` leaves the dict bit-unchanged. Ragged
        paged engines report ``paged_decode == [max_batch]`` — exactly
        one full-capacity decode program, ever (tests pin it)."""
        out = {"decode": sorted(self._decode_fns),
               "prefill": sorted(self._prefill_fns)}
        if self.paged:
            out["paged_decode"] = sorted(self._paged_decode_fns)
            out["paged_prefill"] = sorted(self._paged_prefill_fns)
        if self.prefill_chunk and not self.paged:
            out["prefill_chunk"] = sorted(self._chunk_fns)
        return out

    # -- request intake -------------------------------------------------------

    def submit(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        prompt = [int(t) for t in prompt]
        if len(prompt) > self.max_len - 1:
            raise ValueError(
                f"prompt of {len(prompt)} tokens does not fit a "
                f"{self.max_len}-position cache slot (need >= 1 free)")
        if self.paged:
            # Reject a request that could never fit even an empty pool
            # now, loudly, instead of deadlocking admission later.
            self._paging.check_fits(
                min(len(prompt) + int(max_new_tokens), self.max_len))
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_id=eos_id, deadline_s=deadline_s)
        cause = self._shed_cause(req)
        if cause is not None:
            return self._shed(req, cause)
        self.scheduler.submit(req, now=self.clock())
        metrics.inc("serve.requests.submitted")
        if self.journal is not None:
            self.journal.record_submit(req)
        return req

    def adopt_request(self, prompt: Sequence[int], *,
                      generated: Sequence[int] = (),
                      max_new_tokens: int = 32,
                      eos_id: Optional[int] = None,
                      deadline_s: Optional[float] = None,
                      replays: int = 0) -> Request:
        """Adopt another engine's in-flight request (fleet failover).

        The caller — the fleet router, replaying a dead replica's journal
        onto a survivor — hands over the prompt plus every token the dead
        replica already emitted. The adopted request gets a FRESH rid from
        THIS engine's :meth:`Scheduler.reserve_rid` (two replicas' rid
        spaces overlap by construction, so the donor rid must never be
        pinned here), its full submit+token trail is re-journaled so a
        later crash of the survivor replays it like native work, and the
        greedy continuation re-prefills ``prompt + generated`` — token-
        identical to an uninterrupted run, same as solo journal recovery.

        Mirrors ``_recover_from_journal``'s edge handling: journaled
        tokens already satisfying the stop condition finish here without
        a slot; a request seen ACTIVE in more than ``retry_budget``
        crashes is shed (cause ``retry_budget``) instead of re-admitted.
        """
        prompt = [int(t) for t in prompt]
        if len(prompt) > self.max_len - 1:
            raise ValueError(
                f"prompt of {len(prompt)} tokens does not fit a "
                f"{self.max_len}-position cache slot (need >= 1 free)")
        if self.paged:
            self._paging.check_fits(
                min(len(prompt) + int(max_new_tokens), self.max_len))
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_id=eos_id, deadline_s=deadline_s,
                      generated=[int(t) for t in generated],
                      replays=int(replays))
        req.rid = self.scheduler.reserve_rid()
        if self.journal is not None:
            self.journal.record_submit(req)
            for t in req.generated:
                self.journal.record_token(req.rid, t)
        hit_eos = req.eos_id is not None and req.eos_id in req.generated
        if hit_eos or len(req.generated) >= req.max_new_tokens:
            # The donor's work was complete; only its terminal record
            # died with it. Finish without ever taking a slot.
            now = self.clock()
            req.status = DONE
            req.finish_reason = "eos" if hit_eos else "length"
            req.submit_s = now
            req.finish_s = now
            self.finished.append(req)
            if self.journal is not None:
                self.journal.record_finish(req)
            self._done_count += 1
            metrics.inc("serve.requests.completed")
            return req
        if req.generated and req.replays + 1 > self.retry_budget:
            return self._shed(req, "retry_budget", journaled=True)
        self.scheduler.submit(req, now=self.clock(), rid=req.rid)
        metrics.inc("serve.requests.adopted")
        return req

    # -- overload protection --------------------------------------------------

    def _projected_ttft_s(self) -> float:
        """Conservative time-to-first-token estimate for a request joining
        the queue now: every token owed by work ahead of it (active
        remainders + whole queued requests), spread over ``max_batch``
        lanes, at the EMA decode-step time. 0.0 until the first decode
        step has been measured."""
        if self._step_ema_s is None:
            return 0.0
        owed = sum(max(r.max_new_tokens - len(r.generated), 0)
                   for r in self.scheduler.active())
        owed += sum(r.max_new_tokens for r in self.scheduler.queue)
        return (owed / self.max_batch) * self._step_ema_s

    def _shed_cause(self, req: Request) -> Optional[str]:
        """Admission control, cheapest check first: queue bound, then
        deadline feasibility (could this request meet its deadline even if
        admitted immediately?), then projected TTFT."""
        if self.scheduler.full():
            return "queue_full"
        projected = self._projected_ttft_s()
        if req.deadline_s is not None and self._step_ema_s is not None:
            need = projected + req.max_new_tokens * self._step_ema_s
            if need > req.deadline_s:
                return "deadline_unmeetable"
        if self.max_ttft_s is not None and projected > self.max_ttft_s:
            return "projected_ttft"
        return None

    def _shed(self, req: Request, cause: str, *,
              journaled: bool = False) -> Request:
        """Reject ``req`` at admission: terminal SHED state, never a slot.
        Journaled (submit + finish) so a post-crash replay does not
        resurrect it — shed is an answer, not a loss."""
        if req.rid < 0:
            req.rid = self.scheduler.reserve_rid()
        req.status = SHED
        req.finish_reason = "shed"
        req.shed_cause = cause
        now = self.clock()
        req.submit_s = req.submit_s or now
        req.finish_s = now
        self.finished.append(req)
        metrics.inc("serve.requests.shed")
        if self.journal is not None:
            if not journaled:
                self.journal.record_submit(req)
            self.journal.record_finish(req)
        logger.info("serve: shed request %d (%s)", req.rid, cause)
        return req

    # -- sampling (host-side) -------------------------------------------------

    def _pick(self, logits: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / self.temperature
        z -= z.max()
        p = np.exp(z)
        return int(self._rng.choice(logits.shape[-1], p=p / p.sum()))

    # -- the serving loop -----------------------------------------------------

    def _apply_swap(self, swap: Optional[tuple[int, int]]) -> None:
        if swap is None:
            return
        i, j = swap
        if self.paged:
            # Compaction under paging is a host page-table pointer swap —
            # no device program runs.
            self._paging.swap_slots(i, j)
        else:
            self.cache = self._swap_fn(self.cache, jnp.int32(i),
                                       jnp.int32(j))
        self._tokens[[i, j]] = self._tokens[[j, i]]
        self._lengths[[i, j]] = self._lengths[[j, i]]

    def _release_pages(self, req: Request) -> None:
        """Paged reclaim for a request that just left its slot: index its
        prompt's tail chunk for future prefix hits, then drop the slot's
        page references (compaction-free — freed pages go straight back
        on the free list). Must run BEFORE the mirrored slot swap, while
        the allocator row still belongs to this request."""
        if self.paged and req.released_slot is not None:
            # Bound prefix registration to positions actually written: a
            # request evicted mid-chunked-prefill holds allocated pages
            # past its cursor whose K/V are garbage.
            upto = min(req.prefill_pos, len(req.prompt))
            self._paging.finish(req.released_slot, req.prompt, upto=upto)
            req.released_slot = None

    def _retire(self, req: Request, *, now: float, status: str) -> None:
        swap = self.scheduler.finish(req, now=now, status=status)
        self._release_pages(req)
        self._apply_swap(swap)
        self.finished.append(req)
        if self.journal is not None:
            self.journal.record_finish(req)
        if status == DONE:
            self._done_count += 1
            metrics.inc("serve.requests.completed")
            if req.latency_s is not None:
                metrics.observe_value("serve.request.latency_s",
                                      req.latency_s)
            if req.ttft_s is not None:
                metrics.observe_value("serve.request.ttft_s", req.ttft_s)
        else:
            metrics.inc("serve.requests.evicted")

    def _total_tokens(self, req: Request) -> int:
        """Worst-case positions this request can occupy — the paged
        admission/reservation unit."""
        return min(len(req.prompt) + len(req.generated)
                   + max(req.max_new_tokens - len(req.generated), 0),
                   self.max_len)

    def _admission_gate(self, req: Request) -> bool:
        """Paged admission: a slot is only half the question — the pool
        must also hold this request's worst case. Reserving up front
        keeps every later incremental allocation (decode appends, COW
        clones) deadlock-free."""
        return self._paging.try_admit(self._total_tokens(req))

    def _unpack_prefill(self, out):
        """Unpack a paged-prefill result: int8 pools return a third
        element — the call's max-abs dequantization error — observed
        host-side into the ``serve.kv.quant_error`` distribution (the
        readback happens after the traced program, so shardcheck's
        SC103 host-callback scan stays clean)."""
        if self._kv_quant:
            self.cache, logits, qerr = out
            metrics.observe_value("serve.kv.quant_error", float(qerr))
        else:
            self.cache, logits = out
        return logits

    def _prefill(self, req: Request) -> None:
        # A journal-recovered request re-prefills with prompt + everything
        # it had already generated: the incremental-decode ≡ full-forward
        # equivalence makes the greedy continuation token-identical to an
        # uninterrupted run (req.generated is empty on the normal path).
        # Under chunked prefill, the same holds because recovery re-admits
        # through THIS dispatch: the replayed sequence re-prefills through
        # the identical chunked path.
        if self.prefill_chunk:
            self._begin_chunked_prefill(req)
            return
        seq = list(req.prompt) + list(req.generated)
        plen = len(seq)
        if self.paged:
            setup = self._paging.begin(req.slot, seq,
                                       self._total_tokens(req))
            for src, dst in setup.copies:
                self.cache = self._copy_fn(self.cache, jnp.int32(src),
                                           jnp.int32(dst))
            suffix = plen - setup.start
            pad = _pad_to_pow2(suffix, hi=self.max_len)
            tokens = np.zeros(pad, np.int32)
            tokens[:suffix] = seq[setup.start:]
            fn = self._paged_prefill_fn(pad)
            row = self._paging.allocator.table[req.slot]
            out = fn(self.params, self.cache,
                     jnp.asarray(row), jnp.asarray(tokens),
                     jnp.int32(plen), jnp.int32(setup.start))
            logits = self._unpack_prefill(out)
            self._paging.register_prefill(req.slot, req.prompt)
        else:
            pad = _pad_to_pow2(plen, hi=self.max_len)
            tokens = np.zeros(pad, np.int32)
            tokens[:plen] = seq
            fn = self._prefill_fn(pad)
            self.cache, logits = fn(self.params, self.cache,
                                    jnp.asarray(tokens), jnp.int32(plen),
                                    jnp.int32(req.slot))
        req.prefill_pos = plen
        metrics.inc("serve.prefills")
        # Materialize BEFORE stamping first-token time: jax dispatch is
        # async, so the pre-readback clock() under-reported TTFT against
        # any client-observed wall clock (the PR 12 wart).
        token = self._pick(np.asarray(logits))
        now = self.clock()
        done = self.scheduler.record_token(req, token, now=now)
        metrics.inc("serve.tokens.generated")
        if self.journal is not None:
            self.journal.record_token(req.rid, token)
        self._tokens[req.slot] = token
        self._lengths[req.slot] = plen
        if done or plen >= self.max_len:
            self._retire(req, now=now, status=DONE)

    def _begin_chunked_prefill(self, req: Request) -> None:
        """Admission under ``prefill_chunk > 0``: set up the slot (page
        table + prefix-cache attach in paged mode — allocation is
        chunk-granular from here on) and put the request on the chunk
        queue. No forward pass runs yet; :meth:`step` drains chunks
        interleaved with decode."""
        seq = list(req.prompt) + list(req.generated)
        if self.paged:
            setup = self._paging.begin(req.slot, seq,
                                       self._total_tokens(req),
                                       chunk=self.prefill_chunk)
            for src, dst in setup.copies:
                self.cache = self._copy_fn(self.cache, jnp.int32(src),
                                           jnp.int32(dst))
            req.prefill_pos = setup.start
        else:
            req.prefill_pos = 0
        # Mirror the cursor: a mid-prefill slot rides inside the decode
        # bucket, so decode scatters one garbage K/V write at exactly
        # lengths[slot] — the next unwritten position, which the next
        # chunk (or, on the final chunk's completion, a real append)
        # overwrites before any validity mask admits it.
        self._tokens[req.slot] = 0
        self._lengths[req.slot] = req.prefill_pos
        self.scheduler.enqueue_prefill(req)

    def _prefill_chunk_one(self, req: Request) -> None:
        """Run ONE chunk of ``req``'s prefill: positions
        ``[prefill_pos, min(prefill_pos + prefill_chunk, plen))``. The
        final chunk yields the last valid position's logits — the first
        generated token — and moves the request into the decode set."""
        seq = list(req.prompt) + list(req.generated)
        plen = len(seq)
        startpos = req.prefill_pos
        end = min(startpos + self.prefill_chunk, plen)
        valid = end - startpos
        pad = _pad_to_pow2(valid, hi=self.prefill_chunk)
        tokens = np.zeros(pad, np.int32)
        tokens[:valid] = seq[startpos:end]
        if self.paged:
            self._paging.extend_prefill(req.slot, end)
            fn = self._paged_prefill_fn(pad)
            row = self._paging.allocator.table[req.slot]
            out = fn(self.params, self.cache,
                     jnp.asarray(row), jnp.asarray(tokens),
                     jnp.int32(end), jnp.int32(startpos))
            logits = self._unpack_prefill(out)
        else:
            fn = self._chunk_fn(pad)
            self.cache, logits = fn(self.params, self.cache,
                                    jnp.asarray(tokens), jnp.int32(end),
                                    jnp.int32(req.slot),
                                    jnp.int32(startpos))
        req.prefill_pos = end
        self._lengths[req.slot] = end
        metrics.inc("serve.prefill.chunks")
        if end < plen:
            return  # more chunks owed; logits of a mid-chunk are unused
        self.scheduler.dequeue_prefill(req)
        if self.paged:
            self._paging.register_prefill(req.slot, req.prompt)
        metrics.inc("serve.prefills")
        token = self._pick(np.asarray(logits))  # readback, then stamp
        now = self.clock()
        done = self.scheduler.record_token(req, token, now=now)
        metrics.inc("serve.tokens.generated")
        if self.journal is not None:
            self.journal.record_token(req.rid, token)
        self._tokens[req.slot] = token
        if done or plen >= self.max_len:
            self._retire(req, now=now, status=DONE)

    def step(self) -> int:
        """One scheduling round: deadline evictions → admissions (each
        pays its prefill and emits its first token) → one decode step for
        the active bucket. Returns the number of still-active requests.

        Durability contract: everything journaled this round (submits,
        tokens, finishes) is flushed — one append + fsync — at the END of
        the round, after the fault-injector seams, so an injected crash
        loses the unflushed tail and recovery must regenerate it (the
        harsher ordering for the parity gate)."""
        now = self.clock()
        for req, swap in self.scheduler.evict_deadline(now=now):
            self._release_pages(req)
            self._apply_swap(swap)
            self.finished.append(req)
            metrics.inc("serve.requests.evicted")
            if self.journal is not None:
                self.journal.record_finish(req)

        gate = self._admission_gate if self.paged else None
        for req in self.scheduler.admit(gate=gate):
            self._prefill(req)
        metrics.set_gauge("serve.queue.depth", self.scheduler.queue_depth())

        if self.prefill_chunk:
            # Interleave policy: at most ``prefill_interleave`` prefill
            # chunks between consecutive decode steps, drained
            # arrival-ordered from the head of the chunk queue.
            for _ in range(self.prefill_interleave):
                head = self.scheduler.peek_prefill()
                if head is None:
                    break
                self._prefill_chunk_one(head)

        n = self.scheduler.num_active
        if self.paged:
            self._paging.note_usage()
        if n == 0:
            if self.journal is not None:
                self.journal.flush()
            return 0
        # Decode covers only fully-prefilled slots; a mid-chunk slot's
        # cursor excludes it until its last chunk lands (ready() is all
        # of active() when chunking is off).
        ready = self.scheduler.ready()
        if not ready:
            if self.journal is not None:
                self.journal.flush()
            return n
        # Ragged mode decodes the whole slot capacity in one program —
        # the scheduler's pow2 bucket is never consulted, so occupancy
        # is measured against true capacity.
        bucket = (self.max_batch if self.paged and self.ragged
                  else self.scheduler.bucket())
        metrics.observe_value("serve.batch.occupancy", len(ready) / bucket)
        if self.paged:
            # Host-side page bookkeeping for this round's appends: cross
            # a page boundary -> allocate the next page (covered by the
            # admission reservation); tail page shared with the prefix
            # cache -> copy-on-write it private before the scatter.
            for req in ready:
                for src, dst in self._paging.prepare_append(
                        req.slot, int(self._lengths[req.slot])):
                    self.cache = self._copy_fn(self.cache, jnp.int32(src),
                                               jnp.int32(dst))
        t0 = self.clock()
        timer = None
        if self.stall_timeout_s is not None:
            info = {"timeout_s": self.stall_timeout_s, "bucket": bucket,
                    "active": n}
            timer = threading.Timer(self.stall_timeout_s,
                                    self.stall_action, args=(info,))
            timer.daemon = True
            timer.start()
        try:
            if self.paged and self.ragged:
                # Per-slot active mask: only fully-prefilled decoding
                # slots write to their real tail pages — empty slots AND
                # slots mid-chunked-prefill (whose table rows hold real
                # pages a stray decode write must not touch) route their
                # garbage write to the scratch page inside the kernel.
                active = np.zeros(self.max_batch, bool)
                for req in ready:
                    active[req.slot] = True
                self.cache, logits = self._paged_decode_fn(bucket)(
                    self.params, self.cache,
                    jnp.asarray(self._paging.allocator.table),
                    jnp.asarray(self._tokens), jnp.asarray(self._lengths),
                    jnp.asarray(active))
            elif self.paged:
                self.cache, logits = self._paged_decode_fn(bucket)(
                    self.params, self.cache,
                    jnp.asarray(self._paging.allocator.table),
                    jnp.asarray(self._tokens), jnp.asarray(self._lengths))
            else:
                self.cache, logits = self._decode_fn(bucket)(
                    self.params, self.cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._lengths))
            if self.fault_injector is not None:
                # Inside the watchdog window on purpose: a decode_stall
                # fault must look exactly like a hung runtime call.
                self.fault_injector.on_decode()
            logits = np.asarray(logits)  # blocks until the device is done
        finally:
            if timer is not None:
                timer.cancel()
        metrics.inc("serve.decode.steps")
        if self.virtual_step_s > 0.0 and hasattr(self.clock, "advance"):
            self.clock.advance(self.virtual_step_s)
        dt = self.clock() - t0
        if dt > 0.0:
            self._step_ema_s = (dt if self._step_ema_s is None else
                                _EMA_ALPHA * dt
                                + (1.0 - _EMA_ALPHA) * self._step_ema_s)
        now = self.clock()
        completed = []
        for req in ready:
            token = self._pick(logits[req.slot])
            self._lengths[req.slot] += 1
            self._tokens[req.slot] = token
            done = self.scheduler.record_token(req, token, now=now)
            metrics.inc("serve.tokens.generated")
            if self.journal is not None:
                self.journal.record_token(req.rid, token)
            if done or self._lengths[req.slot] >= self.max_len:
                completed.append(req)
        # Highest slot first: each swap moves the (untouched) last slot.
        for req in sorted(completed, key=lambda r: r.slot, reverse=True):
            self._retire(req, now=now, status=DONE)
        if self.fault_injector is not None:
            self.fault_injector.on_step_end(self._done_count)
        if self.journal is not None:
            self.journal.flush()
        return self.scheduler.num_active

    def run_until_idle(self, *, max_steps: int = 100_000) -> list[Request]:
        """Drive :meth:`step` until queue and batch drain; returns all
        requests finished so far (done + evicted, completion order)."""
        steps = 0
        while not self.scheduler.idle():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serve loop still busy after {max_steps} steps "
                    f"({self.scheduler.num_active} active, "
                    f"{self.scheduler.queue_depth()} queued)")
        return self.finished

    def generate(self, prompt: Sequence[int], *, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None) -> list[int]:
        """Single-request convenience: submit, drain, return the tokens."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          eos_id=eos_id)
        self.run_until_idle()
        return req.generated

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        """Flush + close the journal and drop readiness. Idempotent; a
        crash skips it by definition — that is what recovery is for."""
        if self._closed:
            return
        self._closed = True
        metrics.set_gauge("serve.ready", 0.0)
        if self.journal is not None:
            self.journal.close()

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
