"""tpu_dist.serve — continuous-batching inference on the training mesh.

The serving counterpart to ``tpu_dist.training``: a ``ServeEngine``
compiles one decode program per padded batch bucket (plus per-padded-
length prefill programs) over a preallocated KV cache, and a slot-based
scheduler admits/evicts requests *between* decode steps. Latency SLO
metrics flow through ``tpu_dist.observe``; the prefill/decode programs
are shardcheck entry points with cost baselines. ``python -m
tpu_dist.serve --bench`` runs the seeded load generator.

Serving resilience (README "Serving resilience"): a durable
``RequestJournal`` makes a ``ServeSupervisor``-restarted engine replay
queued and in-flight requests with token-identical greedy continuations;
bounded-queue/projected-TTFT shedding and a decode-stall watchdog keep
overload and hangs from taking the engine down silently. ``python -m
tpu_dist.serve --chaos`` runs the gated serve chaos suite.
"""

from tpu_dist.serve.engine import ServeEngine
from tpu_dist.serve.journal import JournalState, RequestJournal
from tpu_dist.serve.kv_cache import (DecodePlan, build_plan, decode_step,
                                     init_cache, prefill)
from tpu_dist.serve.scheduler import (DONE, EVICTED, SHED, Request,
                                      Scheduler, default_buckets)
from tpu_dist.serve.supervisor import ServeSupervisor

__all__ = [
    "ServeEngine", "DecodePlan", "build_plan", "decode_step", "init_cache",
    "prefill", "Request", "Scheduler", "default_buckets",
    "RequestJournal", "JournalState", "ServeSupervisor",
    "DONE", "EVICTED", "SHED",
]
