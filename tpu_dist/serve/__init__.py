"""tpu_dist.serve — continuous-batching inference on the training mesh.

The serving counterpart to ``tpu_dist.training``: a ``ServeEngine``
compiles one decode program per padded batch bucket (plus per-padded-
length prefill programs) over a preallocated KV cache, and a slot-based
scheduler admits/evicts requests *between* decode steps. Latency SLO
metrics flow through ``tpu_dist.observe``; the prefill/decode programs
are shardcheck entry points with cost baselines. ``python -m
tpu_dist.serve --bench`` runs the seeded load generator.
"""

from tpu_dist.serve.engine import ServeEngine
from tpu_dist.serve.kv_cache import (DecodePlan, build_plan, decode_step,
                                     init_cache, prefill)
from tpu_dist.serve.scheduler import Request, Scheduler, default_buckets

__all__ = [
    "ServeEngine", "DecodePlan", "build_plan", "decode_step", "init_cache",
    "prefill", "Request", "Scheduler", "default_buckets",
]
