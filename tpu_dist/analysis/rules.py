"""shardcheck rule catalogue, findings, and suppression handling.

The rule IDs are the stable public contract: tests assert on them, JSON
output carries them, and inline suppressions name them
(``# shardcheck: disable=SC101``). Message text is free to evolve.

Severity model: ``error`` findings are bugs-in-waiting (the CLI exits
non-zero on them and ``scripts/check.sh`` fails the gate); ``warning`` is
suspicious-but-possibly-intended; ``info`` is diagnostics (e.g. an entry
point the jaxpr pass could not trace).
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Optional


class Severity(enum.IntEnum):
    """Ordered so comparisons read naturally: ERROR > WARNING > INFO."""

    INFO = 10
    WARNING = 20
    ERROR = 30

    def __str__(self) -> str:  # JSON/text rendering
        return self.name.lower()

    @classmethod
    def parse(cls, value: "Severity | str") -> "Severity":
        if isinstance(value, cls):
            return value
        try:
            return cls[str(value).upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {value!r}; valid: "
                f"{[s.name.lower() for s in cls]}") from None


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: Severity
    description: str


#: The advertised catalogue. SC1xx are AST rules (ast_lint.py); SC2xx are
#: jaxpr-level rules (jaxpr_checks.py).
RULES = {r.id: r for r in (
    Rule(
        "SC101", "unknown-collective-axis", Severity.ERROR,
        "A collective (psum/pmean/all_gather/ppermute/...) names a mesh "
        "axis that is neither canonical (tpu_dist.parallel.axes) nor "
        "declared anywhere in the file (mesh/axis_shapes literal, *_AXIS "
        "constant, axis_name= parameter default). A wrong axis name "
        "raises at trace time at best and silently reduces over the "
        "wrong group at worst."),
    Rule(
        "SC102", "partitionspec-rank-mismatch", Severity.ERROR,
        "A PartitionSpec used to place an array has more entries than "
        "the array has dimensions. XLA rejects the placement at run "
        "time; catching it statically saves the trace/compile cycle."),
    Rule(
        "SC103", "host-side-effect-in-jit", Severity.ERROR,
        "A host side effect (print, time.time, stdlib random, input) "
        "inside a jitted function. These run once at trace time, not "
        "per step — prints go silent, clocks freeze, and Python "
        "randomness is baked into the compiled program as a constant."),
    Rule(
        "SC104", "donated-buffer-reuse", Severity.ERROR,
        "An argument donated via jit(donate_argnums=...) is read after "
        "the donating call. The buffer has been handed to XLA for "
        "aliasing; reusing it raises on real hardware and is "
        "silently-wrong on backends that skip donation."),
    Rule(
        "SC105", "swallowed-liveness-error", Severity.ERROR,
        "A bare `except Exception` (or `except:`) around a call that can "
        "raise PeerUnavailableError (liveness verdicts, barriers, chief "
        "broadcasts, host reductions) swallows the dead-peer signal. A "
        "supervised run recovers from that error by restarting the "
        "worker; a handler that eats it leaves the job half-alive. Catch "
        "PeerUnavailableError explicitly first, or re-raise."),
    Rule(
        "SC201", "collective-order-divergence", Severity.ERROR,
        "Branches of a lax.cond/switch issue different collective "
        "sequences. When the predicate is device-varying (the usual "
        "reason to branch in SPMD code), devices taking different "
        "branches launch mismatched collectives and the program "
        "deadlocks — the bug class TF's runtime ordered away and XLA "
        "will not catch for you."),
    Rule(
        "SC202", "data-dependent-collective-trip-count", Severity.ERROR,
        "A collective inside a lax.while_loop body (or its predicate). "
        "A while trip count is data-dependent by construction — unlike "
        "scan's static length — so ranks whose predicates diverge run "
        "different numbers of collective launches and the mismatched "
        "rendezvous deadlocks. Prove the trip count rank-uniform and "
        "rewrite as a bounded scan, or hoist the collective out."),
    Rule(
        "SC203", "collective-payload-mismatch", Severity.ERROR,
        "Paired collective launches whose payloads cannot line up across "
        "ranks: cond/switch branches issuing the same collective "
        "sequence but with different payload shapes/dtypes, or a "
        "ppermute whose permutation is invalid for the mesh axis in "
        "effect (index out of range, duplicate source, duplicate "
        "destination). Both trace fine and hang or corrupt at the "
        "rendezvous on real hardware."),
    Rule(
        "SC301", "comm-budget-regression", Severity.ERROR,
        "An entry point's total modeled communication volume exceeds "
        "the committed baseline (ANALYSIS_BASELINE.json) by more than "
        "the tolerance. Comm regressions only show up as step-time "
        "cliffs at pod scale; the static diff catches them in CI. "
        "Intended growth: re-run with --update-baseline and commit."),
    Rule(
        "SC302", "peak-hbm-over-budget", Severity.WARNING,
        "An entry point's estimated per-rank peak live-buffer bytes "
        "exceed the baseline's HBM budget. The linear-scan liveness "
        "estimate is an upper bound (rematerialization ignored), so "
        "this is a warning, not an error — but a jump usually means a "
        "batch/width change that will OOM first on the real machine."),
    Rule(
        "SC303", "undonated-dead-argument", Severity.WARNING,
        "A large entry-point argument whose jaxpr liveness proves it "
        "dead after its single use, yet never donated. XLA must keep "
        "the input buffer alive alongside its replacement; "
        "donate_argnums would alias them and halve that footprint. "
        "The jaxpr-proof deepening of SC104's AST guess."),
    Rule(
        "SC900", "entry-point-untraceable", Severity.INFO,
        "A registered jaxpr-check entry point could not be traced in "
        "this environment; its collective-order check was skipped."),
)}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str
    line: int
    col: int
    message: str

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    @property
    def severity(self) -> Severity:
        return self.rule.severity

    def to_json(self) -> dict:
        return {
            "rule_id": self.rule_id,
            "name": self.rule.name,
            "severity": str(self.severity),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule_id} {self.severity}] {self.message}")


#: ``# shardcheck: disable=SC101`` or ``disable=SC101,SC103`` or
#: ``disable=all``; anything after the rule list (a justification) is free
#: text. Matches anywhere in the physical line so it can trail code.
_SUPPRESS_RE = re.compile(
    r"#\s*shardcheck:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s+--?\s|$|#)")


def suppressions_for_line(source_line: str) -> Optional[set]:
    """Rule IDs suppressed on this physical line, or None when no
    suppression comment is present. ``{"all"}`` suppresses every rule."""
    m = _SUPPRESS_RE.search(source_line)
    if not m:
        return None
    ids = {part.strip() for part in m.group(1).split(",") if part.strip()}
    return {i if i.lower() != "all" else "all" for i in ids}


def apply_suppressions(findings, source_by_path) -> list:
    """Drop findings whose source line carries a matching suppression.

    ``source_by_path`` maps path -> list of source lines (1-indexed via
    ``line - 1``). Findings for paths not in the map pass through.
    """
    kept = []
    for f in findings:
        lines = source_by_path.get(f.path)
        if lines is not None and 1 <= f.line <= len(lines):
            sup = suppressions_for_line(lines[f.line - 1])
            if sup is not None and ("all" in sup or f.rule_id in sup):
                continue
        kept.append(f)
    return kept
