"""shardcheck rule catalogue, findings, and suppression handling.

The rule IDs are the stable public contract: tests assert on them, JSON
output carries them, and inline suppressions name them
(``# shardcheck: disable=SC101``). Message text is free to evolve.

Severity model: ``error`` findings are bugs-in-waiting (the CLI exits
non-zero on them and ``scripts/check.sh`` fails the gate); ``warning`` is
suspicious-but-possibly-intended; ``info`` is diagnostics (e.g. an entry
point the jaxpr pass could not trace).
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Optional


class Severity(enum.IntEnum):
    """Ordered so comparisons read naturally: ERROR > WARNING > INFO."""

    INFO = 10
    WARNING = 20
    ERROR = 30

    def __str__(self) -> str:  # JSON/text rendering
        return self.name.lower()

    @classmethod
    def parse(cls, value: "Severity | str") -> "Severity":
        if isinstance(value, cls):
            return value
        try:
            return cls[str(value).upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {value!r}; valid: "
                f"{[s.name.lower() for s in cls]}") from None


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: Severity
    description: str


#: The advertised catalogue. SC1xx are AST rules (ast_lint.py); SC2xx are
#: jaxpr-level rules (jaxpr_checks.py); SC3xx are cost/baseline rules
#: (costmodel.py/baseline.py); SC4xx are host-runtime thread-safety rules
#: and SC5xx liveness/protocol rules (concurrency.py/liveness.py, the
#: ``--concurrency`` mode); SC6xx are determinism/RNG-lineage rules
#: (determinism.py, the ``--determinism`` mode, plus the SC610 jaxpr
#: companion in jaxpr_checks.py); SC901 polices the suppressions
#: themselves.
RULES = {r.id: r for r in (
    Rule(
        "SC101", "unknown-collective-axis", Severity.ERROR,
        "A collective (psum/pmean/all_gather/ppermute/...) names a mesh "
        "axis that is neither canonical (tpu_dist.parallel.axes) nor "
        "declared anywhere in the file (mesh/axis_shapes literal, *_AXIS "
        "constant, axis_name= parameter default). A wrong axis name "
        "raises at trace time at best and silently reduces over the "
        "wrong group at worst."),
    Rule(
        "SC102", "partitionspec-rank-mismatch", Severity.ERROR,
        "A PartitionSpec used to place an array has more entries than "
        "the array has dimensions. XLA rejects the placement at run "
        "time; catching it statically saves the trace/compile cycle."),
    Rule(
        "SC103", "host-side-effect-in-jit", Severity.ERROR,
        "A host side effect (print, time.time, stdlib random, input) "
        "inside a jitted function. These run once at trace time, not "
        "per step — prints go silent, clocks freeze, and Python "
        "randomness is baked into the compiled program as a constant."),
    Rule(
        "SC104", "donated-buffer-reuse", Severity.ERROR,
        "An argument donated via jit(donate_argnums=...) is read after "
        "the donating call. The buffer has been handed to XLA for "
        "aliasing; reusing it raises on real hardware and is "
        "silently-wrong on backends that skip donation."),
    Rule(
        "SC105", "swallowed-liveness-error", Severity.ERROR,
        "A bare `except Exception` (or `except:`) around a call that can "
        "raise PeerUnavailableError (liveness verdicts, barriers, chief "
        "broadcasts, host reductions) swallows the dead-peer signal. A "
        "supervised run recovers from that error by restarting the "
        "worker; a handler that eats it leaves the job half-alive. Catch "
        "PeerUnavailableError explicitly first, or re-raise."),
    Rule(
        "SC201", "collective-order-divergence", Severity.ERROR,
        "Branches of a lax.cond/switch issue different collective "
        "sequences. When the predicate is device-varying (the usual "
        "reason to branch in SPMD code), devices taking different "
        "branches launch mismatched collectives and the program "
        "deadlocks — the bug class TF's runtime ordered away and XLA "
        "will not catch for you."),
    Rule(
        "SC202", "data-dependent-collective-trip-count", Severity.ERROR,
        "A collective inside a lax.while_loop body (or its predicate). "
        "A while trip count is data-dependent by construction — unlike "
        "scan's static length — so ranks whose predicates diverge run "
        "different numbers of collective launches and the mismatched "
        "rendezvous deadlocks. Prove the trip count rank-uniform and "
        "rewrite as a bounded scan, or hoist the collective out."),
    Rule(
        "SC203", "collective-payload-mismatch", Severity.ERROR,
        "Paired collective launches whose payloads cannot line up across "
        "ranks: cond/switch branches issuing the same collective "
        "sequence but with different payload shapes/dtypes, or a "
        "ppermute whose permutation is invalid for the mesh axis in "
        "effect (index out of range, duplicate source, duplicate "
        "destination). Both trace fine and hang or corrupt at the "
        "rendezvous on real hardware."),
    Rule(
        "SC301", "comm-budget-regression", Severity.ERROR,
        "An entry point's total modeled communication volume exceeds "
        "the committed baseline (ANALYSIS_BASELINE.json) by more than "
        "the tolerance. Comm regressions only show up as step-time "
        "cliffs at pod scale; the static diff catches them in CI. "
        "Intended growth: re-run with --update-baseline and commit."),
    Rule(
        "SC302", "peak-hbm-over-budget", Severity.WARNING,
        "An entry point's estimated per-rank peak live-buffer bytes "
        "exceed the baseline's HBM budget. The linear-scan liveness "
        "estimate is an upper bound (rematerialization ignored), so "
        "this is a warning, not an error — but a jump usually means a "
        "batch/width change that will OOM first on the real machine."),
    Rule(
        "SC303", "undonated-dead-argument", Severity.WARNING,
        "A large entry-point argument whose jaxpr liveness proves it "
        "dead after its single use, yet never donated. XLA must keep "
        "the input buffer alive alongside its replacement; "
        "donate_argnums would alias them and halve that footprint. "
        "The jaxpr-proof deepening of SC104's AST guess."),
    Rule(
        "SC401", "unlocked-shared-attribute", Severity.WARNING,
        "An instance attribute is written both from a thread entry "
        "(Thread/Timer target, signal handler) and from non-thread code "
        "with no common lock held at either write (lockset approximation "
        "over `with self._lock:` scopes). Writes racing from two threads "
        "tear multi-step updates and publish half-built state; either "
        "share a lock across both writers or confine the attribute to "
        "one side and hand results over via a queue/join."),
    Rule(
        "SC402", "blocking-call-under-lock", Severity.ERROR,
        "A blocking call (Thread.join, Queue.get without timeout, "
        "Event.wait without timeout, barrier/rendezvous/collective) "
        "issued while holding a lock. Any other thread that needs the "
        "same lock to make progress — including the one being joined — "
        "deadlocks the process. Release the lock first, or bound the "
        "wait. (Condition.wait inside `with cond:` is exempt: wait "
        "releases the condition's own lock.)"),
    Rule(
        "SC403", "collective-on-worker-thread", Severity.ERROR,
        "A jax dispatch (device_put) or collective/barrier/rendezvous "
        "call is reachable from a non-main thread entry. Collectives "
        "rendezvous across ranks in launch order; issuing one from a "
        "helper thread races the main thread's launches and deadlocks "
        "or mismatches the pairing (the async-checkpoint writer-thread "
        "rule, machine-checked). Keep collectives on the main thread "
        "and hand the result to the worker."),
    Rule(
        "SC404", "hard-exit-under-lock", Severity.ERROR,
        "os._exit reachable from a code path that holds a lock. _exit "
        "skips atexit/finally teardown, so lock-protected state (a "
        "half-written protocol file, an unpublished async save) is "
        "abandoned in whatever state the holder left it; exit from "
        "outside the critical section or use the supervised-exit path."),
    Rule(
        "SC501", "rank-divergent-barrier", Severity.ERROR,
        "A rank-conditional branch (`if rank == 0` / process_index() / "
        "chief checks) where one arm reaches a barrier/rendezvous/"
        "collective the other arm cannot. The rank(s) taking the "
        "barrier-free arm never show up at the rendezvous and every "
        "other rank blocks until timeout. Hoist the barrier out of the "
        "conditional, or make both arms join it."),
    Rule(
        "SC502", "unbounded-blocking-wait", Severity.WARNING,
        "A wait/poll loop whose blocking calls carry no timeout and "
        "whose body has no deadline or abort_check-style escape. If the "
        "peer it waits on dies, the loop spins or blocks forever and "
        "the rank hangs the gang; bound each wait or consult an abort "
        "signal per iteration."),
    Rule(
        "SC503", "torn-protocol-write", Severity.ERROR,
        "A write to a protocol/marker/manifest file not staged through "
        "tmp + os.replace. Readers polling the path can observe a "
        "truncated or half-written payload mid-write; write to a tmp "
        "name in the same directory and os.replace it into place so "
        "publication is atomic."),
    Rule(
        "SC601", "nondet-source-taints-state", Severity.ERROR,
        "A nondeterministic value (wall-clock time.time/datetime.now, "
        "uuid1/uuid4, os.urandom, unseeded stdlib/np.random) flows — "
        "through the transitive assignment/call taint walk — into RNG "
        "key derivation (PRNGKey/fold_in/seed=), a checkpoint/journal/"
        "apply-log payload, or a protocol-file name used for ordering. "
        "One such value silently converts 'bit-exact replay' into "
        "'usually replays'. Coordinate-derived folds (epoch/step/rank) "
        "are the contract; mtime read back inside scan_grads is exempt "
        "(arrival order is the documented PS contract)."),
    Rule(
        "SC602", "rng-key-reuse", Severity.ERROR,
        "The same PRNG key expression is consumed by two jax.random "
        "sampler calls with no interleaving split/fold_in re-derivation. "
        "Reused keys make 'independent' draws identical — losses look "
        "plausible, statistics are silently wrong. Split the key, or "
        "fold a coordinate in between consumptions."),
    Rule(
        "SC603", "unordered-iteration-feeds-order", Severity.ERROR,
        "A loop over an unordered iterable (os.listdir/glob/scandir/"
        "iterdir, a set) whose body writes durable state, appends to a "
        "sequence that is never sorted, or launches collectives. "
        "Filesystem enumeration order is arbitrary; state derived from "
        "it differs run to run and rank to rank. Wrap the iterable in "
        "sorted(), or prove order-insensitivity (pure set/count/unlink "
        "bodies are not flagged)."),
    Rule(
        "SC604", "fold-constant-collision", Severity.WARNING,
        "Two distinct seed-derivation sites fold an identical constant "
        "into their streams. Derivations sharing a fold constant can "
        "collide (job A's seed arithmetic landing on job B's epoch "
        "stream), correlating 'independent' RNG streams. Give each "
        "derive domain its own constant."),
    Rule(
        "SC605", "float-accumulation-over-unordered", Severity.WARNING,
        "A float reduction (sum()/+= in a loop) over an unordered "
        "iterable inside a checksum/replay/verify/audit path. Float "
        "addition is not associative, so accumulation order changes the "
        "bits — exactly where bit-identity is the contract. Sort the "
        "iterable or use an order-insensitive (integer) accumulator."),
    Rule(
        "SC610", "rng-consumption-regression", Severity.ERROR,
        "A traced entry point whose committed baseline records ZERO RNG "
        "primitives (serve decode/prefill, audit checksums, the PS "
        "server apply — the contractually RNG-free steps) now consumes "
        "one. Randomness sneaking into an RNG-free program breaks "
        "replay/token-identity gates at the program level. Intended "
        "randomness: re-run cost --update-baseline and commit the "
        "diff."),
    Rule(
        "SC901", "stale-suppression", Severity.WARNING,
        "A `# shardcheck: disable=SCnnn` comment that suppresses "
        "nothing: no finding for that rule is raised at that line by "
        "the current pass. Stale suppressions rot into blanket "
        "exemptions as code moves; delete the comment or re-point it "
        "at the line that still needs it. Only rules the running mode "
        "actually evaluates are judged."),
    Rule(
        "SC900", "entry-point-untraceable", Severity.INFO,
        "A registered jaxpr-check entry point could not be traced in "
        "this environment; its collective-order check was skipped."),
)}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule_id: str
    path: str
    line: int
    col: int
    message: str

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    @property
    def severity(self) -> Severity:
        return self.rule.severity

    def to_json(self) -> dict:
        return {
            "rule_id": self.rule_id,
            "name": self.rule.name,
            "severity": str(self.severity),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule_id} {self.severity}] {self.message}")


#: ``# shardcheck: disable=SC101`` or ``disable=SC101,SC103`` or
#: ``disable=all``; anything after the rule list (a justification) is free
#: text. Matches anywhere in the physical line so it can trail code.
_SUPPRESS_RE = re.compile(
    r"#\s*shardcheck:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s+--?\s|$|#)")


def suppressions_for_line(source_line: str) -> Optional[set]:
    """Rule IDs suppressed on this physical line, or None when no
    suppression comment is present. ``{"all"}`` suppresses every rule."""
    m = _SUPPRESS_RE.search(source_line)
    if not m:
        return None
    ids = {part.strip() for part in m.group(1).split(",") if part.strip()}
    return {i if i.lower() != "all" else "all" for i in ids}


def apply_suppressions(findings, source_by_path) -> list:
    """Drop findings whose source line carries a matching suppression.

    ``source_by_path`` maps path -> list of source lines (1-indexed via
    ``line - 1``). Findings for paths not in the map pass through.
    """
    kept = []
    for f in findings:
        lines = source_by_path.get(f.path)
        if lines is not None and 1 <= f.line <= len(lines):
            sup = suppressions_for_line(lines[f.line - 1])
            if sup is not None and ("all" in sup or f.rule_id in sup):
                continue
        kept.append(f)
    return kept


def stale_suppressions(pre_findings, source_by_path, evaluated) -> list:
    """SC901: suppression comments that suppress nothing.

    ``pre_findings`` must be the findings *before* apply_suppressions,
    so a live suppression (one that is eating a real finding) can be
    told apart from a stale one. Only rule IDs in ``evaluated`` — the
    rules the current mode actually ran — are judged; a comment naming
    a rule from another family is left alone (its finding may exist in
    the other mode), and ``disable=all`` is never judged for the same
    reason.
    """
    fired: dict = {}
    for f in pre_findings:
        fired.setdefault((f.path, f.line), set()).add(f.rule_id)
    evaluated = set(evaluated)
    out = []
    for path in sorted(source_by_path):
        for i, line in enumerate(source_by_path[path], 1):
            sup = suppressions_for_line(line)
            if not sup or "all" in sup:
                continue
            live = fired.get((path, i), set())
            for rule_id in sorted(sup & (evaluated - live)):
                out.append(Finding(
                    "SC901", path, i, 0,
                    f"suppression for {rule_id} matches no {rule_id} "
                    f"finding at this line; delete the comment or "
                    f"re-point it at the code that still needs it"))
    return out
