"""shardcheck CLI: ``python -m tpu_dist.analysis [paths]``.

Two passes over the given paths (default: the installed ``tpu_dist``
package):

1. the AST lint (ast_lint.py) over every ``.py`` file — no imports, no
   backend;
2. unless ``--no-trace``: the jaxpr checks (jaxpr_checks.py) — the
   built-in entry points (trainer step, both pipeline schedules) traced on
   a forced-CPU backend, plus any analyzed module that defines a
   ``shardcheck_entry()`` returning ``(fn, example_args)``.

Exit code 1 when any finding reaches ``--fail-on`` severity (default:
error), 0 otherwise — the contract ``scripts/check.sh`` builds on.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import sys
from typing import Optional

from tpu_dist.analysis import ast_lint, report
from tpu_dist.analysis.rules import Finding, apply_suppressions


def _force_cpu_backend() -> None:
    """Pin tracing to CPU with enough virtual devices for a 2-stage pipe
    mesh. jax reads XLA_FLAGS at backend init and its platform config
    lazily, so this works even though the package import already pulled in
    jax — unless a backend was initialized first, in which case the entry
    traces degrade to SC900 info findings on their own."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - leave the default backend
        pass


def _has_shardcheck_entry(path: str) -> bool:
    """Cheap AST probe so only opted-in modules get imported."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return False
    return any(isinstance(node, (ast.FunctionDef,))
               and node.name == "shardcheck_entry"
               for node in tree.body)


def _check_module_entry(path: str) -> list[Finding]:
    """Import ``path`` and run jaxpr checks on its shardcheck_entry()."""
    from tpu_dist.analysis import jaxpr_checks

    name = "_shardcheck_" + os.path.splitext(
        os.path.basename(path))[0]
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        fn, args = module.shardcheck_entry()
        return jaxpr_checks.check_callable(
            fn, tuple(args), label=f"{path}::shardcheck_entry", path=path)
    except Exception as e:  # noqa: BLE001 - degrade, never crash the run
        return [Finding(
            "SC900", path, 1, 0,
            f"shardcheck_entry() could not be traced "
            f"({type(e).__name__}: {e})")]


def _default_paths() -> list[str]:
    """The installed package itself — the dogfood target."""
    import tpu_dist

    return [os.path.dirname(os.path.abspath(tpu_dist.__file__))]


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="shardcheck: static sharding/collective consistency "
                    "checks for tpu_dist programs")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to check (default: the tpu_dist "
             "package)")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable JSON on stdout instead of text")
    parser.add_argument(
        "--no-trace", action="store_true",
        help="skip the jaxpr-level checks (AST lint only; no jax backend "
             "touched)")
    parser.add_argument(
        "--fail-on", default="error",
        choices=("error", "warning", "info", "never"),
        help="lowest severity that makes the exit code non-zero "
             "(default: error)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        report.render_rules()
        return 0

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            parser.error(f"no such path: {p}")

    findings = ast_lint.lint_paths(paths)

    if not args.no_trace:
        _force_cpu_backend()
        from tpu_dist.analysis import jaxpr_checks

        files = ast_lint.iter_python_files(paths)
        # Built-in entry points run when the package under check is (or
        # contains) tpu_dist itself — the dogfooded self-check.
        if any(os.sep + "tpu_dist" + os.sep in os.path.abspath(f) + os.sep
               or os.path.basename(f) == "trainer.py" for f in files):
            findings.extend(jaxpr_checks.run_entry_points())
        trace_findings = []
        for f in files:
            if _has_shardcheck_entry(f):
                trace_findings.extend(_check_module_entry(f))
        source_by_path = {}
        for f in {t.path for t in trace_findings if os.path.exists(t.path)}:
            with open(f, "r", encoding="utf-8") as fh:
                source_by_path[f] = fh.read().splitlines()
        findings.extend(apply_suppressions(trace_findings, source_by_path))

    if args.json:
        report.dump_json(report.to_json_dict(
            findings, paths=paths, fail_on=args.fail_on))
    else:
        report.render_text(findings, paths=paths)
    return report.exit_code(findings, fail_on=args.fail_on)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
