"""shardcheck CLI: ``python -m tpu_dist.analysis [cost] [paths]``.

The default (check) mode runs two passes over the given paths (default:
the installed ``tpu_dist`` package):

1. the AST lint (ast_lint.py) over every ``.py`` file — no imports, no
   backend;
2. unless ``--no-trace``: the jaxpr checks (jaxpr_checks.py) — the
   built-in entry points (trainer step, both pipeline schedules, the
   TP/SP/MoE parallel steps) traced on a forced-CPU backend, plus any
   analyzed module that defines a ``shardcheck_entry()`` returning
   ``(fn, example_args)`` or ``(fn, example_args, donate_argnums)``.

Exit code 1 when any finding reaches ``--fail-on`` severity (default:
error; ``--strict`` lowers it to warning), 0 otherwise — the contract
``scripts/check.sh`` builds on. ``--format github`` renders findings as
workflow annotations (``::error file=…,line=…::``).

``--concurrency`` (SC4xx/SC5xx) and ``--determinism`` (SC6xx) each swap
in a pure-AST interprocedural pass over a shared call-graph Project —
no imports, no backend. ``--rules SC601,SC603`` narrows any mode to the
listed rules (SC900/SC901 always ride along); ``--list-rules`` prints
the catalogue.

``cost`` mode prices the same traces instead of rule-checking them: per
entry point, modeled communication volume and peak live-buffer bytes
(costmodel.py), optionally diffed against a committed baseline
(baseline.py) — ``--baseline`` to gate, ``--update-baseline`` to commit
intended growth, ``--mesh data=8,model=4`` to model a topology other
than the traced one.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import sys
from typing import Optional

from tpu_dist.analysis import ast_lint, report
from tpu_dist.analysis.rules import (
    RULES,
    Finding,
    apply_suppressions,
    stale_suppressions,
)

#: Which rules each mode evaluates — the SC901 staleness scope. SC2xx/
#: SC3xx are excluded on purpose: whether a trace/baseline finding
#: exists depends on the environment, so their suppressions cannot be
#: proven stale from a single run.
_AST_RULE_IDS = frozenset({"SC101", "SC102", "SC103", "SC104", "SC105"})
_CONCURRENCY_RULE_IDS = frozenset({
    "SC401", "SC402", "SC403", "SC404", "SC501", "SC502", "SC503"})
_DETERMINISM_RULE_IDS = frozenset({
    "SC601", "SC602", "SC603", "SC604", "SC605"})


def _add_rules_arg(parser) -> None:
    parser.add_argument(
        "--rules", default=None, metavar="SCnnn[,SCnnn...]",
        help="run only these rule IDs (e.g. --rules SC601,SC603); "
             "SC900 degradation and SC901 staleness reporting always "
             "stay on")


def _parse_rules(parser, spec: Optional[str]) -> Optional[frozenset]:
    """Validated ``--rules`` selection, or None for 'all rules'."""
    if spec is None:
        return None
    selected = {part.strip() for part in spec.split(",") if part.strip()}
    unknown = sorted(r for r in selected if r not in RULES)
    if unknown:
        parser.error(f"unknown rule ID(s): {', '.join(unknown)}; "
                     f"see --list-rules")
    if not selected:
        parser.error("--rules given but no rule IDs parsed")
    return frozenset(selected)


def _filter_rules(findings, selected: Optional[frozenset]) -> list:
    """Keep only selected rules. SC900 (degradation) and SC901 (stale
    suppressions) are never filtered out: a narrowed run must still be
    honest about what it could not analyze."""
    if selected is None:
        return list(findings)
    keep = set(selected) | {"SC900", "SC901"}
    return [f for f in findings if f.rule_id in keep]


def _force_cpu_backend() -> None:
    """Pin tracing to CPU with enough virtual devices for the entry-point
    meshes (the data x expert MoE entry needs 8). jax reads XLA_FLAGS at
    backend init and its platform config lazily, so this works even though
    the package import already pulled in jax — unless a backend was
    initialized first, in which case the entry traces degrade to SC900
    info findings on their own."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - leave the default backend
        pass


def _has_shardcheck_entry(path: str) -> bool:
    """Cheap AST probe so only opted-in modules get imported."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return False
    return any(isinstance(node, (ast.FunctionDef,))
               and node.name == "shardcheck_entry"
               for node in tree.body)


def _load_module_entry(path: str):
    """Import ``path`` and normalize its ``shardcheck_entry()`` to
    ``(fn, args, donate_argnums)`` — the optional third element tells
    SC303 which arguments the production caller donates."""
    name = "_shardcheck_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    entry = tuple(module.shardcheck_entry())
    if len(entry) == 3:
        fn, args, donated = entry
    else:
        fn, args = entry
        donated = ()
    return fn, tuple(args), tuple(donated)


def _check_module_entry(path: str) -> list[Finding]:
    """Import ``path`` and run jaxpr checks on its shardcheck_entry()."""
    from tpu_dist.analysis import jaxpr_checks

    try:
        fn, args, donated = _load_module_entry(path)
        return jaxpr_checks.check_callable(
            fn, args, label=f"{path}::shardcheck_entry", path=path,
            donated=donated)
    except Exception as e:  # noqa: BLE001 - degrade, never crash the run
        from tpu_dist.analysis.jaxpr_checks import _cause

        return [Finding(
            "SC900", path, 1, 0,
            f"shardcheck_entry() could not be traced ({_cause(e)})")]


def _default_paths() -> list[str]:
    """The installed package itself — the dogfood target."""
    import tpu_dist

    return [os.path.dirname(os.path.abspath(tpu_dist.__file__))]


def _render(findings, *, fmt: str, paths=(), fail_on: str) -> None:
    if fmt == "json":
        report.dump_json(report.to_json_dict(
            findings, paths=paths, fail_on=fail_on))
    elif fmt == "github":
        report.render_github(findings)
    else:
        report.render_text(findings, paths=paths)


def _project_mode_check(paths, checkers, mode_rule_ids,
                        selected: Optional[frozenset]) -> list[Finding]:
    """Shared driver for the project-graph modes (--concurrency,
    --determinism): build the call graph once, run the mode's checkers,
    apply suppressions, then SC901 staleness scoped to the rules this
    run actually evaluated (mode ∩ --rules selection — a suppression for
    a deselected rule cannot be proven stale by a run that never looked
    for its finding)."""
    from tpu_dist.analysis import concurrency

    project = concurrency.build_project(paths)
    raw: list[Finding] = []
    for check in checkers:
        raw.extend(check(project))
    raw = _filter_rules(raw, selected)
    source_by_path = {m.path: m.source_lines
                      for m in project.modules.values()}
    evaluated = (mode_rule_ids if selected is None
                 else mode_rule_ids & selected)
    findings = apply_suppressions(raw, source_by_path)
    findings.extend(apply_suppressions(
        stale_suppressions(raw, source_by_path, evaluated),
        source_by_path))
    return findings


def _concurrency_check(paths,
                       selected: Optional[frozenset] = None
                       ) -> list[Finding]:
    """``--concurrency`` mode: SC4xx thread-safety + SC5xx liveness over
    the interprocedural host call graph. Pure AST — no imports, no
    backend."""
    from tpu_dist.analysis import concurrency, liveness

    return _project_mode_check(
        paths, [concurrency.check_project, liveness.check_project],
        _CONCURRENCY_RULE_IDS, selected)


def _determinism_check(paths,
                       selected: Optional[frozenset] = None
                       ) -> list[Finding]:
    """``--determinism`` mode: SC6xx determinism/RNG-lineage rules over
    the same host call graph. Pure AST — the jaxpr half of the family
    (SC610) rides the `cost` subcommand, which already traces."""
    from tpu_dist.analysis import determinism

    return _project_mode_check(
        paths, [determinism.check_project],
        _DETERMINISM_RULE_IDS, selected)


def main(argv: Optional[list] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "cost":
        return cost_main(argv[1:])

    parser = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis",
        description="shardcheck: static sharding/collective consistency "
                    "checks for tpu_dist programs (see also the `cost` "
                    "subcommand for the communication/memory cost model)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to check (default: the tpu_dist "
             "package)")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable JSON on stdout instead of text "
             "(alias for --format json)")
    parser.add_argument(
        "--format", default=None, choices=("text", "json", "github"),
        help="output format; `github` emits ::error/::warning workflow "
             "annotations (default: text)")
    parser.add_argument(
        "--no-trace", action="store_true",
        help="skip the jaxpr-level checks (AST lint only; no jax backend "
             "touched)")
    parser.add_argument(
        "--concurrency", action="store_true",
        help="run the host-runtime concurrency/liveness analyzer "
             "(SC4xx/SC5xx + SC901) instead of the sharding lint; pure "
             "AST, no backend")
    parser.add_argument(
        "--determinism", action="store_true",
        help="run the determinism/RNG-lineage analyzer (SC6xx + SC901) "
             "instead of the sharding lint; pure AST, no backend (the "
             "SC610 jaxpr companion runs under the `cost` subcommand)")
    _add_rules_arg(parser)
    parser.add_argument(
        "--fail-on", default="error",
        choices=("error", "warning", "info", "never"),
        help="lowest severity that makes the exit code non-zero "
             "(default: error)")
    parser.add_argument(
        "--strict", action="store_true",
        help="fail on warnings too (shorthand for --fail-on warning)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        report.render_rules()
        return 0

    fmt = args.format or ("json" if args.json else "text")
    fail_on = "warning" if args.strict else args.fail_on
    selected = _parse_rules(parser, args.rules)

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            parser.error(f"no such path: {p}")

    if args.concurrency and args.determinism:
        parser.error("--concurrency and --determinism are separate "
                     "modes; run them as two invocations")
    if args.concurrency:
        findings = _concurrency_check(paths, selected)
        _render(findings, fmt=fmt, paths=paths, fail_on=fail_on)
        return report.exit_code(findings, fail_on=fail_on)
    if args.determinism:
        findings = _determinism_check(paths, selected)
        _render(findings, fmt=fmt, paths=paths, fail_on=fail_on)
        return report.exit_code(findings, fail_on=fail_on)

    raw, source_by_path = ast_lint.lint_paths_raw(paths)
    raw = _filter_rules(raw, selected)
    evaluated = (_AST_RULE_IDS if selected is None
                 else _AST_RULE_IDS & selected)
    findings = apply_suppressions(raw, source_by_path)
    findings.extend(apply_suppressions(
        stale_suppressions(raw, source_by_path, evaluated),
        source_by_path))

    if not args.no_trace:
        _force_cpu_backend()
        from tpu_dist.analysis import jaxpr_checks

        files = ast_lint.iter_python_files(paths)
        # Built-in entry points run when the package under check is (or
        # contains) tpu_dist itself — the dogfooded self-check.
        if any(os.sep + "tpu_dist" + os.sep in os.path.abspath(f) + os.sep
               or os.path.basename(f) == "trainer.py" for f in files):
            findings.extend(_filter_rules(
                jaxpr_checks.run_entry_points(), selected))
        trace_findings = []
        for f in files:
            if _has_shardcheck_entry(f):
                trace_findings.extend(_check_module_entry(f))
        trace_findings = _filter_rules(trace_findings, selected)
        source_by_path = {}
        for f in {t.path for t in trace_findings if os.path.exists(t.path)}:
            with open(f, "r", encoding="utf-8") as fh:
                source_by_path[f] = fh.read().splitlines()
        findings.extend(apply_suppressions(trace_findings, source_by_path))

    _render(findings, fmt=fmt, paths=paths, fail_on=fail_on)
    return report.exit_code(findings, fail_on=fail_on)


def cost_main(argv: Optional[list] = None) -> int:
    """``python -m tpu_dist.analysis cost`` — the cost model + baseline
    gate. See the module docstring for semantics; the mesh precedence is
    ``--mesh`` > the baseline's committed mesh > the traced meshes
    unmodified, so a bare ``cost --baseline ...`` (the check.sh stage)
    reprices exactly the topology the baseline was committed at."""
    from tpu_dist.analysis import baseline as baseline_lib
    from tpu_dist.analysis import costmodel

    parser = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis cost",
        description="shardcheck cost model: static per-entry-point "
                    "communication volume and peak live-buffer estimate, "
                    "with an optional committed-baseline CI gate")
    parser.add_argument(
        "paths", nargs="*",
        help="additional modules with a shardcheck_entry() to price "
             "alongside the built-in entry points")
    parser.add_argument(
        "--mesh", default=None,
        metavar="AXIS=N[:BW_GBPS[:LAT_US]][,...]",
        help="model the ring costs at these axis sizes (e.g. "
             "data=8,model=4) instead of the traced mesh sizes; an "
             "optional per-axis link suffix (e.g. data=8:90:1.5 for "
             "90 GB/s links with 1.5 us launch latency) feeds the step "
             "latency estimate")
    parser.add_argument(
        "--entries", default=None, metavar="NAME[,NAME...]",
        help="restrict to these built-in entry points (default: all)")
    parser.add_argument(
        "--calibrate", action="store_true",
        help="microbench THIS host (timed psum sweep + one timed matmul) "
             "into per-axis link bandwidth/latency and a TFLOP/s rate, "
             "emit the JSON, and exit; feed it back with --links")
    parser.add_argument(
        "--calibrate-out", default=None, metavar="PATH",
        help="write the --calibrate JSON here instead of stdout")
    parser.add_argument(
        "--links", default=None, metavar="@PATH",
        help="price with a calibration file from --calibrate (per-axis "
             "links + flops rate); explicit --mesh link suffixes still "
             "win for their axes")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="diff against this committed baseline; comm growth past the "
             "tolerance is an SC301 error, peak HBM past budget an SC302 "
             "warning")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="write the measured costs to --baseline (default "
             "ANALYSIS_BASELINE.json) instead of diffing, carrying over "
             "still-valid HBM budgets")
    parser.add_argument(
        "--tolerance", type=float, default=None, metavar="PCT",
        help="comm-growth tolerance in percent (default: the baseline's "
             f"committed value, else {baseline_lib.DEFAULT_TOLERANCE_PCT:g})")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable JSON on stdout instead of text "
             "(alias for --format json)")
    parser.add_argument(
        "--format", default=None, choices=("text", "json", "github"),
        help="output format (github: workflow annotations for findings)")
    parser.add_argument(
        "--strict", action="store_true",
        help="fail on warnings (SC302) too, not just SC301 errors")
    _add_rules_arg(parser)
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        report.render_rules()
        return 0

    fmt = args.format or ("json" if args.json else "text")
    fail_on = "warning" if args.strict else "error"
    selected = _parse_rules(parser, args.rules)
    baseline_path = args.baseline or "ANALYSIS_BASELINE.json"

    if args.calibrate:
        import json

        # Before _force_cpu_backend(): the whole point is measuring the
        # backend this process actually has.
        axis_names = (tuple(costmodel.parse_mesh(args.mesh))
                      if args.mesh else ("data",))
        spec = costmodel.calibrate(axis_names=axis_names or ("data",))
        text = json.dumps(spec, indent=2, sort_keys=True) + "\n"
        if args.calibrate_out:
            with open(args.calibrate_out, "w") as f:
                f.write(text)
            print(f"wrote {args.calibrate_out}: backend "
                  f"{spec['backend']} x{spec['device_count']}, "
                  f"{spec['flops_per_s'] / 1e12:.3f} TFLOP/s")
        else:
            sys.stdout.write(text)
        return 0
    for p in args.paths:
        if not os.path.exists(p):
            parser.error(f"no such path: {p}")

    previous = None
    if os.path.exists(baseline_path) and (args.baseline
                                          or args.update_baseline):
        previous = baseline_lib.load(baseline_path)
    elif args.baseline and not args.update_baseline:
        parser.error(f"no such baseline: {args.baseline}")

    links = {}
    if args.mesh is not None:
        model_mesh, links = costmodel.parse_mesh_links(args.mesh)
    elif previous is not None and not args.update_baseline:
        model_mesh = dict(previous.get("mesh", {}))
    else:
        model_mesh = {}

    flops_per_s = None
    if args.links is not None:
        path = args.links[1:] if args.links.startswith("@") else args.links
        if not os.path.exists(path):
            parser.error(f"no such calibration file: {path}")
        file_links, flops_per_s = costmodel.load_links(path)
        # Explicit --mesh suffixes override the file per axis.
        links = {**file_links, **links}

    _force_cpu_backend()
    from tpu_dist.analysis import jaxpr_checks

    names = (set(args.entries.split(",")) if args.entries else None)
    if names:
        # ``module:<basename>`` labels select path entries; the rest must
        # name built-ins.
        unknown = {n for n in names
                   if n not in jaxpr_checks.ENTRY_POINTS
                   and not n.startswith("module:")}
        if unknown:
            parser.error(f"unknown entry point(s): {sorted(unknown)}; "
                         f"known: {sorted(jaxpr_checks.ENTRY_POINTS)} "
                         "plus module:<basename> labels")
    traced, findings = jaxpr_checks.trace_entry_points(names)
    reports = {
        name: costmodel.analyze_jaxpr(
            closed, entry=name, model_mesh=model_mesh, links=links,
            flops_per_s=flops_per_s)
        for name, closed in traced.items()}
    # SC610 rides the cost pipeline: the traces are already in hand, so
    # the RNG-consumption sets are free to record/diff here.
    rng_now = {name: jaxpr_checks.rng_primitives(closed)
               for name, closed in traced.items()}

    for p in args.paths:
        for f in ast_lint.iter_python_files([p]):
            if not _has_shardcheck_entry(f):
                continue
            label = "module:" + os.path.splitext(os.path.basename(f))[0]
            if names is not None and label not in names:
                continue
            try:
                fn, fargs, _ = _load_module_entry(f)
                import jax

                closed = jax.make_jaxpr(fn)(*fargs)
                reports[label] = costmodel.analyze_jaxpr(
                    closed, entry=label, model_mesh=model_mesh, links=links,
                    flops_per_s=flops_per_s)
                rng_now[label] = jaxpr_checks.rng_primitives(closed)
            except Exception as e:  # noqa: BLE001 - degrade, never crash
                findings.append(Finding(
                    "SC900", f, 1, 0,
                    f"shardcheck_entry() could not be traced "
                    f"({jaxpr_checks._cause(e)})"))

    if args.update_baseline:
        tol = (args.tolerance if args.tolerance is not None
               else (previous or {}).get(
                   "tolerance_pct", baseline_lib.DEFAULT_TOLERANCE_PCT))
        data = baseline_lib.build(
            reports, mesh=model_mesh, tolerance_pct=tol, previous=previous,
            rng=rng_now)
        baseline_lib.write(baseline_path, data)
        print(f"wrote {baseline_path}: {len(reports)} entry point(s), "
              f"mesh {model_mesh or '(as traced)'}, "
              f"tolerance {float(tol):g}%")
        for f in report.sort_findings(findings):
            print(f.render())
        return 0

    if previous is not None:
        findings.extend(baseline_lib.compare(
            reports, previous, tolerance_pct=args.tolerance,
            path=baseline_path))
        findings.extend(jaxpr_checks.check_rng_baseline(
            rng_now, previous.get("rng", {}), baseline_path))

    findings = _filter_rules(findings, selected)
    if fmt == "json":
        report.dump_json(report.to_cost_json(
            reports, findings, mesh=model_mesh,
            baseline_path=args.baseline, fail_on=fail_on))
    elif fmt == "github":
        report.render_github(findings)
    else:
        report.render_cost_text(reports, findings, mesh=model_mesh)
    return report.exit_code(findings, fail_on=fail_on)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
