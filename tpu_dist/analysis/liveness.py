"""Host-runtime liveness & protocol analyzer — the SC5xx family.

Where SC4xx (concurrency.py) asks "can these threads corrupt each
other", SC5xx asks "can this rank hang the gang or tear the protocol":

* **SC501 rank-divergent barrier** — a rank-conditional ``if`` (chief
  checks, ``process_index() == 0``, ``rank == 0``) where one arm
  transitively reaches a barrier/rendezvous/collective and the other
  cannot. The barrier-free ranks never show up and everyone else blocks.
  Arms that *abort* (end in ``raise`` or hard-exit) are exempt — dying
  instead of diverging is the supervised-restart contract, not a hang.
  An ``if`` whose body terminates in ``return`` compares against the
  rest of the enclosing block (the guard-clause form); an ``if`` with
  no ``else`` and no return compares against an empty arm.
* **SC502 unbounded blocking wait** — a ``while`` loop that waits or
  polls (``.wait()``/``.get()``/``.join()``/``.acquire()``/``sleep``)
  where no wait carries a timeout and neither the loop condition nor
  the body consults a deadline/abort escape. Every blocking wait in
  this runtime is supposed to be bounded or abortable (the PR-3 rule).
* **SC503 torn protocol write** — ``open(..., "w")`` /
  ``Path.write_text`` / ``write_bytes`` whose path expression looks
  protocol-ish (marker/reform/generation/pointer/manifest/heartbeat…)
  but is neither a tmp/staging name nor in a function that also calls
  ``os.replace``. Readers polling such files must never observe a
  half-written payload; the repo idiom is tmp + ``os.replace``.

All three run over the :class:`~tpu_dist.analysis.concurrency.Project`
call graph, so "reaches a collective" is transitive, with the same
conservative resolution (an unresolvable call contributes nothing).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from tpu_dist.analysis.concurrency import (
    RENDEZVOUS_TAILS,
    FunctionInfo,
    Project,
    _iter_calls,
    _tail,
    _unparse,
    build_project,
)
from tpu_dist.analysis.rules import Finding

#: Does an if-test look rank-conditional? Matched against the unparsed
#: test expression, so `bootstrap.is_chief()`, `rank == 0`,
#: `jax.process_index() != 0` and `self.chief` all hit.
_RANK_TEST_RE = re.compile(
    r"\b(is_chief|chief|rank|process_index|worker_index)\b")

#: Loop-condition identifiers that are themselves an escape: the loop
#: exits when a stop/deadline signal flips, so it is not an unbounded
#: wait on a peer.
_ESCAPE_TEST_RE = re.compile(
    r"\b(deadline|timeout|stop|shutdown|abort|done|exit|budget|"
    r"remaining|max_steps|attempts|retries|monotonic|perf_counter)", re.I)

#: Inside the loop body only deadline-ish comparisons count as escapes
#: (a sentinel `break` alone still blocks forever on the unbounded get).
_ESCAPE_BODY_RE = re.compile(
    r"\b(deadline|timeout|abort|remaining|budget|max_restarts|max_steps|"
    r"attempts|retries)", re.I)

#: Clock reads that mark a loop as deadline-driven when paired with a
#: `break`/`return` — the `wait = target - monotonic(); if wait <= 0:
#: break` pacing idiom, where the deadline variable carries no
#: deadline-ish name.
_CLOCK_TAILS = frozenset({"monotonic", "perf_counter"})

#: Path expressions that look like gang-protocol artifacts.
_PROTOCOL_PATH_RE = re.compile(
    r"(marker|reform|protocol|generation|pointer|latest|manifest|"
    r"barrier|rendezvous|gang|heartbeat|commit)", re.I)

#: ...and the staging half of the atomic-publish idiom.
_STAGING_PATH_RE = re.compile(r"(tmp|temp|stage|staging|partial)", re.I)

_WAIT_TAILS = frozenset({"wait", "get", "join", "acquire", "sleep"})


def _stmt_lines(stmts) -> list:
    """(first, last) physical-line spans covered by a statement list."""
    spans = []
    for s in stmts:
        end = getattr(s, "end_lineno", None) or s.lineno
        spans.append((s.lineno, end))
    return spans


def _in_spans(line: int, spans) -> bool:
    return any(a <= line <= b for a, b in spans)


def _iter_own_stmts(node):
    """Statement lists belonging to this function, pruning nested defs."""
    todo = [node.body]
    while todo:
        body = todo.pop()
        yield body
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    todo.append(sub)
            for h in getattr(stmt, "handlers", []):
                todo.append(h.body)


# ----------------------------------------------------------------------
# SC501


def _arm_reaches_rendezvous(fn: FunctionInfo, project: Project,
                            stmts) -> Optional[str]:
    """Name of a rendezvous the arm can reach, or None."""
    spans = _stmt_lines(stmts)
    for name, line, _col in fn.rendezvous_sites:
        if _in_spans(line, spans):
            return name
    for callee, line, _col, _locks, _call in fn.call_sites:
        if callee in project.reaches_rendezvous and _in_spans(line, spans):
            return project.functions[callee].qualname + "()"
    return None


def _arm_aborts(fn: FunctionInfo, stmts) -> bool:
    if stmts and isinstance(stmts[-1], ast.Raise):
        return True
    spans = _stmt_lines(stmts)
    return any(_in_spans(line, spans) for line, _c, _l in fn.exit_sites)


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], (ast.Return, ast.Raise))


def _check_rank_divergence(fn: FunctionInfo, project: Project) -> list:
    findings = []
    if isinstance(fn.node, ast.Lambda):
        return findings
    for body in _iter_own_stmts(fn.node):
        for i, stmt in enumerate(body):
            if not isinstance(stmt, ast.If):
                continue
            if not _RANK_TEST_RE.search(_unparse(stmt.test)):
                continue
            then_arm = stmt.body
            if stmt.orelse:
                else_arm = stmt.orelse
            elif _terminates(then_arm):
                # guard clause: the implicit else is the rest of the block
                else_arm = body[i + 1:]
            else:
                else_arm = []
            then_hit = _arm_reaches_rendezvous(fn, project, then_arm)
            else_hit = (_arm_reaches_rendezvous(fn, project, else_arm)
                        if else_arm else None)
            if bool(then_hit) == bool(else_hit):
                continue
            if (_arm_aborts(fn, then_arm)
                    or (else_arm and _arm_aborts(fn, else_arm))):
                continue
            hit = then_hit or else_hit
            which = "taken" if then_hit else "skipped"
            findings.append(Finding(
                "SC501", fn.path, stmt.lineno, stmt.col_offset,
                f"rank-conditional `if {_unparse(stmt.test)}` reaches "
                f"{hit} only when the test arm is {which}; ranks on the "
                f"other arm never join that rendezvous and the gang "
                f"blocks"))
    return findings


# ----------------------------------------------------------------------
# SC502


def _wait_calls(node: ast.While):
    """(call, bounded) for every wait/poll call in the loop, nested defs
    pruned. `sleep` marks a poll loop but never bounds it."""
    for call in _iter_calls(node):
        tail = _tail(call.func)
        if tail not in _WAIT_TAILS:
            continue
        timeout_kw = any(k.arg and "timeout" in k.arg
                         for k in call.keywords)
        recv = (call.func.value if isinstance(call.func, ast.Attribute)
                else None)
        if tail == "get":
            first = call.args[0] if call.args else None
            if first is not None and not (isinstance(first, ast.Constant)
                                          and first.value is True):
                continue  # dict.get(key)/environ.get(key): not a wait
            # q.get() / q.get(True) block; only a timeout bounds them
            yield call, timeout_kw or len(call.args) > 1
        elif tail == "join":
            if call.args or timeout_kw:
                continue  # "sep".join(parts) or a bounded join: ignore
            if isinstance(recv, ast.Constant):
                continue  # literal-separator string join
            yield call, False
        elif tail in ("wait", "acquire"):
            bounded = timeout_kw
            if call.args:
                first = call.args[0]
                if (isinstance(first, ast.Constant)
                        and (first.value is None or first.value is True)):
                    # cond.wait(None) / lock.acquire(True) spell out the
                    # defaults and still block forever; a second arg is
                    # acquire's timeout
                    bounded = bounded or len(call.args) > 1
                else:
                    # a numeric first arg is a timeout; acquire(False)
                    # never blocks
                    bounded = True
            yield call, bounded
        else:  # sleep: bounded per call, but it never bounds the loop
            yield call, False


def _loop_has_escape(node: ast.While) -> bool:
    if _ESCAPE_TEST_RE.search(_unparse(node.test)):
        return True
    reads_clock = has_break = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.If) and _ESCAPE_BODY_RE.search(
                _unparse(sub.test)):
            return True
        if isinstance(sub, ast.Call):
            t = _tail(sub.func)
            if t and "abort" in t.lower():
                return True
            if t in _CLOCK_TAILS or _unparse(sub.func) == "time.time":
                reads_clock = True
        if isinstance(sub, (ast.Break, ast.Return)):
            has_break = True
        if isinstance(sub, ast.Raise) and sub.exc is not None:
            if _ESCAPE_BODY_RE.search(_unparse(sub.exc)):
                return True
    return reads_clock and has_break


def _check_unbounded_waits(fn: FunctionInfo) -> list:
    findings = []
    if isinstance(fn.node, ast.Lambda):
        return findings
    for body in _iter_own_stmts(fn.node):
        for stmt in body:
            if not isinstance(stmt, ast.While):
                continue
            waits = list(_wait_calls(stmt))
            if not waits:
                continue
            if any(bounded for _c, bounded in waits):
                continue
            if _loop_has_escape(stmt):
                continue
            calls = ", ".join(sorted({
                f"{_unparse(c.func)}()" for c, _b in waits}))
            findings.append(Finding(
                "SC502", fn.path, stmt.lineno, stmt.col_offset,
                f"wait loop blocks on {calls} with no timeout and no "
                f"deadline/abort escape; a dead peer leaves this rank "
                f"hung forever"))
    return findings


# ----------------------------------------------------------------------
# SC503


def _fn_calls(fn: FunctionInfo):
    """Calls in the function's own body (nested defs pruned) — _iter_calls
    seeded below the def node itself, which it would otherwise prune."""
    if isinstance(fn.node, ast.Lambda):
        yield from _iter_calls(fn.node.body)
        return
    for stmt in fn.node.body:
        yield from _iter_calls(stmt)


def _write_sites(fn: FunctionInfo):
    """(path expression text, line, col) for plain-file writes."""
    for call in _fn_calls(fn):
        tail = _tail(call.func)
        if tail in ("write_text", "write_bytes") and isinstance(
                call.func, ast.Attribute):
            yield (_unparse(call.func.value), call.lineno,
                   call.col_offset)
        elif tail == "open" and len(call.args) >= 2:
            mode = call.args[1]
            if (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and mode.value[:1] in ("w", "x")):
                yield (_unparse(call.args[0]), call.lineno,
                       call.col_offset)
        elif tail == "open":
            mode = next((k.value for k in call.keywords
                         if k.arg == "mode"), None)
            if (mode is not None and isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and mode.value[:1] in ("w", "x") and call.args):
                yield (_unparse(call.args[0]), call.lineno,
                       call.col_offset)


def _has_os_replace(fn: FunctionInfo) -> bool:
    for call in _fn_calls(fn):
        if _tail(call.func) == "replace" and isinstance(
                call.func, ast.Attribute):
            return True
    return False


def _check_protocol_writes(fn: FunctionInfo) -> list:
    findings = []
    sites = list(_write_sites(fn))
    if not sites:
        return findings
    atomic = _has_os_replace(fn)
    for pathexpr, line, col in sites:
        if not _PROTOCOL_PATH_RE.search(pathexpr):
            continue
        if _STAGING_PATH_RE.search(pathexpr) or atomic:
            continue
        findings.append(Finding(
            "SC503", fn.path, line, col,
            f"protocol file {pathexpr} written in place; a polling "
            f"reader can observe a torn payload — stage to a tmp name "
            f"and os.replace() it into place"))
    return findings


# ----------------------------------------------------------------------


def check_project(project: Project) -> list:
    """SC501-SC503 over an already-built concurrency project."""
    findings: list[Finding] = []
    for fn in sorted(project.functions.values(), key=lambda f: (
            f.path, f.node.lineno if hasattr(f.node, "lineno") else 0)):
        findings.extend(_check_rank_divergence(fn, project))
        findings.extend(_check_unbounded_waits(fn))
        findings.extend(_check_protocol_writes(fn))
    return findings


def check_paths(paths: Iterable[str]):
    """Convenience for standalone use: build + check. Returns
    ``(findings, project)``."""
    project = build_project(paths)
    return check_project(project), project
